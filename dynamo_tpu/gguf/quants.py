"""GGML block-quantization formats: dequantize (+ test encoders).

Dequantization is bit-exact to ggml's reference dequantize_row_*
(reference: lib/llm/src/gguf/ loads these through candle, which mirrors
ggml/src/ggml-quants.c) — practically every distributed GGUF is
Q4_K/Q5_K/Q6_K, so the serving path must read them. All kernels are
vectorized numpy over the block structure.

The encoders here exist for round-trip tests and the writer; they pick
valid (not necessarily ggml-optimal) scales, while the DEQUANT layout
is what real llama.cpp files require.

Formats (values per block / bytes per block):
  Q4_0  32 / 18   d f16, 16B nibbles;             v = d*(q-8)
  Q5_0  32 / 22   d f16, 4B high bits, 16B;       v = d*(q-16)
  Q8_0  32 / 34   d f16, 32 int8;                 v = d*q
  Q4_K 256 / 144  d,dmin f16, 12B 6-bit scales, 128B;   v = d*sc*q - dmin*m
  Q5_K 256 / 176  + 32B high bits;                v = d*sc*q - dmin*m
  Q6_K 256 / 210  128B low, 64B high, 16 int8 scales;   v = d*sc*(q-32)
"""

from __future__ import annotations

import numpy as np

QK = 32       # classic block size
QK_K = 256    # k-quant super-block size

GGML_Q4_0 = 2
GGML_Q5_0 = 6
GGML_Q8_0 = 8
GGML_Q4_K = 12
GGML_Q5_K = 13
GGML_Q6_K = 14

BLOCK_SIZES = {
    GGML_Q4_0: (QK, 18),
    GGML_Q5_0: (QK, 22),
    GGML_Q8_0: (QK, 34),
    GGML_Q4_K: (QK_K, 144),
    GGML_Q5_K: (QK_K, 176),
    GGML_Q6_K: (QK_K, 210),
}


# ---------------------------------------------------------------------------
# scale packing for Q4_K/Q5_K (ggml get_scale_min_k4)
# ---------------------------------------------------------------------------


def _unpack_scales_k4(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """scales: [nb, 12] uint8 -> (sc [nb, 8], m [nb, 8]) 6-bit values."""
    q = scales.astype(np.uint8)
    sc = np.empty(q.shape[:-1] + (8,), np.uint8)
    m = np.empty_like(sc)
    for j in range(4):
        sc[..., j] = q[..., j] & 63
        m[..., j] = q[..., j + 4] & 63
    for j in range(4, 8):
        sc[..., j] = (q[..., j + 4] & 0x0F) | ((q[..., j - 4] >> 6) << 4)
        m[..., j] = (q[..., j + 4] >> 4) | ((q[..., j] >> 6) << 4)
    return sc, m


def _pack_scales_k4(sc: np.ndarray, m: np.ndarray) -> np.ndarray:
    """(sc [nb, 8], m [nb, 8]) 6-bit -> [nb, 12] uint8 (inverse of
    _unpack_scales_k4)."""
    sc = sc.astype(np.uint8)
    m = m.astype(np.uint8)
    out = np.zeros(sc.shape[:-1] + (12,), np.uint8)
    for j in range(4):
        out[..., j] = (sc[..., j] & 63) | ((sc[..., j + 4] >> 4) << 6)
        out[..., j + 4] = (m[..., j] & 63) | ((m[..., j + 4] >> 4) << 6)
        out[..., j + 8] = (sc[..., j + 4] & 0x0F) | ((m[..., j + 4] & 0x0F) << 4)
    return out


# ---------------------------------------------------------------------------
# dequantize: raw bytes -> f32 [n]
# ---------------------------------------------------------------------------


def dequant_q4_0(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK
    rec = np.frombuffer(raw, np.dtype([("d", np.float16), ("qs", np.uint8, 16)]),
                        count=nb)
    d = rec["d"].astype(np.float32)[:, None]
    lo = (rec["qs"] & 0x0F).astype(np.float32) - 8.0
    hi = (rec["qs"] >> 4).astype(np.float32) - 8.0
    return (np.concatenate([lo, hi], axis=1) * d).reshape(-1)


def dequant_q5_0(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK
    rec = np.frombuffer(
        raw,
        np.dtype([("d", np.float16), ("qh", np.uint32), ("qs", np.uint8, 16)]),
        count=nb,
    )
    d = rec["d"].astype(np.float32)[:, None]
    qh = rec["qh"][:, None].astype(np.uint32)
    ls = np.arange(16, dtype=np.uint32)[None, :]
    # ggml: xh_0 = ((qh >> l) << 4) & 0x10 ; xh_1 = (qh >> (l + 12)) & 0x10
    xh0 = ((qh >> ls) << 4) & 0x10
    xh1 = (qh >> (ls + 12)) & 0x10
    lo = ((rec["qs"] & 0x0F) | xh0.astype(np.uint8)).astype(np.float32) - 16.0
    hi = ((rec["qs"] >> 4) | xh1.astype(np.uint8)).astype(np.float32) - 16.0
    return (np.concatenate([lo, hi], axis=1) * d).reshape(-1)


def dequant_q8_0(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK
    rec = np.frombuffer(raw, np.dtype([("d", np.float16), ("q", np.int8, QK)]),
                        count=nb)
    return (rec["d"].astype(np.float32)[:, None]
            * rec["q"].astype(np.float32)).reshape(-1)


def dequant_q4_k(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK_K
    rec = np.frombuffer(
        raw,
        np.dtype([("d", np.float16), ("dmin", np.float16),
                  ("scales", np.uint8, 12), ("qs", np.uint8, 128)]),
        count=nb,
    )
    d = rec["d"].astype(np.float32)
    dmin = rec["dmin"].astype(np.float32)
    sc, mn = _unpack_scales_k4(rec["scales"])  # [nb, 8]
    out = np.empty((nb, QK_K), np.float32)
    qs = rec["qs"].reshape(nb, 4, 32)  # 4 chunks of 64 values (32 bytes)
    for c in range(4):
        lo = (qs[:, c] & 0x0F).astype(np.float32)
        hi = (qs[:, c] >> 4).astype(np.float32)
        j0, j1 = 2 * c, 2 * c + 1
        out[:, c * 64: c * 64 + 32] = (
            (d * sc[:, j0])[:, None] * lo - (dmin * mn[:, j0])[:, None]
        )
        out[:, c * 64 + 32: c * 64 + 64] = (
            (d * sc[:, j1])[:, None] * hi - (dmin * mn[:, j1])[:, None]
        )
    return out.reshape(-1)


def dequant_q5_k(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK_K
    rec = np.frombuffer(
        raw,
        np.dtype([("d", np.float16), ("dmin", np.float16),
                  ("scales", np.uint8, 12), ("qh", np.uint8, 32),
                  ("qs", np.uint8, 128)]),
        count=nb,
    )
    d = rec["d"].astype(np.float32)
    dmin = rec["dmin"].astype(np.float32)
    sc, mn = _unpack_scales_k4(rec["scales"])
    out = np.empty((nb, QK_K), np.float32)
    qs = rec["qs"].reshape(nb, 4, 32)
    qh = rec["qh"]  # [nb, 32], bit pairs per 64-chunk
    for c in range(4):
        u1 = np.uint8(1 << (2 * c))
        u2 = np.uint8(2 << (2 * c))
        hi1 = np.where(qh & u1, 16.0, 0.0).astype(np.float32)
        hi2 = np.where(qh & u2, 16.0, 0.0).astype(np.float32)
        lo = (qs[:, c] & 0x0F).astype(np.float32) + hi1
        hi = (qs[:, c] >> 4).astype(np.float32) + hi2
        j0, j1 = 2 * c, 2 * c + 1
        out[:, c * 64: c * 64 + 32] = (
            (d * sc[:, j0])[:, None] * lo - (dmin * mn[:, j0])[:, None]
        )
        out[:, c * 64 + 32: c * 64 + 64] = (
            (d * sc[:, j1])[:, None] * hi - (dmin * mn[:, j1])[:, None]
        )
    return out.reshape(-1)


def dequant_q6_k(raw: bytes, n: int) -> np.ndarray:
    nb = n // QK_K
    rec = np.frombuffer(
        raw,
        np.dtype([("ql", np.uint8, 128), ("qh", np.uint8, 64),
                  ("scales", np.int8, 16), ("d", np.float16)]),
        count=nb,
    )
    d = rec["d"].astype(np.float32)  # [nb]
    sc = rec["scales"].astype(np.float32)  # [nb, 16]
    out = np.empty((nb, QK_K), np.float32)
    for half in range(2):  # two 128-value halves
        ql = rec["ql"][:, half * 64:(half + 1) * 64]  # [nb, 64]
        qh = rec["qh"][:, half * 32:(half + 1) * 32]  # [nb, 32]
        base = half * 128
        sbase = half * 8
        l = np.arange(32)
        q1 = ((ql[:, :32] & 0x0F) | (((qh >> 0) & 3) << 4)).astype(np.int8) - 32
        q2 = ((ql[:, 32:] & 0x0F) | (((qh >> 2) & 3) << 4)).astype(np.int8) - 32
        q3 = ((ql[:, :32] >> 4) | (((qh >> 4) & 3) << 4)).astype(np.int8) - 32
        q4 = ((ql[:, 32:] >> 4) | (((qh >> 6) & 3) << 4)).astype(np.int8) - 32
        for k, q in enumerate((q1, q2, q3, q4)):
            # scale index: is = l/16 + k*2 within this half
            s_idx = sbase + (l // 16) + 2 * k  # [32]
            out[:, base + 32 * k: base + 32 * (k + 1)] = (
                d[:, None] * np.take_along_axis(
                    sc, np.broadcast_to(s_idx, (nb, 32)), axis=1
                ) * q.astype(np.float32)
            )
    return out.reshape(-1)


DEQUANT = {
    GGML_Q4_0: dequant_q4_0,
    GGML_Q5_0: dequant_q5_0,
    GGML_Q8_0: dequant_q8_0,
    GGML_Q4_K: dequant_q4_k,
    GGML_Q5_K: dequant_q5_k,
    GGML_Q6_K: dequant_q6_k,
}


# ---------------------------------------------------------------------------
# encoders (writer/tests): pick valid scales, pack per format
# ---------------------------------------------------------------------------


def quant_q4_0(x: np.ndarray) -> bytes:
    f = x.astype(np.float32).reshape(-1, QK)
    d = np.abs(f).max(axis=1) / 8.0
    ds = np.where(d == 0, 1.0, d).astype(np.float32)
    q = np.clip(np.round(f / ds[:, None]) + 8, 0, 15).astype(np.uint8)
    rec = np.zeros(len(f), np.dtype([("d", np.float16), ("qs", np.uint8, 16)]))
    rec["d"] = d.astype(np.float16)
    # re-derive q against the f16-rounded scale the decoder will use
    df = rec["d"].astype(np.float32)
    df = np.where(df == 0, 1.0, df)
    q = np.clip(np.round(f / df[:, None]) + 8, 0, 15).astype(np.uint8)
    rec["qs"] = q[:, :16] | (q[:, 16:] << 4)
    return rec.tobytes()


def quant_q5_0(x: np.ndarray) -> bytes:
    f = x.astype(np.float32).reshape(-1, QK)
    d = np.abs(f).max(axis=1) / 16.0
    rec = np.zeros(
        len(f),
        np.dtype([("d", np.float16), ("qh", np.uint32), ("qs", np.uint8, 16)]),
    )
    rec["d"] = d.astype(np.float16)
    df = rec["d"].astype(np.float32)
    df = np.where(df == 0, 1.0, df)
    q = np.clip(np.round(f / df[:, None]) + 16, 0, 31).astype(np.uint8)
    q0, q1 = q[:, :16], q[:, 16:]
    rec["qs"] = (q0 & 0x0F) | ((q1 & 0x0F) << 4)
    qh = np.zeros(len(f), np.uint32)
    for l in range(16):
        qh |= ((q0[:, l] >> 4).astype(np.uint32) & 1) << l
        qh |= ((q1[:, l] >> 4).astype(np.uint32) & 1) << (l + 16)
    rec["qh"] = qh
    return rec.tobytes()


def _kquant_scales(f: np.ndarray, nsub: int):
    """Per-sub-block (min, span-scale) for the v = d*sc*q - dmin*m shape.
    f: [nb, QK_K] -> sub [nb, nsub, QK_K//nsub]."""
    sub = f.reshape(f.shape[0], nsub, -1)
    mins = np.minimum(sub.min(axis=2), 0.0)  # m >= 0 means min <= 0
    return sub, -mins  # (sub-blocks, positive offsets)


def quant_q4_k(x: np.ndarray) -> bytes:
    f = x.astype(np.float32).reshape(-1, QK_K)
    nb = len(f)
    sub, m = _kquant_scales(f, 8)  # [nb, 8, 32], m [nb, 8]
    span = (sub.max(axis=2) + m) / 15.0  # value step per sub-block
    d = span.max(axis=1) / 63.0
    dmin = m.max(axis=1) / 63.0
    ds = np.where(d == 0, 1.0, d)
    dm = np.where(dmin == 0, 1.0, dmin)
    rec = np.zeros(
        nb,
        np.dtype([("d", np.float16), ("dmin", np.float16),
                  ("scales", np.uint8, 12), ("qs", np.uint8, 128)]),
    )
    rec["d"] = d.astype(np.float16)
    rec["dmin"] = dmin.astype(np.float16)
    df = np.where(rec["d"].astype(np.float32) == 0, 1.0, rec["d"].astype(np.float32))
    dmf = np.where(rec["dmin"].astype(np.float32) == 0, 1.0,
                   rec["dmin"].astype(np.float32))
    sc = np.clip(np.round(span / df[:, None]), 0, 63).astype(np.uint8)
    mn = np.clip(np.round(m / dmf[:, None]), 0, 63).astype(np.uint8)
    rec["scales"] = _pack_scales_k4(sc, mn)
    # re-read packed 6-bit values so q is computed against decoder scales
    sc_u, mn_u = _unpack_scales_k4(rec["scales"])
    step = df[:, None] * sc_u.astype(np.float32)
    step = np.where(step == 0, 1.0, step)
    offs = dmf[:, None] * mn_u.astype(np.float32)
    q = np.clip(
        np.round((sub + offs[:, :, None]) / step[:, :, None]), 0, 15
    ).astype(np.uint8)  # [nb, 8, 32]
    qs = np.empty((nb, 4, 32), np.uint8)
    for c in range(4):
        qs[:, c] = q[:, 2 * c] | (q[:, 2 * c + 1] << 4)
    rec["qs"] = qs.reshape(nb, 128)
    return rec.tobytes()


def quant_q5_k(x: np.ndarray) -> bytes:
    f = x.astype(np.float32).reshape(-1, QK_K)
    nb = len(f)
    sub, m = _kquant_scales(f, 8)
    span = (sub.max(axis=2) + m) / 31.0
    d = span.max(axis=1) / 63.0
    dmin = m.max(axis=1) / 63.0
    rec = np.zeros(
        nb,
        np.dtype([("d", np.float16), ("dmin", np.float16),
                  ("scales", np.uint8, 12), ("qh", np.uint8, 32),
                  ("qs", np.uint8, 128)]),
    )
    rec["d"] = d.astype(np.float16)
    rec["dmin"] = dmin.astype(np.float16)
    df = np.where(rec["d"].astype(np.float32) == 0, 1.0,
                  rec["d"].astype(np.float32))
    dmf = np.where(rec["dmin"].astype(np.float32) == 0, 1.0,
                   rec["dmin"].astype(np.float32))
    sc = np.clip(np.round(span / df[:, None]), 0, 63).astype(np.uint8)
    mn = np.clip(np.round(m / dmf[:, None]), 0, 63).astype(np.uint8)
    rec["scales"] = _pack_scales_k4(sc, mn)
    sc_u, mn_u = _unpack_scales_k4(rec["scales"])
    step = df[:, None] * sc_u.astype(np.float32)
    step = np.where(step == 0, 1.0, step)
    offs = dmf[:, None] * mn_u.astype(np.float32)
    q = np.clip(
        np.round((sub + offs[:, :, None]) / step[:, :, None]), 0, 31
    ).astype(np.uint8)  # [nb, 8, 32], 5-bit
    qs = np.empty((nb, 4, 32), np.uint8)
    qh = np.zeros((nb, 32), np.uint8)
    for c in range(4):
        lo_q, hi_q = q[:, 2 * c], q[:, 2 * c + 1]
        qs[:, c] = (lo_q & 0x0F) | ((hi_q & 0x0F) << 4)
        qh |= ((lo_q >> 4) & 1) << (2 * c)
        qh |= ((hi_q >> 4) & 1) << (2 * c + 1)
    rec["qs"] = qs.reshape(nb, 128)
    rec["qh"] = qh
    return rec.tobytes()


def quant_q6_k(x: np.ndarray) -> bytes:
    f = x.astype(np.float32).reshape(-1, QK_K)
    nb = len(f)
    sub = f.reshape(nb, 16, 16)
    s = np.abs(sub).max(axis=2) / 31.0  # [nb, 16]
    d = s.max(axis=1) / 127.0
    rec = np.zeros(
        nb,
        np.dtype([("ql", np.uint8, 128), ("qh", np.uint8, 64),
                  ("scales", np.int8, 16), ("d", np.float16)]),
    )
    rec["d"] = d.astype(np.float16)
    df = np.where(rec["d"].astype(np.float32) == 0, 1.0,
                  rec["d"].astype(np.float32))
    sc = np.clip(np.round(s / df[:, None]), -128, 127).astype(np.int8)
    rec["scales"] = sc
    step = df[:, None] * sc.astype(np.float32)
    step = np.where(step == 0, 1.0, step)
    q = np.clip(
        np.round(sub / step[:, :, None]), -32, 31
    ).astype(np.int32) + 32  # [nb, 16, 16] in [0, 63]
    qq = q.reshape(nb, QK_K)
    ql = np.zeros((nb, 128), np.uint8)
    qh = np.zeros((nb, 64), np.uint8)
    for half in range(2):
        base = half * 128
        part = qq[:, base: base + 128]  # 128 values
        q1, q2 = part[:, :32], part[:, 32:64]
        q3, q4 = part[:, 64:96], part[:, 96:]
        ql[:, half * 64: half * 64 + 32] = (q1 & 0x0F) | ((q3 & 0x0F) << 4)
        ql[:, half * 64 + 32: half * 64 + 64] = (q2 & 0x0F) | ((q4 & 0x0F) << 4)
        qh[:, half * 32: half * 32 + 32] = (
            ((q1 >> 4) & 3) | (((q2 >> 4) & 3) << 2)
            | (((q3 >> 4) & 3) << 4) | (((q4 >> 4) & 3) << 6)
        )
    rec["ql"] = ql
    rec["qh"] = qh
    return rec.tobytes()


QUANTIZE = {
    GGML_Q4_0: quant_q4_0,
    GGML_Q5_0: quant_q5_0,
    GGML_Q4_K: quant_q4_k,
    GGML_Q5_K: quant_q5_k,
    GGML_Q6_K: quant_q6_k,
}
