"""Pure-python GGUF v3 reader/writer + model bring-up from GGUF.

The native analogue of the reference's GGUF layer (reference:
lib/llm/src/gguf/{mod,content,gguf_tokenizer}.rs and
model_card/create.rs from_gguf): parse header/metadata/tensor infos,
mmap tensor data with dequantization (F32/F16/Q8_0), extract the
embedded tokenizer, derive a ModelConfig, and load weights into the
stacked-layer decoder pytree (models/llama.py layout).

Format (GGUF v3, little-endian): magic "GGUF", version u32,
tensor_count u64, kv_count u64; metadata KVs (string key + typed
value); tensor infos (name, n_dims, dims in ne-order [fastest-varying
first], ggml dtype, data offset); data section aligned to
``general.alignment`` (default 32). A tensor with ne-dims [a, b] is the
row-major array of shape (b, a) — reversed, like torch's [out, in].
"""

from __future__ import annotations

import mmap
import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, Optional

import numpy as np

MAGIC = b"GGUF"
VERSION = 3

# metadata value types
U8, I8, U16, I16, U32, I32, F32, BOOL, STRING, ARRAY, U64, I64, F64 = range(13)

_SCALAR_FMT = {
    U8: "<B", I8: "<b", U16: "<H", I16: "<h", U32: "<I", I32: "<i",
    F32: "<f", U64: "<Q", I64: "<q", F64: "<d",
}

# ggml tensor dtypes we understand (block formats: gguf/quants.py)
GGML_F32, GGML_F16 = 0, 1
GGML_Q8_0 = 8
Q8_0_BLOCK = 32  # values per Q8_0 quantization block

from dynamo_tpu.gguf import quants as _quants  # noqa: E402
from dynamo_tpu.gguf.quants import (  # noqa: E402,F401 (re-exported)
    GGML_Q4_0,
    GGML_Q4_K,
    GGML_Q5_0,
    GGML_Q5_K,
    GGML_Q6_K,
)


@dataclass(frozen=True)
class GGUFTensorInfo:
    name: str
    dims: tuple[int, ...]  # ne order (fastest-varying first)
    ggml_type: int
    offset: int  # relative to data-section start

    @property
    def shape(self) -> tuple[int, ...]:
        """numpy (row-major) shape."""
        return tuple(reversed(self.dims))

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def data_bytes(self) -> int:
        if self.ggml_type == GGML_F32:
            return self.num_elements * 4
        if self.ggml_type == GGML_F16:
            return self.num_elements * 2
        block = _quants.BLOCK_SIZES.get(self.ggml_type)
        if block is not None:
            values, nbytes = block
            if self.num_elements % values:
                raise ValueError(
                    f"{self.name}: type {self.ggml_type} needs a multiple "
                    f"of {values} elements"
                )
            return (self.num_elements // values) * nbytes
        raise ValueError(f"{self.name}: unsupported ggml type {self.ggml_type}")


class GGUFReader:
    """Parses a .gguf file; tensor data stays memory-mapped until read."""

    def __init__(self, path: str):
        self.path = path
        self._f: BinaryIO = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self._pos = 0
        if self._read(4) != MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        version = self._u32()
        if version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {version}")
        n_tensors = self._u64()
        n_kv = self._u64()
        if n_tensors > 1 << 20 or n_kv > 1 << 20:
            raise ValueError(f"{path}: implausible header counts")
        self.metadata: dict[str, Any] = {}
        for _ in range(n_kv):
            key = self._string()
            self.metadata[key] = self._value(self._u32())
        self.tensors: dict[str, GGUFTensorInfo] = {}
        for _ in range(n_tensors):
            name = self._string()
            n_dims = self._u32()
            dims = tuple(self._u64() for _ in range(n_dims))
            ggml_type = self._u32()
            offset = self._u64()
            self.tensors[name] = GGUFTensorInfo(name, dims, ggml_type, offset)
        align = int(self.metadata.get("general.alignment", 32))
        self._data_start = (self._pos + align - 1) // align * align

    # -- primitive readers -------------------------------------------------
    def _read(self, n: int) -> bytes:
        out = self._mm[self._pos : self._pos + n]
        if len(out) != n:
            raise ValueError(f"{self.path}: truncated")
        self._pos += n
        return out

    def _u32(self) -> int:
        return struct.unpack("<I", self._read(4))[0]

    def _u64(self) -> int:
        return struct.unpack("<Q", self._read(8))[0]

    def _string(self) -> str:
        n = self._u64()
        if n > 1 << 24:
            raise ValueError(f"{self.path}: implausible string length")
        return self._read(n).decode("utf-8")

    def _value(self, vtype: int) -> Any:
        if vtype in _SCALAR_FMT:
            fmt = _SCALAR_FMT[vtype]
            return struct.unpack(fmt, self._read(struct.calcsize(fmt)))[0]
        if vtype == BOOL:
            return bool(self._read(1)[0])
        if vtype == STRING:
            return self._string()
        if vtype == ARRAY:
            etype = self._u32()
            count = self._u64()
            if count > 1 << 26:
                raise ValueError(f"{self.path}: implausible array length")
            return [self._value(etype) for _ in range(count)]
        raise ValueError(f"{self.path}: unknown metadata type {vtype}")

    # -- tensor data -------------------------------------------------------
    def load(self, name: str) -> np.ndarray:
        """Read + dequantize one tensor to its numpy shape (f32/f16)."""
        info = self.tensors[name]
        start = self._data_start + info.offset
        raw = self._mm[start : start + info.data_bytes]
        if len(raw) != info.data_bytes:
            raise ValueError(f"{name}: tensor data out of file bounds")
        if info.ggml_type == GGML_F32:
            arr = np.frombuffer(raw, np.float32)
        elif info.ggml_type == GGML_F16:
            arr = np.frombuffer(raw, np.float16)
        elif info.ggml_type in _quants.DEQUANT:
            arr = _quants.DEQUANT[info.ggml_type](raw, info.num_elements)
        else:
            raise ValueError(f"{name}: unsupported ggml type {info.ggml_type}")
        return arr.reshape(info.shape)

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def __enter__(self) -> "GGUFReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Model bring-up from GGUF metadata
# ---------------------------------------------------------------------------


# Architectures the native decoder implements (models/llama.py). Anything
# else would silently load with llama semantics and produce corrupted
# logits (e.g. gemma without scale_embeddings/norm_bias_one), so unknown
# archs must fail loudly here.
SUPPORTED_GGUF_ARCHS = ("llama", "mistral", "qwen2", "gemma")


def config_from_gguf(reader: GGUFReader):
    """ModelConfig from llama.* GGUF metadata (reference:
    model_card/create.rs from_gguf). The derived kwargs are routed through
    ModelConfig.from_dict so the model_type-based semantic fixups (gemma
    embedding scaling / +1 norm bias / gelu, qwen2 qkv-bias and
    sliding-window gating) apply exactly as they do for HF-dir models."""
    from dynamo_tpu.models.config import ModelConfig

    md = reader.metadata
    arch = md.get("general.architecture", "llama")
    if arch not in SUPPORTED_GGUF_ARCHS:
        raise ValueError(
            f"{reader.path}: unsupported GGUF architecture {arch!r} "
            f"(supported: {', '.join(SUPPORTED_GGUF_ARCHS)})"
        )

    def key(suffix: str, default=None):
        return md.get(f"{arch}.{suffix}", default)

    heads = int(key("attention.head_count", 32))
    emb = int(key("embedding_length", 4096))
    vocab_size = md.get("llama.vocab_size") or md.get(f"{arch}.vocab_size")
    if vocab_size is None:
        toks = md.get("tokenizer.ggml.tokens")
        vocab_size = len(toks) if toks else 32000
    eos = md.get("tokenizer.ggml.eos_token_id", 2)
    bos = md.get("tokenizer.ggml.bos_token_id", 1)
    raw: dict = {
        "model_type": arch,
        "vocab_size": int(vocab_size),
        "hidden_size": emb,
        "intermediate_size": int(key("feed_forward_length", 11008)),
        "num_hidden_layers": int(key("block_count", 32)),
        "num_attention_heads": heads,
        "num_key_value_heads": int(key("attention.head_count_kv", heads)),
        "max_position_embeddings": int(key("context_length", 4096)),
        "rms_norm_eps": float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        "rope_theta": float(key("rope.freq_base", 10000.0)),
        "bos_token_id": int(bos),
        "eos_token_id": int(eos),
    }
    # gemma heads are wider than hidden_size/num_heads; GGUF records the
    # true per-head width as attention.key_length
    head_dim = key("attention.key_length")
    if head_dim and int(head_dim) != emb // heads:
        raw["head_dim"] = int(head_dim)
    # qwen2-family GGUFs carry QKV bias tensors; detect either way so
    # param_shapes includes bq/bk/bv and loading doesn't silently skip
    # them (from_dict's qwen2 fixup only covers the arch==qwen2 case)
    if arch == "qwen2" or "blk.0.attn_q.bias" in reader.tensors:
        raw["attention_bias"] = True
    # mistral-family GGUFs export the window; from_dict gates it off for
    # qwen2 (no use_sliding_window key in GGUF metadata = HF default False)
    window = key("attention.sliding_window")
    if window:
        raw["sliding_window"] = int(window)
    return ModelConfig.from_dict(raw)


def tokenizer_from_gguf(reader: GGUFReader):
    """Build a fast tokenizer from the GGUF-embedded vocab (reference:
    gguf/gguf_tokenizer.rs). Supports tokenizer.ggml.model == "gpt2"
    (byte-level BPE with merges) and "llama" (sentencepiece-style
    unigram with scores)."""
    from tokenizers import Tokenizer as HfTokenizer
    from tokenizers import decoders, models, normalizers, pre_tokenizers

    from dynamo_tpu.tokenizer import Tokenizer

    md = reader.metadata
    kind = md.get("tokenizer.ggml.model", "llama")
    tokens = md.get("tokenizer.ggml.tokens")
    if not tokens:
        raise ValueError("GGUF carries no embedded tokenizer")
    if kind == "gpt2":
        merges_raw = md.get("tokenizer.ggml.merges") or []
        vocab = {tok: i for i, tok in enumerate(tokens)}
        merges = [tuple(m.split(" ", 1)) for m in merges_raw]
        inner = HfTokenizer(models.BPE(vocab=vocab, merges=merges))
        inner.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        inner.decoder = decoders.ByteLevel()
    elif kind == "llama":
        scores = md.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
        unk_id = int(md.get("tokenizer.ggml.unknown_token_id", 0))
        inner = HfTokenizer(
            models.Unigram(
                list(zip(tokens, map(float, scores))),
                unk_id=unk_id,
                byte_fallback=True,
            )
        )
        # sentencepiece text normalization: without the Prepend/Replace
        # pair, plain words never match their "▁word" vocab entries and
        # everything degrades to byte fallback
        inner.normalizer = normalizers.Sequence(
            [normalizers.Prepend("▁"), normalizers.Replace(" ", "▁")]
        )
        # byte-fallback tokens (<0x0A> etc.) must decode to real bytes,
        # not literal text
        inner.decoder = decoders.Sequence(
            [
                decoders.Replace("▁", " "),
                decoders.ByteFallback(),
                decoders.Fuse(),
                # drop the space the Prepend normalizer added at encode
                decoders.Strip(" ", 1, 0),
            ]
        )
    else:
        raise ValueError(f"unsupported GGUF tokenizer model {kind!r}")
    return Tokenizer(inner)


# GGUF tensor name -> our param name (global + per-layer)
_GGUF_GLOBAL = {
    "embed": ("token_embd.weight", False),
    "final_norm": ("output_norm.weight", False),
    "lm_head": ("output.weight", True),
}
_GGUF_LAYER = {
    "attn_norm": ("blk.{i}.attn_norm.weight", False),
    "wq": ("blk.{i}.attn_q.weight", True),
    "wk": ("blk.{i}.attn_k.weight", True),
    "wv": ("blk.{i}.attn_v.weight", True),
    "wo": ("blk.{i}.attn_output.weight", True),
    "mlp_norm": ("blk.{i}.ffn_norm.weight", False),
    "w_gate": ("blk.{i}.ffn_gate.weight", True),
    "w_up": ("blk.{i}.ffn_up.weight", True),
    "w_down": ("blk.{i}.ffn_down.weight", True),
    "bq": ("blk.{i}.attn_q.bias", False),
    "bk": ("blk.{i}.attn_k.bias", False),
    "bv": ("blk.{i}.attn_v.bias", False),
}


def load_params_from_gguf(cfg, reader: GGUFReader, mesh=None, specs=None,
                          quantize=None):
    """Load GGUF weights into the stacked-layer pytree (same contract as
    models/loader.py load_params, including ``quantize="int8"``:
    GGUF-quantized tensors dequantize per layer on the host and
    re-quantize to the engine's symmetric per-channel int8)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from dynamo_tpu.models import quant
    from dynamo_tpu.models.llama import param_shapes, param_specs

    shapes = param_shapes(cfg)
    specs = specs if specs is not None else param_specs(cfg)
    params: dict[str, Any] = {}

    def quantizing(name: str) -> bool:
        return quantize == "int8" and name in quant.QUANT_AXIS

    def put(name: str, arr) -> Any:
        shape, dtype = shapes[name]
        arr = jnp.asarray(arr).astype(dtype)
        if arr.shape != shape:
            raise ValueError(f"{name}: expected {shape}, got {arr.shape}")
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, specs[name]))
        return arr

    def put_q(name: str, q_np: np.ndarray, s_np: np.ndarray) -> None:
        shape, _ = shapes[name]
        if q_np.shape != shape:
            raise ValueError(f"{name}: expected {shape}, got {q_np.shape}")
        qa, sa = jnp.asarray(q_np), jnp.asarray(s_np)
        if mesh is not None:
            wspec = specs[name]
            qa = jax.device_put(qa, NamedSharding(mesh, wspec))
            sa = jax.device_put(
                sa,
                NamedSharding(
                    mesh, quant.scale_spec(wspec, quant.QUANT_AXIS[name])
                ),
            )
        params[name] = qa
        params[name + quant.SCALE_SUFFIX] = sa

    for name, (gname, transpose) in _GGUF_GLOBAL.items():
        if name == "lm_head" and gname not in reader.tensors:
            # tied embeddings (quantized: transposed values, same
            # per-row scales — both reduce over the hidden axis)
            if quantizing(name):
                put_q(
                    name,
                    np.asarray(params["embed"]).T,
                    np.asarray(params["embed" + quant.SCALE_SUFFIX]),
                )
            else:
                params[name] = put(name, params["embed"].T)
            continue
        arr = reader.load(gname)
        arr = arr.T if transpose else arr
        if quantizing(name):
            q, s = quant.quantize_array(arr, quant.QUANT_AXIS[name])
            put_q(name, q, s)
        else:
            params[name] = put(name, arr)

    for name, (tmpl, transpose) in _GGUF_LAYER.items():
        if name not in shapes:
            continue
        if quantizing(name):
            qs, ss = [], []
            for i in range(cfg.num_hidden_layers):
                arr = reader.load(tmpl.format(i=i))
                q, s = quant.quantize_array(arr.T if transpose else arr, -2)
                qs.append(q)
                ss.append(s)
            put_q(name, np.stack(qs), np.stack(ss))
            continue
        per_layer = []
        for i in range(cfg.num_hidden_layers):
            arr = reader.load(tmpl.format(i=i))
            per_layer.append(arr.T if transpose else arr)
        params[name] = put(name, np.stack(per_layer))

    missing = set(shapes) - {k for k in params if not quant.is_quantized_name(k)}
    if missing:
        raise ValueError(f"GGUF missing params: {sorted(missing)}")
    return params


# ---------------------------------------------------------------------------
# Writer (tests + export parity)
# ---------------------------------------------------------------------------


def _write_string(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)) + b)


def _value_type(v: Any) -> int:
    if isinstance(v, bool):
        return BOOL
    if isinstance(v, int):
        return I64 if v < 0 else U64
    if isinstance(v, float):
        return F64
    if isinstance(v, str):
        return STRING
    if isinstance(v, (list, tuple)):
        return ARRAY
    raise ValueError(f"cannot encode metadata value {v!r}")


def _write_value(f: BinaryIO, v: Any, vtype: Optional[int] = None) -> None:
    vtype = _value_type(v) if vtype is None else vtype
    if vtype == BOOL:
        f.write(bytes([1 if v else 0]))
    elif vtype in _SCALAR_FMT:
        f.write(struct.pack(_SCALAR_FMT[vtype], v))
    elif vtype == STRING:
        _write_string(f, v)
    elif vtype == ARRAY:
        etype = _value_type(v[0]) if v else STRING
        f.write(struct.pack("<IQ", etype, len(v)))
        for item in v:
            _write_value(f, item, etype)
    else:
        raise ValueError(f"cannot encode metadata type {vtype}")


def write_gguf(
    path: str,
    metadata: dict[str, Any],
    tensors: dict[str, np.ndarray],
    quantize: Optional[dict[str, int]] = None,
    alignment: int = 32,
) -> None:
    """Write a GGUF v3 file. ``tensors`` are numpy arrays in row-major
    shape (dims are reversed on disk per GGUF ne-order); ``quantize``
    optionally maps tensor name -> GGML_Q8_0 to store Q8_0."""
    quantize = quantize or {}
    if alignment != 32:
        # the reader defaults to 32: a non-default alignment must be
        # declared or every tensor offset lands wrong
        metadata = {**metadata, "general.alignment": alignment}

    def encode(name: str, arr: np.ndarray) -> tuple[int, bytes]:
        gt = quantize.get(name)
        if gt == GGML_Q8_0:
            flat = arr.astype(np.float32).reshape(-1, Q8_0_BLOCK)
            d = np.abs(flat).max(axis=1) / 127.0
            d_safe = np.where(d == 0, 1.0, d)
            q = np.clip(np.round(flat / d_safe[:, None]), -127, 127).astype(np.int8)
            out = np.zeros(
                len(flat), np.dtype([("d", np.float16), ("q", np.int8, Q8_0_BLOCK)])
            )
            out["d"] = d.astype(np.float16)
            out["q"] = q
            return GGML_Q8_0, out.tobytes()
        if gt in _quants.QUANTIZE:
            values = _quants.BLOCK_SIZES[gt][0]
            if arr.size % values:
                raise ValueError(
                    f"{name}: type {gt} needs a multiple of {values} elements"
                )
            return gt, _quants.QUANTIZE[gt](arr)
        if gt is not None and gt not in (GGML_F32, GGML_F16):
            raise ValueError(f"{name}: cannot quantize to ggml type {gt}")
        if arr.dtype == np.float16:
            return GGML_F16, np.ascontiguousarray(arr).tobytes()
        return GGML_F32, np.ascontiguousarray(arr, np.float32).tobytes()

    encoded = {name: encode(name, arr) for name, arr in tensors.items()}
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<IQQ", VERSION, len(tensors), len(metadata)))
        for k, v in metadata.items():
            _write_string(f, k)
            vt = _value_type(v)
            f.write(struct.pack("<I", vt))
            _write_value(f, v, vt)
        offset = 0
        for name, arr in tensors.items():
            gt, raw = encoded[name]
            _write_string(f, name)
            dims = tuple(reversed(arr.shape))
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", gt, offset))
            offset += (len(raw) + alignment - 1) // alignment * alignment
        pos = f.tell()
        f.write(b"\x00" * ((pos + alignment - 1) // alignment * alignment - pos))
        for name, arr in tensors.items():
            _, raw = encoded[name]
            f.write(raw)
            pad = (len(raw) + alignment - 1) // alignment * alignment - len(raw)
            f.write(b"\x00" * pad)
