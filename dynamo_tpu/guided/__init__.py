"""Guided decoding: schema-compiled token masks for constrained output.

The subsystem that opens the structured-output / function-calling
workload class (docs/guided_decoding.md):

- ``fsm``       — byte-level regex -> NFA -> DFA + the json_object PDA
- ``schema``    — JSON Schema -> DFA (fragment composition)
- ``automaton`` — vocab tries, [V_pad] allow-masks, per-sequence
                  ``GuidedState``, the process-wide compile LRU
- ``tools``     — streaming tool-call parsing into OpenAI
                  ``tool_calls`` deltas

Dependency-free by design: the compiler targets the served tokenizer's
vocabulary directly, and the mask rides the existing sampling pytree
into the jitted step (engine/sampling.py) — applied before
``filter_keep_mask`` so greedy, seeded sampling, top-k/top-p, logprobs,
AND speculative verification all see the same constrained distribution.
"""

from dynamo_tpu.guided.automaton import (
    GuidedState,
    TokenAutomaton,
    automaton_for,
    normalize_spec,
)
from dynamo_tpu.guided.fsm import JsonAutomaton, compile_regex
from dynamo_tpu.guided.schema import compile_schema
from dynamo_tpu.guided.tools import (
    ToolCallStreamParser,
    forced_tool_name,
    tool_parameters_schema,
)

__all__ = [
    "GuidedState",
    "TokenAutomaton",
    "automaton_for",
    "normalize_spec",
    "JsonAutomaton",
    "compile_regex",
    "compile_schema",
    "ToolCallStreamParser",
    "forced_tool_name",
    "tool_parameters_schema",
]
