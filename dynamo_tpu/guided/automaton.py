"""Token-level automata: vocab tries, allow-masks, per-sequence state.

The layer between the byte automata (guided/fsm.py, guided/schema.py)
and the engine: a ``TokenAutomaton`` pairs one compiled byte automaton
with one tokenizer's vocabulary and answers the two questions the
decode hot path asks —

- ``mask(state)``: which token ids may be sampled next ([V_pad] bool,
  computed by walking the shared vocab byte-trie against the byte
  automaton, LRU-cached per automaton state);
- ``token_step(state, tok)``: the state after committing one token
  (every byte of the token walked through the byte automaton).

EOS semantics: special tokens never appear in the trie (they carry no
output bytes), so the mask disallows them — EXCEPT the configured eos
ids, which are allowed exactly at final automaton states. A state that
is final with no outgoing byte transitions therefore masks to eos-only:
the model is FORCED to stop when the document is complete.

Compilation is the expensive part (subset construction + trie sharing),
so ``automaton_for`` keeps a process-wide LRU keyed by
(spec, tokenizer) — one compile serves every request carrying the same
schema against the same served model — and meters compile seconds and
cache hits (docs/observability.md).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from dynamo_tpu.guided.fsm import JsonAutomaton, compile_regex
from dynamo_tpu.guided.schema import compile_schema
from dynamo_tpu.telemetry.instruments import (
    GUIDED_CACHE_EVENTS,
    GUIDED_COMPILE_SECONDS,
)

# per-automaton bound on cached per-state masks (each is V_pad bytes;
# at a 128k vocab that is ~0.5 GB at the cap — states repeat heavily in
# practice because JSON structure revisits the same grammar positions)
MASK_CACHE_STATES = 4096


class _TrieNode:
    __slots__ = ("children", "ids")

    def __init__(self) -> None:
        self.children: dict[int, _TrieNode] = {}
        self.ids: list[int] = []


def build_trie(token_bytes: list[Optional[bytes]]) -> _TrieNode:
    """Byte-trie over the vocabulary: token ids collect at the node
    their full byte sequence reaches. ``None`` entries (special tokens,
    padding ids) are excluded — they can never be emitted under a mask."""
    root = _TrieNode()
    for tid, data in enumerate(token_bytes):
        if not data:  # None or empty bytes: never maskable
            continue
        node = root
        for b in data:
            nxt = node.children.get(b)
            if nxt is None:
                nxt = node.children[b] = _TrieNode()
            node = nxt
        node.ids.append(tid)
    return root


class TokenAutomaton:
    """One compiled (byte automaton, tokenizer) pair. Stateless per
    request — per-sequence position lives in :class:`GuidedState`."""

    def __init__(
        self,
        char_automaton: Any,
        token_bytes: list[Optional[bytes]],
        trie: _TrieNode,
        vocab_pad: int,
        eos_ids: frozenset[int],
        kind: str = "",
    ):
        if len(token_bytes) > vocab_pad:
            # the shared trie holds ids up to the TOKENIZER's vocab; a
            # model whose lm_head is smaller could never emit them, and
            # mask() would index past [vocab_pad]. Fail at COMPILE time
            # (request admission) — not on the engine step path.
            raise ValueError(
                f"tokenizer vocab ({len(token_bytes)}) exceeds the "
                f"model vocab ({vocab_pad}); guided masks cannot cover "
                "tokens the model head cannot emit"
            )
        self.automaton = char_automaton
        self._tok_bytes = token_bytes
        self._trie = trie
        self.vocab_pad = vocab_pad
        self.eos_ids = frozenset(i for i in eos_ids if 0 <= i < vocab_pad)
        self.kind = kind
        self._mask_cache: OrderedDict[Any, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()

    def start_state(self) -> Any:
        return self.automaton.start()

    def is_final(self, state: Any) -> bool:
        return self.automaton.is_final(state)

    def token_step(self, state: Any, tok: int) -> Optional[Any]:
        """State after committing token ``tok``, or None when any of
        its bytes is disallowed (the token was not maskable here)."""
        if not (0 <= tok < len(self._tok_bytes)):
            return None
        data = self._tok_bytes[tok]
        if not data:
            return None
        step = self.automaton.step
        for b in data:
            state = step(state, b)
            if state is None:
                return None
        return state

    def mask(self, state: Any) -> np.ndarray:
        """[V_pad] bool allow-mask for ``state`` (cached). A token is
        allowed iff EVERY byte it contributes is a legal transition;
        eos ids are allowed iff the state is final."""
        with self._lock:
            cached = self._mask_cache.get(state)
            if cached is not None:
                self._mask_cache.move_to_end(state)
                return cached
        m = np.zeros((self.vocab_pad,), dtype=bool)
        step = self.automaton.step
        # iterative trie x automaton product walk
        stack: list[tuple[_TrieNode, Any]] = [(self._trie, state)]
        while stack:
            node, s = stack.pop()
            for b, child in node.children.items():
                ns = step(s, b)
                if ns is None:
                    continue
                if child.ids:
                    m[child.ids] = True
                if child.children:
                    stack.append((child, ns))
        if self.is_final(state):
            for e in self.eos_ids:
                m[e] = True
        m.setflags(write=False)  # cached array is shared across steps
        with self._lock:
            self._mask_cache[state] = m
            while len(self._mask_cache) > MASK_CACHE_STATES:
                self._mask_cache.popitem(last=False)
        return m


@dataclass
class GuidedState:
    """Per-sequence guided-decoding cursor (scheduler Sequence field,
    like ``drafter_state``). ``advance`` runs on the engine thread as
    tokens COMMIT (scheduler.append_token) — staged speculative drafts
    never touch it, mirroring how token state itself is unwound."""

    automaton: TokenAutomaton
    state: Any = None
    done: bool = False
    # defensive marker: a committed token the automaton rejected (can
    # only happen on unmasked paths; the mask itself prevents it)
    broken: bool = False

    def __post_init__(self) -> None:
        if self.state is None:
            self.state = self.automaton.start_state()

    def allow_mask(self) -> np.ndarray:
        if self.done:
            # document complete (or state lost): only stopping is legal
            m = np.zeros((self.automaton.vocab_pad,), dtype=bool)
            eos = list(self.automaton.eos_ids)
            if eos:
                m[eos] = True
            else:  # no configured eos: never mask everything out
                m[:] = True
            return m
        return self.automaton.mask(self.state)

    def advance(self, tok: int) -> None:
        if self.done:
            return
        if tok in self.automaton.eos_ids:
            self.done = True
            return
        ns = self.automaton.token_step(self.state, tok)
        if ns is None:
            self.done = True
            self.broken = True
            return
        self.state = ns

    # -- speculative-decoding hooks (docs/guided_decoding.md) ------------
    def filter_drafts(self, drafts: list) -> list:
        """Longest draft prefix the automaton accepts from the current
        state (eos proposals are cut — the verify step's own sampling
        emits eos through the mask when the document can end)."""
        if self.done:
            return []
        out: list[int] = []
        s = self.state
        for t in drafts:
            t = int(t)
            if t in self.automaton.eos_ids:
                break
            ns = self.automaton.token_step(s, t)
            if ns is None:
                break
            out.append(t)
            s = ns
        return out

    def masks_for_drafts(self, drafts: list) -> np.ndarray:
        """[len(drafts)+1, V_pad] per-position allow-masks for a verify
        run: position j constrains the token sampled AFTER the first j
        drafts commit. Drafts must already be filter_drafts-accepted."""
        A = self.automaton
        rows = [self.allow_mask()]
        s = self.state
        for t in drafts:
            ns = A.token_step(s, int(t))
            assert ns is not None, "masks_for_drafts on unfiltered drafts"
            s = ns
            rows.append(A.mask(s))
        return np.stack(rows)


# ---------------------------------------------------------------------------
# Process-wide compile cache
# ---------------------------------------------------------------------------

_AUTOMATON_CACHE: OrderedDict[tuple, TokenAutomaton] = OrderedDict()
_AUTOMATON_CACHE_SIZE = 64
_TOKENIZER_CACHE: dict[str, tuple[list[Optional[bytes]], _TrieNode]] = {}
_CACHE_LOCK = threading.Lock()


def normalize_spec(guided: Any) -> dict:
    """Canonical spec dict ({"kind", "json_schema"?, "regex"?}) from a
    GuidedOptions model, a plain dict, or None. Raises ValueError for
    malformed specs so callers fail the REQUEST, not the batch."""
    if guided is None:
        raise ValueError("no guided spec")
    if hasattr(guided, "model_dump"):
        guided = guided.model_dump(exclude_none=True)
    kind = guided.get("kind")
    if kind == "json_schema":
        schema = guided.get("json_schema")
        if not isinstance(schema, dict):
            raise ValueError("json_schema spec needs a schema object")
        return {"kind": kind, "json_schema": schema}
    if kind == "regex":
        rx = guided.get("regex")
        if not isinstance(rx, str) or not rx:
            raise ValueError("regex spec needs a pattern")
        return {"kind": kind, "regex": rx}
    if kind == "json_object":
        return {"kind": kind}
    raise ValueError(f"unknown guided kind {kind!r}")


def _compile_char_automaton(spec: dict) -> Any:
    kind = spec["kind"]
    if kind == "json_schema":
        return compile_schema(spec["json_schema"])
    if kind == "regex":
        return compile_regex(spec["regex"])
    return JsonAutomaton()


def token_bytes_table(
    tokenizer: Any, key: str
) -> tuple[list[Optional[bytes]], _TrieNode]:
    """(token_bytes, shared trie) for one tokenizer, cached by ``key``
    (the served model path — one table per process per model)."""
    with _CACHE_LOCK:
        hit = _TOKENIZER_CACHE.get(key)
    if hit is not None:
        return hit
    specials = set(tokenizer.special_token_ids())
    table: list[Optional[bytes]] = []
    for tid in range(tokenizer.vocab_size):
        if tid in specials:
            table.append(None)
            continue
        try:
            table.append(tokenizer.token_bytes(tid))
        except Exception:
            table.append(None)
    trie = build_trie(table)
    with _CACHE_LOCK:
        _TOKENIZER_CACHE[key] = (table, trie)
    return table, trie


def automaton_for(
    guided: Any,
    tokenizer: Any,
    tokenizer_key: str,
    vocab_pad: int,
    eos_ids,
) -> TokenAutomaton:
    """The process-wide entry point: compile (or fetch) the
    TokenAutomaton for one (spec, tokenizer) pair. Compile time and
    cache hits are metered — compiles happen at request admission, and
    the LRU makes repeat schemas (the common case for structured-output
    traffic) free."""
    spec = normalize_spec(guided)
    cache_key = (
        json.dumps(spec, sort_keys=True),
        tokenizer_key,
        vocab_pad,
        tuple(sorted(eos_ids)),
    )
    with _CACHE_LOCK:
        hit = _AUTOMATON_CACHE.get(cache_key)
        if hit is not None:
            _AUTOMATON_CACHE.move_to_end(cache_key)
    if hit is not None:
        GUIDED_CACHE_EVENTS.labels("hit").inc()
        return hit
    GUIDED_CACHE_EVENTS.labels("miss").inc()
    t0 = time.monotonic()
    char_auto = _compile_char_automaton(spec)
    table, trie = token_bytes_table(tokenizer, tokenizer_key)
    auto = TokenAutomaton(
        char_auto, table, trie, vocab_pad, frozenset(int(e) for e in eos_ids),
        kind=spec["kind"],
    )
    GUIDED_COMPILE_SECONDS.labels(spec["kind"]).observe(time.monotonic() - t0)
    with _CACHE_LOCK:
        _AUTOMATON_CACHE[cache_key] = auto
        while len(_AUTOMATON_CACHE) > _AUTOMATON_CACHE_SIZE:
            _AUTOMATON_CACHE.popitem(last=False)
    return auto
