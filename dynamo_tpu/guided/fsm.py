"""Byte-level finite automata for constrained decoding.

Dependency-free core of the guided-decoding subsystem
(docs/guided_decoding.md): a regex subset compiles through a Thompson
NFA into a DFA over BYTES, and ``json_object`` mode is a depth-bounded
JSON pushdown automaton exposing the same small protocol. Everything
token-level (vocab tries, allow-masks) lives one layer up in
``guided/automaton.py`` — this module never sees a tokenizer.

The shared protocol (duck-typed; both classes implement it):

- ``start()``    -> opaque hashable state
- ``step(s, b)`` -> next state for byte ``b`` (0..255), or ``None``
                    when the byte is not allowed from ``s``
- ``is_final(s)``-> True when generation may STOP here (the token-level
                    layer allows EOS exactly at final states)

Operating on bytes (not chars) keeps the automaton aligned with what
tokens actually contribute to the stream (``Tokenizer.token_bytes``) —
a token holding half a UTF-8 sequence advances the automaton half-way
through that character, which a char-level automaton cannot express.

Design bound: states are hashable and cheap to hash — the token layer
caches one vocab mask per distinct state it encounters.
"""

from __future__ import annotations

from typing import Optional, Union

# A byte set is a 256-bit int mask: bit b set <=> byte b allowed.
ALL_BYTES = (1 << 256) - 1
# regex `.`: any byte except \n (multi-byte chars therefore need one
# `.` per BYTE — documented subset semantics)
DOT_BYTES = ALL_BYTES & ~(1 << 0x0A)

# bounded-repetition expansion cap: {m,n} duplicates the fragment n
# times; past this the automaton (and its compile time) stops being
# "negligible per-step cost"
MAX_BOUNDED_REPEAT = 256


def byteset(*chars: str) -> int:
    m = 0
    for c in chars:
        for b in c.encode("utf-8"):
            m |= 1 << b
    return m


def byterange(lo: int, hi: int) -> int:
    """Inclusive byte range as a bitmask."""
    return ((1 << (hi - lo + 1)) - 1) << lo


DIGITS = byterange(0x30, 0x39)
WORD = DIGITS | byterange(0x41, 0x5A) | byterange(0x61, 0x7A) | byteset("_")
SPACE = byteset(" \t\n\r\f\v")


class NfaBuilder:
    """Thompson-construction NFA: fragments are (start, accept) state
    pairs; every combinator allocates fresh states so fragments compose
    freely. ``eps[s]`` are epsilon targets, ``edges[s]`` are
    (byte-mask, target) pairs."""

    def __init__(self) -> None:
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[int, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    # -- fragment combinators (each returns (start, accept)) -------------
    def lit_mask(self, mask: int) -> tuple[int, int]:
        s, a = self.state(), self.state()
        self.edges[s].append((mask, a))
        return s, a

    def empty(self) -> tuple[int, int]:
        s = self.state()
        return s, s

    def seq_bytes(self, data: bytes) -> tuple[int, int]:
        s = self.state()
        cur = s
        for b in data:
            nxt = self.state()
            self.edges[cur].append((1 << b, nxt))
            cur = nxt
        return s, cur

    def concat(self, a: tuple[int, int], b: tuple[int, int]) -> tuple[int, int]:
        self.eps[a[1]].append(b[0])
        return a[0], b[1]

    def alt(self, *frags: tuple[int, int]) -> tuple[int, int]:
        s, acc = self.state(), self.state()
        for f in frags:
            self.eps[s].append(f[0])
            self.eps[f[1]].append(acc)
        return s, acc

    def opt(self, f: tuple[int, int]) -> tuple[int, int]:
        s, acc = self.state(), self.state()
        self.eps[s] += [f[0], acc]
        self.eps[f[1]].append(acc)
        return s, acc

    def star(self, f: tuple[int, int]) -> tuple[int, int]:
        s, acc = self.state(), self.state()
        self.eps[s] += [f[0], acc]
        self.eps[f[1]] += [f[0], acc]
        return s, acc

    def plus(self, f: tuple[int, int]) -> tuple[int, int]:
        s, acc = self.state(), self.state()
        self.eps[s].append(f[0])
        self.eps[f[1]] += [f[0], acc]
        return s, acc

    def repeat(
        self, make, lo: int, hi: Optional[int]
    ) -> tuple[int, int]:
        """{lo,hi} by duplication; ``make()`` builds one fresh copy of
        the fragment (fragments cannot be reused — their states carry
        the epsilon wiring of their position). ``hi=None`` = unbounded."""
        if hi is not None and hi - lo > MAX_BOUNDED_REPEAT:
            raise ValueError(
                f"bounded repetition span {lo},{hi} exceeds "
                f"{MAX_BOUNDED_REPEAT}"
            )
        if lo > MAX_BOUNDED_REPEAT:
            raise ValueError(f"repetition floor {lo} exceeds {MAX_BOUNDED_REPEAT}")
        frag = self.empty()
        for _ in range(lo):
            frag = self.concat(frag, make())
        if hi is None:
            frag = self.concat(frag, self.star(make()))
        else:
            for _ in range(hi - lo):
                frag = self.concat(frag, self.opt(make()))
        return frag

    # -- DFA via subset construction -------------------------------------
    def _closure(self, states: frozenset[int]) -> frozenset[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def to_dfa(self, frag: tuple[int, int]) -> "Dfa":
        start_set = self._closure(frozenset([frag[0]]))
        ids: dict[frozenset[int], int] = {start_set: 0}
        table: list[dict[int, int]] = []
        finals: list[bool] = []
        work = [start_set]
        accept = frag[1]
        while work:
            cur = work.pop()
            row: dict[int, int] = {}
            finals_idx = ids[cur]
            while len(table) <= finals_idx:
                table.append({})
                finals.append(False)
            finals[finals_idx] = accept in cur
            # distinct edge masks reaching out of this subset
            edges = [e for s in cur for e in self.edges[s]]
            if edges:
                for b in range(256):
                    bit = 1 << b
                    tgt = frozenset(
                        t for mask, t in edges if mask & bit
                    )
                    if not tgt:
                        continue
                    tgt = self._closure(tgt)
                    if tgt not in ids:
                        ids[tgt] = len(ids)
                        work.append(tgt)
                    row[b] = ids[tgt]
            table[finals_idx] = row
        return Dfa(table, finals)


class Dfa:
    """Deterministic byte automaton. States are ints; every reachable
    state is live (dead transitions are simply absent)."""

    def __init__(self, table: list[dict[int, int]], finals: list[bool]):
        self.table = table
        self.finals = finals

    def start(self) -> int:
        return 0

    def step(self, state: int, byte: int) -> Optional[int]:
        return self.table[state].get(byte)

    def is_final(self, state: int) -> bool:
        return self.finals[state]

    @property
    def num_states(self) -> int:
        return len(self.table)


# ---------------------------------------------------------------------------
# Regex subset -> NFA fragment
# ---------------------------------------------------------------------------

_CLASS_ESCAPES = {
    "d": DIGITS,
    "D": ALL_BYTES & ~DIGITS,
    "w": WORD,
    "W": ALL_BYTES & ~WORD,
    "s": SPACE,
    "S": ALL_BYTES & ~SPACE,
}
_LITERAL_ESCAPES = {
    "n": "\n", "r": "\r", "t": "\t", "f": "\f", "v": "\v", "0": "\0",
}


class _RegexParser:
    """Recursive-descent parser for the supported subset: literals,
    UTF-8-encoded non-ASCII literals, ``.``, escapes (``\\d \\w \\s``
    and their negations, ``\\n \\t`` etc., escaped metachars), char
    classes ``[a-z0-9_]`` / ``[^...]``, groups ``(...)`` / ``(?:...)``,
    quantifiers ``* + ? {m} {m,} {m,n}``, and alternation ``|``.
    Fullmatch semantics: ``^``/``$`` at the pattern edges are accepted
    and ignored; anywhere else they are an error."""

    def __init__(self, pattern: str, b: NfaBuilder):
        self.p = pattern
        self.i = 0
        self.b = b

    def error(self, msg: str) -> ValueError:
        return ValueError(f"regex: {msg} at offset {self.i} in {self.p!r}")

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def eat(self) -> str:
        c = self.peek()
        self.i += 1
        return c

    def parse(self) -> tuple[int, int]:
        if self.peek() == "^":
            self.eat()
        frag = self.alternation()
        if self.i < len(self.p):
            raise self.error(f"unexpected {self.peek()!r}")
        return frag

    def alternation(self) -> tuple[int, int]:
        frags = [self.concat()]
        while self.peek() == "|":
            self.eat()
            frags.append(self.concat())
        return frags[0] if len(frags) == 1 else self.b.alt(*frags)

    def concat(self) -> tuple[int, int]:
        frag = self.b.empty()
        while self.peek() not in ("", "|", ")"):
            if self.peek() == "$" and self.i == len(self.p) - 1:
                self.eat()
                break
            frag = self.b.concat(frag, self.repeatable())
        return frag

    def repeatable(self) -> tuple[int, int]:
        start_i = self.i
        frag = self.atom()
        c = self.peek()
        if not c or c not in "*+?{":
            return frag
        end_i = self.i

        def make() -> tuple[int, int]:
            # fresh copy of the fragment: re-parse the atom's source span
            # (fragments can't be reused — states carry position wiring)
            save = self.i
            self.i = start_i
            f = self.atom()
            assert self.i == end_i
            self.i = save
            return f

        if c == "*":
            self.eat()
            return self.b.repeat(make, 0, None)
        if c == "+":
            self.eat()
            return self.b.repeat(make, 1, None)
        if c == "?":
            self.eat()
            return self.b.repeat(make, 0, 1)
        # {m} {m,} {m,n}
        j = self.p.find("}", self.i)
        if j < 0:
            raise self.error("unterminated {")
        body = self.p[self.i + 1 : j]
        self.i = j + 1
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s else None
        except ValueError:
            raise self.error(f"bad repetition {{{body}}}")
        if hi is not None and hi < lo:
            raise self.error(f"bad repetition {{{body}}}")
        return self.b.repeat(make, lo, hi)

    def atom(self) -> tuple[int, int]:
        c = self.eat()
        if c == "(":
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
            elif self.peek() == "?":
                raise self.error("only (?: non-capturing groups supported")
            frag = self.alternation()
            if self.eat() != ")":
                raise self.error("unterminated group")
            return frag
        if c == ".":
            return self.b.lit_mask(DOT_BYTES)
        if c == "[":
            return self.b.lit_mask(self.char_class())
        if c == "\\":
            return self.b.lit_mask(self.escape_mask())
        if c in "*+?{":
            raise self.error(f"dangling quantifier {c!r}")
        if c in ")|":
            raise self.error(f"unexpected {c!r}")
        if c in "^$":
            raise self.error(f"anchor {c!r} only supported at pattern edges")
        return self.b.seq_bytes(c.encode("utf-8")) if len(c.encode("utf-8")) > 1 \
            else self.b.lit_mask(1 << ord(c))

    def escape_mask(self) -> int:
        c = self.eat()
        if not c:
            raise self.error("dangling backslash")
        if c in _CLASS_ESCAPES:
            return _CLASS_ESCAPES[c]
        if c in _LITERAL_ESCAPES:
            return byteset(_LITERAL_ESCAPES[c])
        if c == "x":
            h = self.p[self.i : self.i + 2]
            if len(h) != 2:
                raise self.error("bad \\x escape")
            self.i += 2
            return 1 << int(h, 16)
        # escaped metachar / punctuation: match it literally
        return byteset(c)

    def char_class(self) -> int:
        negate = False
        if self.peek() == "^":
            self.eat()
            negate = True
        mask = 0
        first = True
        while True:
            c = self.eat()
            if not c:
                raise self.error("unterminated character class")
            if c == "]" and not first:
                break
            first = False
            if c == "\\":
                m = self.escape_mask()
                mask |= m
                continue
            lo = ord(c)
            if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                self.eat()
                hi_c = self.eat()
                if hi_c == "\\":
                    raise self.error("escape as range endpoint unsupported")
                hi = ord(hi_c)
                if hi < lo or hi > 0xFF:
                    raise self.error(f"bad range {c}-{hi_c}")
                mask |= byterange(lo, hi)
            else:
                if lo > 0x7F:
                    # a class member is ONE byte transition; OR-ing a
                    # multi-byte character's bytes in would match lone
                    # lead/continuation bytes (invalid UTF-8), never
                    # the character itself — reject instead of lying
                    raise self.error(
                        f"non-ASCII {c!r} in a character class is "
                        "unsupported (classes are byte sets); use "
                        f"alternation (...|{c}|...) instead"
                    )
                mask |= 1 << lo
        return (ALL_BYTES & ~mask) if negate else mask


def compile_regex(pattern: str) -> Dfa:
    """Compile the supported regex subset into a byte DFA with
    fullmatch semantics."""
    b = NfaBuilder()
    frag = _RegexParser(pattern, b).parse()
    return b.to_dfa(frag)


# ---------------------------------------------------------------------------
# json_object mode: a depth-bounded JSON value automaton
# ---------------------------------------------------------------------------

# Opening a new {/[ past this stack depth is disallowed: the state
# space (and the token layer's per-state mask cache) stays finite.
MAX_JSON_DEPTH = 16

_WS = frozenset(b" \t\n\r")
_ESCAPABLE = frozenset(b'"\\/bfnrt')
_HEX = frozenset(b"0123456789abcdefABCDEF")
_DIGIT = frozenset(b"0123456789")

# number states where the number read so far is already a complete
# JSON number (a terminator byte may follow)
_NUM_COMPLETE = frozenset(("N0", "NI", "NF", "ND"))


class JsonAutomaton:
    """Byte automaton accepting one JSON document (``json_object`` mode:
    the top-level value must be an object). States are
    ``(mode, aux, stack)`` tuples — ``stack`` is a tuple of ``"o"``/
    ``"a"`` frames (bounded by MAX_JSON_DEPTH), ``aux`` carries literal
    progress (``tru<e>``) — so they hash cheaply and the token layer's
    per-state mask cache works unchanged.

    String content allows any byte >= 0x20 except ``"`` and ``\\``
    (UTF-8 well-formedness inside strings is the tokenizer's problem,
    not the grammar's), plus the standard escapes and ``\\uXXXX``.
    """

    def __init__(
        self, max_depth: int = MAX_JSON_DEPTH, top_level_object: bool = True
    ):
        self.max_depth = max_depth
        self.top = top_level_object

    def start(self):
        return ("TOP", "", ()) if self.top else ("V", "", ())

    def is_final(self, state) -> bool:
        mode, _aux, stack = state
        if stack:
            return False
        return mode == "END" or (mode in _NUM_COMPLETE)

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _after_value(stack):
        """State entered once a value closes, given the remaining stack."""
        if not stack:
            return ("END", "", ())
        return (("OV", "", stack) if stack[-1] == "o" else ("AV", "", stack))

    def step(self, state, byte: int):
        mode, aux, stack = state

        # string bodies (value strings S*, key strings K*)
        if mode in ("S", "KS"):
            if byte == 0x22:  # closing quote
                return self._after_value(stack) if mode == "S" else (
                    "COLON", "", stack
                )
            if byte == 0x5C:
                return ("SE" if mode == "S" else "KSE", "", stack)
            if byte >= 0x20:
                return (mode, "", stack)
            return None
        if mode in ("SE", "KSE"):
            base = "S" if mode == "SE" else "KS"
            if byte in _ESCAPABLE:
                return (base, "", stack)
            if byte == 0x75:  # \uXXXX
                return (base + "U", "1", stack)
            return None
        if mode in ("SU", "KSU"):
            if byte not in _HEX:
                return None
            n = int(aux)
            base = "S" if mode == "SU" else "KS"
            return (base, "", stack) if n == 4 else (mode, str(n + 1), stack)

        # literals: true/false/null spelled byte by byte
        if mode == "L":
            word = aux
            if byte == ord(word[0]):
                rest = word[1:]
                if not rest:
                    return self._after_value(stack)
                return ("L", rest, stack)
            return None

        # numbers
        if mode in ("N-", "NF0", "NE1"):  # a digit is REQUIRED here
            if byte in _DIGIT:
                if mode == "N-":
                    return ("N0" if byte == 0x30 else "NI", "", stack)
                return ("NF" if mode == "NF0" else "ND", "", stack)
            return None
        if mode in _NUM_COMPLETE:
            if mode in ("NI", "NF", "ND") and byte in _DIGIT:
                return (mode, "", stack)
            if mode in ("N0", "NI") and byte == 0x2E:  # .
                return ("NF0", "", stack)
            if mode in ("N0", "NI", "NF") and byte in (0x65, 0x45):  # e E
                return ("NE", "", stack)
            # not a number byte: the number closed — the terminator byte
            # is consumed by the after-value state
            return self.step(self._after_value(stack), byte)
        if mode == "NE":
            if byte in (0x2B, 0x2D):
                return ("NE1", "", stack)
            if byte in _DIGIT:
                return ("ND", "", stack)
            return None

        # whitespace is legal in every structural mode below
        if byte in _WS:
            return state

        if mode == "TOP":  # json_object: the document must be an object
            if byte == 0x7B:  # {
                return ("O0", "", stack + ("o",))
            return None
        if mode == "V":  # any value
            if byte == 0x7B:
                if len(stack) >= self.max_depth:
                    return None
                return ("O0", "", stack + ("o",))
            if byte == 0x5B:  # [
                if len(stack) >= self.max_depth:
                    return None
                return ("A0", "", stack + ("a",))
            if byte == 0x22:
                return ("S", "", stack)
            if byte == 0x2D:
                return ("N-", "", stack)
            if byte in _DIGIT:
                return ("N0" if byte == 0x30 else "NI", "", stack)
            if byte == 0x74:  # t
                return ("L", "rue", stack)
            if byte == 0x66:  # f
                return ("L", "alse", stack)
            if byte == 0x6E:  # n
                return ("L", "ull", stack)
            return None
        if mode == "O0":  # just after '{': first key or '}'
            if byte == 0x22:
                return ("KS", "", stack)
            if byte == 0x7D:  # }
                return self._after_value(stack[:-1])
            return None
        if mode == "OK":  # after ',' in an object: a key is REQUIRED
            if byte == 0x22:
                return ("KS", "", stack)
            return None
        if mode == "COLON":
            if byte == 0x3A:  # :
                return ("V", "", stack)
            return None
        if mode == "OV":  # after a value inside an object
            if byte == 0x2C:  # ,
                return ("OK", "", stack)
            if byte == 0x7D:
                return self._after_value(stack[:-1])
            return None
        if mode == "A0":  # just after '[': first value or ']'
            if byte == 0x5D:  # ]
                return self._after_value(stack[:-1])
            return self.step(("V", "", stack), byte)
        if mode == "AV":  # after a value inside an array
            if byte == 0x2C:
                return ("V", "", stack)
            if byte == 0x5D:
                return self._after_value(stack[:-1])
            return None
        if mode == "END":  # trailing whitespace only
            return None
        raise AssertionError(f"unknown json automaton mode {mode!r}")


CharAutomaton = Union[Dfa, JsonAutomaton]
