"""JSON Schema -> byte DFA for constrained decoding.

Composes NFA fragments directly (guided/fsm.NfaBuilder) instead of
going through a regex string — the optional-property comma problem that
makes object regexes quadratic is a linear two-chain construction here
(see ``_object_frag``).

Supported subset (ValueError on anything else, at COMPILE time — a
request with an uncompilable schema fails at the frontend, not
mid-generation):

- ``type``: object / array / string / integer / number / boolean / null
- ``enum`` / ``const`` (JSON-encoded literal alternation)
- object: ``properties`` (emitted in declared order), ``required``
- array: ``items``, ``minItems`` / ``maxItems``
- string: ``minLength`` / ``maxLength`` (in characters: one escape or
  one UTF-8 sequence counts as one), ``pattern`` (the guided regex
  subset, applied to the UNESCAPED content — patterns that need to
  match ``"`` or ``\\`` inside strings are rejected)
- ``anyOf`` / ``oneOf`` (alternation — oneOf's exclusivity is NOT
  enforced), top-level ``$defs``/``definitions`` with local ``$ref``
  expanded to ``MAX_REF_DEPTH``
- numeric ``minimum``/``maximum`` etc. are NOT enforced (value bounds
  are not regular); unknown constraint keys are ignored

Whitespace: a bounded run (``WS_MAX`` bytes of space/tab/newline) is
allowed after every structural token — enough for any sane formatting,
while an UNBOUNDED ws loop would hand the model an infinite stall that
never violates the mask.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from dynamo_tpu.guided.fsm import (
    ALL_BYTES,
    Dfa,
    NfaBuilder,
    _RegexParser,
    byterange,
    byteset,
)

WS_MAX = 6
MAX_REF_DEPTH = 8

_WS_MASK = byteset(" \t\n\r")
# JSON string content: any byte >= 0x20 except '"' and '\' (multi-byte
# UTF-8 is matched structurally by _string_char_frag)
_HEX_MASK = byterange(0x30, 0x39) | byterange(0x41, 0x46) | byterange(0x61, 0x66)


class SchemaCompiler:
    def __init__(self, schema: dict, builder: Optional[NfaBuilder] = None):
        self.schema = schema
        self.b = builder or NfaBuilder()
        self.defs = {}
        for key in ("$defs", "definitions"):
            if isinstance(schema.get(key), dict):
                self.defs[key] = schema[key]

    # -- small shared fragments ------------------------------------------
    def ws(self):
        return self.b.repeat(lambda: self.b.lit_mask(_WS_MASK), 0, WS_MAX)

    def lit(self, text: str):
        return self.b.seq_bytes(text.encode("utf-8"))

    def _seq(self, *frags):
        out = frags[0]
        for f in frags[1:]:
            out = self.b.concat(out, f)
        return out

    def _string_char_frag(self):
        """One JSON string character: an unescaped single byte, a
        standard escape, a \\uXXXX escape, or one complete multi-byte
        UTF-8 sequence — each alternative counts as ONE toward
        min/maxLength."""
        b = self.b
        ascii_ok = (
            byterange(0x20, 0x21) | byterange(0x23, 0x5B) | byterange(0x5D, 0x7F)
        )
        esc = self._seq(
            b.lit_mask(byteset("\\")),
            b.alt(
                b.lit_mask(byteset('"\\/bfnrt')),
                self._seq(
                    b.lit_mask(byteset("u")),
                    b.repeat(lambda: b.lit_mask(_HEX_MASK), 4, 4),
                ),
            ),
        )
        cont = lambda: b.lit_mask(byterange(0x80, 0xBF))  # noqa: E731
        utf8_2 = self._seq(b.lit_mask(byterange(0xC2, 0xDF)), cont())
        utf8_3 = self._seq(b.lit_mask(byterange(0xE0, 0xEF)), cont(), cont())
        utf8_4 = self._seq(b.lit_mask(byterange(0xF0, 0xF4)), cont(), cont(), cont())
        return b.alt(b.lit_mask(ascii_ok), esc, utf8_2, utf8_3, utf8_4)

    # -- per-type fragments ----------------------------------------------
    # bytes a pattern-constrained string body may produce: everything a
    # JSON string can carry UNESCAPED (no quote, no backslash, no
    # control bytes). Pattern edges are intersected with this, so
    # metacharacter forms (., [^...], \S) can never admit a raw '"'
    # that would terminate the string early and break the JSON.
    _PATTERN_CONTENT = (
        ALL_BYTES & ~byteset('"', "\\") & ~byterange(0x00, 0x1F)
    )

    def _pattern_frag(self, pat: str):
        """Compile a string ``pattern`` in a scratch builder, strip
        string-illegal bytes from every edge, then graft the fragment
        into the main NFA (states renumbered). A pattern that REQUIRES
        an illegal byte (e.g. a literal '"') becomes unsatisfiable —
        rejected below rather than emitted as broken JSON."""
        sub = NfaBuilder()
        frag = _RegexParser(pat, sub).parse()
        n = len(sub.eps)
        base = [self.b.state() for _ in range(n)]
        dead_edge = False
        for i in range(n):
            self.b.eps[base[i]] = [base[t] for t in sub.eps[i]]
            edges = []
            for mask, t in sub.edges[i]:
                stripped = mask & self._PATTERN_CONTENT
                if stripped != mask and stripped == 0:
                    dead_edge = True
                if stripped:
                    edges.append((stripped, base[t]))
            self.b.edges[base[i]] = edges
        if dead_edge:
            raise ValueError(
                f"string pattern {pat!r} requires a quote/backslash/"
                "control byte, which JSON string content cannot carry "
                "unescaped (patterns apply to unescaped content)"
            )
        return base[frag[0]], base[frag[1]]

    def _string_frag(self, schema: dict):
        b = self.b
        if "pattern" in schema:
            body = self._pattern_frag(schema["pattern"])
        else:
            lo = int(schema.get("minLength", 0))
            hi = schema.get("maxLength")
            body = b.repeat(
                self._string_char_frag, lo, int(hi) if hi is not None else None
            )
        return self._seq(self.lit('"'), body, self.lit('"'))

    def _number_frag(self, integer: bool):
        b = self.b
        int_part = self._seq(
            b.opt(b.lit_mask(byteset("-"))),
            b.alt(
                b.lit_mask(byteset("0")),
                self._seq(
                    b.lit_mask(byterange(0x31, 0x39)),
                    b.repeat(lambda: b.lit_mask(byterange(0x30, 0x39)), 0, None),
                ),
            ),
        )
        if integer:
            return int_part
        digit = lambda: self.b.lit_mask(byterange(0x30, 0x39))  # noqa: E731
        frac = self._seq(self.lit("."), b.repeat(digit, 1, None))
        exp = self._seq(
            b.lit_mask(byteset("eE")),
            b.opt(b.lit_mask(byteset("+-"))),
            b.repeat(digit, 1, None),
        )
        return self._seq(int_part, b.opt(frac), b.opt(exp))

    def _array_frag(self, schema: dict, depth: int):
        b = self.b
        items = schema.get("items", {})
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        hi = int(hi) if hi is not None else None

        def item():
            return self.value_frag(items, depth)

        def rest_item():
            return self._seq(self.ws(), self.lit(","), self.ws(), item())

        if hi == 0:
            inner = b.empty()
        elif lo == 0:
            inner = b.opt(
                self._seq(
                    item(),
                    b.repeat(rest_item, 0, None if hi is None else hi - 1),
                )
            )
        else:
            inner = self._seq(
                item(),
                b.repeat(rest_item, lo - 1, None if hi is None else hi - 1),
            )
        return self._seq(
            self.lit("["), self.ws(), inner, self.ws(), self.lit("]")
        )

    def _object_frag(self, schema: dict, depth: int):
        """Properties in declared order, each present or (when not
        required) absent. Linear two-chain construction: chain N tracks
        'nothing emitted yet' (next property needs no comma), chain S
        'something emitted' (next property is comma-prefixed); skipping
        is an epsilon available only for optional properties."""
        b = self.b
        props: dict = schema.get("properties", {}) or {}
        required = set(schema.get("required", []) or [])
        unknown = required - set(props)
        if unknown:
            raise ValueError(f"required names {sorted(unknown)} not in properties")

        def prop_frag(name: str, sub: Any):
            return self._seq(
                self.lit(json.dumps(name)),
                self.ws(),
                self.lit(":"),
                self.ws(),
                self.value_frag(sub, depth),
            )

        # none[i] / some[i]: about to decide property i, with nothing /
        # something already emitted
        n = len(props)
        none_states = [b.state() for _ in range(n + 1)]
        some_states = [b.state() for _ in range(n + 1)]
        for i, (name, sub) in enumerate(props.items()):
            f1 = prop_frag(name, sub)
            b.eps[none_states[i]].append(f1[0])
            b.eps[f1[1]].append(some_states[i + 1])
            f2 = self._seq(
                self.ws(), self.lit(","), self.ws(), prop_frag(name, sub)
            )
            b.eps[some_states[i]].append(f2[0])
            b.eps[f2[1]].append(some_states[i + 1])
            if name not in required:
                b.eps[none_states[i]].append(none_states[i + 1])
                b.eps[some_states[i]].append(some_states[i + 1])
        end = b.state()
        b.eps[none_states[n]].append(end)
        b.eps[some_states[n]].append(end)
        inner = (none_states[0], end)
        return self._seq(
            self.lit("{"), self.ws(), inner, self.ws(), self.lit("}")
        )

    # -- dispatch ---------------------------------------------------------
    def _resolve_ref(self, ref: str) -> dict:
        for prefix, key in (("#/$defs/", "$defs"), ("#/definitions/", "definitions")):
            if ref.startswith(prefix):
                name = ref[len(prefix):]
                defs = self.defs.get(key, {})
                if name in defs:
                    return defs[name]
        raise ValueError(f"unsupported $ref {ref!r} (local #/$defs/* only)")

    def value_frag(self, schema: Any, depth: int = 0):
        b = self.b
        if depth > MAX_REF_DEPTH:
            raise ValueError(
                f"schema nesting/$ref expansion exceeds depth {MAX_REF_DEPTH}"
            )
        if schema is True or schema == {}:
            # unconstrained subschema: any json value — delegate to the
            # bounded generic value grammar (one level of each structure)
            raise ValueError(
                "unconstrained subschema ({}/true) is not supported; use "
                'response_format {"type": "json_object"} for free-form JSON'
            )
        if not isinstance(schema, dict):
            raise ValueError(f"schema must be an object, got {type(schema).__name__}")
        if "$ref" in schema:
            return self.value_frag(self._resolve_ref(schema["$ref"]), depth + 1)
        if "const" in schema:
            return self.lit(json.dumps(schema["const"], sort_keys=True))
        if "enum" in schema:
            if not schema["enum"]:
                raise ValueError("empty enum")
            return b.alt(
                *[
                    self.lit(json.dumps(v, sort_keys=True))
                    for v in schema["enum"]
                ]
            )
        for comb in ("anyOf", "oneOf"):
            if comb in schema:
                subs = schema[comb]
                if not subs:
                    raise ValueError(f"empty {comb}")
                return b.alt(
                    *[self.value_frag(s, depth + 1) for s in subs]
                )
        if "allOf" in schema:
            raise ValueError("allOf is not supported")
        t = schema.get("type")
        if isinstance(t, list):
            return b.alt(
                *[
                    self.value_frag({**schema, "type": one}, depth + 1)
                    for one in t
                ]
            )
        if t == "object":
            return self._object_frag(schema, depth + 1)
        if t == "array":
            return self._array_frag(schema, depth + 1)
        if t == "string":
            return self._string_frag(schema)
        if t == "integer":
            return self._number_frag(integer=True)
        if t == "number":
            return self._number_frag(integer=False)
        if t == "boolean":
            return b.alt(self.lit("true"), self.lit("false"))
        if t == "null":
            return self.lit("null")
        raise ValueError(f"unsupported schema: {json.dumps(schema)[:120]}")

    def compile(self) -> Dfa:
        # leading/trailing ws around the document itself
        frag = self._seq(self.ws(), self.value_frag(self.schema), self.ws())
        return self.b.to_dfa(frag)


def compile_schema(schema: dict) -> Dfa:
    """JSON Schema -> byte DFA with fullmatch-over-the-document
    semantics. Raises ValueError for the unsupported subset."""
    return SchemaCompiler(schema).compile()
