"""Streaming tool-call parsing: model text -> OpenAI tool_calls deltas.

Two modes, selected by the preprocessor (docs/guided_decoding.md):

- FORCED (``tool_choice`` names a function): generation was
  schema-guided to the function's parameters object, so EVERY text
  delta is an arguments delta — no detection needed, and the stream's
  finish_reason is ``tool_calls`` by construction.

- AUTO (``tools`` present, ``tool_choice`` auto/absent): the parser
  watches the start of the output for the canonical inline-JSON call
  shape ``{"name": "<fn>", "arguments": { ... }}`` (``"parameters"``
  accepted as an alias). While the prefix is still AMBIGUOUS it
  buffers (bounded); the moment it mismatches, everything buffered
  flushes as ordinary content — plain chat traffic pays one bounded
  buffer, never a lost token. On a match the function name becomes the
  tool_call header delta and the arguments object streams through
  brace-depth tracking (string-aware) until it closes.

The parser emits a flat event list per feed() so the preprocessor's
backward() can map events 1:1 onto ChatDelta chunks; one tool call per
response (index 0), matching what schema-guided generation produces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# detection buffer bound: the call header `{"name": "<fn>", "arguments":`
# comfortably fits; past this the output is treated as plain text
DETECT_BUFFER_LIMIT = 256

_WS = " \t\n\r"


@dataclass
class ToolEvent:
    kind: str  # "text" | "tool_start" | "tool_args"
    value: str = ""


@dataclass
class ToolCallStreamParser:
    forced_name: Optional[str] = None
    # internal phase: init/detect -> args -> tail | text
    _phase: str = field(default="init", repr=False)
    _buf: str = field(default="", repr=False)
    # everything consumed by a matched header (replayed as text when
    # the arguments value turns out not to be an object)
    _header: str = field(default="", repr=False)
    _name: str = field(default="", repr=False)
    _depth: int = field(default=0, repr=False)
    _in_str: bool = field(default=False, repr=False)
    _esc: bool = field(default=False, repr=False)
    _seen_obj: bool = field(default=False, repr=False)
    started: bool = False

    def __post_init__(self) -> None:
        if self.forced_name is not None:
            self._phase = "forced"

    @property
    def tool_call_detected(self) -> bool:
        return self.started

    @property
    def arguments_complete(self) -> bool:
        """True once the streamed arguments form a CLOSED object —
        the preprocessor only reports finish_reason="tool_calls" when
        this holds (a stream that stopped mid-arguments keeps its real
        finish reason; clients json.loads on "tool_calls")."""
        if not self.started:
            return False
        if self._phase == "forced":
            return self._seen_obj and self._depth == 0
        return self._phase == "tail"

    def feed(self, text: str) -> list[ToolEvent]:
        if not text:
            return []
        if self._phase == "forced":
            out = []
            if not self.started:
                self.started = True
                out.append(ToolEvent("tool_start", self.forced_name or ""))
            self._track(text)
            out.append(ToolEvent("tool_args", text))
            return out
        if self._phase == "text":
            return [ToolEvent("text", text)]
        if self._phase == "args":
            return self._feed_args(text)
        if self._phase == "tail":
            return []  # wrapper remainder after the arguments closed
        # detection phase
        self._buf += text
        return self._detect()

    def finish(self) -> list[ToolEvent]:
        """End of stream: flush whatever detection still holds. A
        header whose arguments object never opened replays as text —
        no tool_start was emitted for it."""
        if self._phase in ("init", "detect") and self._buf:
            self._phase = "text"
            buf, self._buf = self._buf, ""
            return [ToolEvent("text", buf)]
        if self._phase == "args" and not self.started:
            self._phase = "text"
            header, self._header = self._header, ""
            return [ToolEvent("text", header)] if header else []
        return []

    def _track(self, text: str) -> None:
        """String-aware brace tracking over forced-mode passthrough —
        feeds arguments_complete only (forced text IS the arguments)."""
        for ch in text:
            if self._in_str:
                if self._esc:
                    self._esc = False
                elif ch == "\\":
                    self._esc = True
                elif ch == '"':
                    self._in_str = False
                continue
            if self._depth == 0:
                if ch == "{":
                    self._seen_obj = True
                    self._depth = 1
                continue
            if ch == '"':
                self._in_str = True
            elif ch == "{":
                self._depth += 1
            elif ch == "}":
                self._depth -= 1

    # -- detection --------------------------------------------------------
    def _detect(self) -> list[ToolEvent]:
        status, name, rest = _match_call_header(self._buf)
        if status == "prefix":
            if len(self._buf) > DETECT_BUFFER_LIMIT:
                self._phase = "text"
                buf, self._buf = self._buf, ""
                return [ToolEvent("text", buf)]
            self._phase = "detect"
            return []
        if status == "no":
            self._phase = "text"
            buf, self._buf = self._buf, ""
            return [ToolEvent("text", buf)]
        # header matched — but do NOT emit the tool_start delta until
        # the arguments value proves to be an object: `"arguments":
        # null` must degrade to plain text with no phantom call header
        self._header = self._buf[: len(self._buf) - len(rest)]
        self._buf = ""
        self._phase = "args"
        self._name = name
        return self._feed_args(rest)

    def _feed_args(self, text: str) -> list[ToolEvent]:
        """Stream the arguments object, tracking brace depth with
        string/escape awareness; the byte that closes it ends the
        arguments — the wrapper's trailing ``}`` is swallowed."""
        out: list[ToolEvent] = []
        emitted: list[str] = []
        for i, ch in enumerate(text):
            if self._depth == 0:
                # waiting for the args object to open
                if ch in _WS:
                    self._header += ch
                    continue
                if ch == "{":
                    if not self.started:
                        self.started = True
                        out.append(ToolEvent("tool_start", self._name))
                    self._depth = 1
                    emitted.append(ch)
                    continue
                # not an object (null / string / number): degrade to
                # text, replaying the consumed header verbatim
                self._phase = "text"
                header, self._header = self._header, ""
                out.append(ToolEvent("text", header + text[i:]))
                return out
            emitted.append(ch)
            if self._in_str:
                if self._esc:
                    self._esc = False
                elif ch == "\\":
                    self._esc = True
                elif ch == '"':
                    self._in_str = False
                continue
            if ch == '"':
                self._in_str = True
            elif ch == "{":
                self._depth += 1
            elif ch == "}":
                self._depth -= 1
                if self._depth == 0:
                    self._phase = "tail"
                    break
        if emitted:
            out.append(ToolEvent("tool_args", "".join(emitted)))
        return out


def _match_call_header(buf: str) -> tuple[str, str, str]:
    """Match ``{ "name" : "<fn>" , "arguments"|"parameters" :`` against
    ``buf``. Returns ("match", fn, rest) / ("prefix", "", "") when buf
    is a proper prefix of a possible header / ("no", "", "")."""
    i = 0
    n = len(buf)

    def skip_ws(j: int) -> int:
        while j < n and buf[j] in _WS:
            j += 1
        return j

    def expect(j: int, lit: str) -> tuple[str, int]:
        # returns ("ok"|"prefix"|"no", next index)
        for ch in lit:
            if j >= n:
                return "prefix", j
            if buf[j] != ch:
                return "no", j
            j += 1
        return "ok", j

    i = skip_ws(i)
    if i >= n:
        return ("prefix", "", "")
    st, i = expect(i, "{")
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    i = skip_ws(i)
    st, i = expect(i, '"name"')
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    i = skip_ws(i)
    st, i = expect(i, ":")
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    i = skip_ws(i)
    st, i = expect(i, '"')
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    # function name: up to the closing quote (escapes not supported in
    # function names — OpenAI names are [a-zA-Z0-9_-]{1,64})
    j = i
    while j < n and buf[j] != '"':
        if buf[j] == "\\":
            return ("no", "", "")
        j += 1
    if j >= n:
        return ("prefix", "", "") if j - i <= 64 else ("no", "", "")
    name = buf[i:j]
    if not name:
        return ("no", "", "")
    i = j + 1
    i = skip_ws(i)
    st, i = expect(i, ",")
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    i = skip_ws(i)
    matched_key = None
    for key in ('"arguments"', '"parameters"'):
        st, k = expect(i, key)
        if st == "ok":
            matched_key = key
            i = k
            break
        if st == "prefix":
            return ("prefix", "", "")
    if matched_key is None:
        return ("no", "", "")
    i = skip_ws(i)
    st, i = expect(i, ":")
    if st != "ok":
        return (st if st == "prefix" else "no", "", "")
    return ("match", name, buf[i:])


def forced_tool_name(tool_choice, tools) -> Optional[str]:
    """The function name a request's tool_choice FORCES, or None.
    Accepts the OpenAI object form ({"type": "function", "function":
    {"name": ...}}), the bare {"name": ...} shorthand, and
    ``"required"`` when exactly one tool is listed."""
    if isinstance(tool_choice, dict):
        fn = tool_choice.get("function") or tool_choice
        name = fn.get("name") if isinstance(fn, dict) else None
        return str(name) if name else None
    if tool_choice == "required" and tools and len(tools) == 1:
        fn = (tools[0] or {}).get("function") or {}
        name = fn.get("name")
        return str(name) if name else None
    return None


def tool_parameters_schema(tools, name: str) -> Optional[dict]:
    """The ``parameters`` JSON Schema of the named tool, or None."""
    for t in tools or []:
        fn = (t or {}).get("function") or {}
        if fn.get("name") == name:
            params = fn.get("parameters")
            return params if isinstance(params, dict) else None
    return None
