"""OpenAI-compatible HTTP service (aiohttp)."""

from dynamo_tpu.http.service import HttpService, ModelManager

__all__ = ["HttpService", "ModelManager"]
