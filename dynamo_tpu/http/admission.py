"""Admission control / load shedding for the HTTP frontend.

The Tail-at-Scale failure mode this prevents: under overload an
unbounded queue converts every request into a guaranteed SLO miss (and
eventually an OOM) — the fleet "serves" everything and satisfies
nothing. Rejecting early with ``429 Retry-After`` keeps the queue
shallow enough that admitted requests still meet their deadlines, and
gives well-behaved clients an explicit pacing signal.

Signals (read per request from a live load snapshot — the engine's
``stats()`` in single-process serving; anything matching the
``LoadSnapshot`` shape elsewhere):

- scheduler queue depth (waiting + prefilling) vs ``max_queue_depth``
- KV pool pressure vs ``max_kv_usage``

Retry budget: when overloaded, a small token bucket still admits a
bounded trickle of probe requests (SRE retry-budget pattern inverted to
the server side) so recovery is observed promptly instead of waiting a
full Retry-After period after the backlog drains.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.telemetry.instruments import REQUESTS_SHED
from dynamo_tpu.utils.clock import SYSTEM


@dataclass
class LoadSnapshot:
    queue_depth: int = 0
    active_slots: int = 0
    total_slots: int = 0
    kv_usage: float = 0.0  # 0..1 fraction of the device KV pool in use


@dataclass
class AdmissionConfig:
    max_queue_depth: int = 0   # 0 = queue-depth check disabled
    max_kv_usage: float = 0.0  # 0.0 = KV-pressure check disabled
    retry_after_s: float = 1.0  # base Retry-After; scaled by backlog
    probe_rate_per_s: float = 1.0  # token-bucket refill (probes/s)
    probe_burst: float = 2.0       # token-bucket capacity

    @property
    def enabled(self) -> bool:
        return self.max_queue_depth > 0 or self.max_kv_usage > 0.0


@dataclass
class Rejection:
    reason: str        # queue_depth | kv_pressure
    retry_after_s: float
    detail: str


class TokenBucket:
    """Minimal monotonic-clock token bucket (injectable clock: pass the
    sim clock's ``monotonic`` to run admission on virtual time)."""

    def __init__(
        self, rate_per_s: float, burst: float,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.rate = max(0.0, rate_per_s)
        self.burst = max(0.0, burst)
        self._clock = clock or SYSTEM.monotonic
        self._tokens = self.burst
        self._last = self._clock()

    def take(self, n: float = 1.0) -> bool:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class AdmissionController:
    """Per-request admit/reject decision from a live load snapshot.

    ``load_fn`` returns a :class:`LoadSnapshot` (or None when load is
    momentarily unknown — unknown load ADMITS: shedding must fail open,
    an introspection hiccup is not overload).
    """

    def __init__(
        self,
        config: AdmissionConfig,
        load_fn: Callable[[], Optional[LoadSnapshot]],
        clock: Optional[Callable[[], float]] = None,
        on_shed: Optional[Callable[[], None]] = None,
    ):
        self.config = config
        self.load_fn = load_fn
        # scores each shed into the SLO rolling window (SloTracker
        # .note_shed) so the planner's attainment signal sees offered
        # load, not just the requests the fleet chose to serve
        self.on_shed = on_shed
        self._probes = TokenBucket(
            config.probe_rate_per_s, config.probe_burst, clock=clock
        )
        # degradation ladder rung 3 (planner/degradation.py): shed to
        # the probe trickle even when no load signal is available —
        # the one case where failing open is wrong, because the planner
        # has already concluded the fleet is saturated past max size
        self.force_shed = False
        self.shed_total = 0
        self.admitted_total = 0
        self.resumed_total = 0
        # host data plane (telemetry/hostplane.py): the admit/reject
        # decision reads a LIVE load snapshot — engine.stats() under a
        # busy loop is real host cost, so the controller keeps its own
        # decision-latency EMA for /debug/hostplane
        self._mono = clock or SYSTEM.monotonic
        self.checks_total = 0
        self.check_ema_s = 0.0

    def check(self, resume: bool = False) -> Optional[Rejection]:
        t0 = self._mono()
        try:
            return self._decide(resume)
        finally:
            dt = self._mono() - t0
            self.checks_total += 1
            self.check_ema_s = (
                dt if self.checks_total == 1
                else self.check_ema_s + 0.2 * (dt - self.check_ema_s)
            )

    def _decide(self, resume: bool = False) -> Optional[Rejection]:
        """None = admit; a Rejection = shed with 429 + Retry-After.

        ``resume=True`` marks a mid-stream migration re-dispatch
        (docs/robustness.md "Mid-stream migration"): the request
        already paid for admission when it first arrived and its
        tokens are mid-flight to a client, so shedding it now would
        convert a recoverable worker death into a dropped answer while
        saving almost nothing — the continuation's marginal cost is a
        re-prefill, not a whole new request. Resumes are therefore
        ALWAYS admitted (even under force_shed); ``resumed_total``
        counts migration windows (one per worker death a stream
        recovers from, not one per retry or per request)."""
        if resume:
            self.resumed_total += 1
            self.admitted_total += 1
            return None
        cfg = self.config
        # force_shed engages the controller even with no caps
        # configured (the --out auto frontend ships caps of 0)
        if not cfg.enabled and not self.force_shed:
            return None
        try:
            load = self.load_fn()
        except Exception:
            load = None
        if load is None:
            if self.force_shed and not self._probes.take():
                self.shed_total += 1
                REQUESTS_SHED.labels("degraded").inc()
                if self.on_shed is not None:
                    self.on_shed()
                return Rejection(
                    "degraded", cfg.retry_after_s,
                    "degradation ladder: shedding to the probe trickle "
                    "(fleet saturated, no local load signal)",
                )
            self.admitted_total += 1
            return None
        reason = detail = None
        over = 0.0  # backlog multiple, scales Retry-After
        if cfg.max_queue_depth > 0 and load.queue_depth >= cfg.max_queue_depth:
            reason = "queue_depth"
            over = load.queue_depth / cfg.max_queue_depth
            detail = (
                f"queue depth {load.queue_depth} >= limit "
                f"{cfg.max_queue_depth}"
            )
        elif cfg.max_kv_usage > 0.0 and load.kv_usage >= cfg.max_kv_usage:
            reason = "kv_pressure"
            over = load.kv_usage / cfg.max_kv_usage
            detail = (
                f"kv pool usage {load.kv_usage:.2f} >= limit "
                f"{cfg.max_kv_usage:.2f}"
            )
        if reason is None or self._probes.take():
            self.admitted_total += 1
            return None
        self.shed_total += 1
        REQUESTS_SHED.labels(reason).inc()
        if self.on_shed is not None:
            self.on_shed()
        # deeper backlog -> longer Retry-After (coarse drain estimate),
        # capped so clients never park for minutes on a stale hint
        retry_after = min(30.0, self.config.retry_after_s * max(1.0, over))
        return Rejection(
            reason=reason, retry_after_s=retry_after, detail=detail or reason
        )

    def stats(self) -> dict:
        return {
            "enabled": self.config.enabled,
            "max_queue_depth": self.config.max_queue_depth,
            "max_kv_usage": self.config.max_kv_usage,
            "shed_total": self.shed_total,
            "admitted_total": self.admitted_total,
            "resumed_total": self.resumed_total,
            "checks_total": self.checks_total,
            "check_ema_us": round(self.check_ema_s * 1e6, 1),
        }


def engine_load_fn(engine) -> Callable[[], Optional[LoadSnapshot]]:
    """Adapt a JaxEngine's ForwardPassMetrics into LoadSnapshots."""

    def load() -> Optional[LoadSnapshot]:
        try:
            stats = engine.stats()
        except Exception:
            return None
        return LoadSnapshot(
            queue_depth=stats.num_requests_waiting,
            active_slots=stats.request_active_slots,
            total_slots=stats.request_total_slots,
            kv_usage=stats.gpu_cache_usage_perc,
        )

    return load
