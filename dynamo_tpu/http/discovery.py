"""Discovery-driven model add/remove for the HTTP frontend.

Analogue of the reference's ModelWatcher (reference:
lib/llm/src/http/service/discovery.rs:46-383 — etcd-watched ModelEntry
keys drive ModelManager add/remove; components/http/src/main.rs — the
standalone frontend that serves whatever models workers register).

Watches ``models/{slug}/{lease_hex}`` entries: the first instance of a
model fetches its deployment card, materializes tokenizer artifacts, and
builds the full pipeline (preprocessor → backend → push router to the
instance's endpoint); the last instance disappearing (worker death revokes
the lease) removes the model from the manager.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.model_card.card import MODELS_PREFIX, ModelEntry, fetch_card
from dynamo_tpu.runtime.component import parse_dyn_path
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.telemetry.instruments import WATCH_RESTARTS
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.http.discovery")


class ModelWatcher:
    """Keeps a ModelManager in sync with the store's model registry."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager,
        router_mode: str = "round_robin",
        cache_dir: Optional[str] = None,
        admission=None,
    ):
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self.cache_dir = cache_dir
        # the frontend's AdmissionController, handed to every router it
        # builds: mid-stream migration resumes report through
        # check(resume=True), which never sheds them (they already paid
        # for admission — docs/robustness.md "Mid-stream migration")
        self.admission = admission
        # slug -> set of live entry keys; slug -> (display name, closer)
        self._instances: dict[str, set[str]] = {}
        self._models: dict[str, tuple[str, list]] = {}
        self._watch = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self) -> None:
        self._watch = await self.drt.store.watch_prefix(f"{MODELS_PREFIX}/")
        for entry in self._watch.snapshot():
            try:
                await self._on_put(entry.key, entry.value)
            except Exception:
                # one bad registry entry must not take down the frontend
                log.exception("bad model entry in snapshot: %s", entry.key)
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._watch is not None:
            await self._watch.close()
        for slug in list(self._models):
            await self._drop_model(slug)

    async def _pump(self) -> None:
        """Consume watch events; when the watch dies (store restart,
        connection blip), resubscribe on capped backoff + jitter and
        resync from the fresh snapshot — the model registry must never
        silently FREEZE (the pre-fix failure mode: one watch error and
        the frontend served a stale model table forever)."""
        assert self._watch is not None
        backoff = Backoff(base_s=0.5, cap_s=30.0)
        while not self._closed:
            try:
                async for ev in self._watch:
                    try:
                        if ev.type == "put":
                            await self._on_put(ev.entry.key, ev.entry.value)
                        else:
                            await self._on_delete(ev.entry.key)
                    except Exception:
                        log.exception(
                            "model watch event failed: %s", ev.entry.key
                        )
                # stream ENDED cleanly (store dropped it): resubscribe too
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("model watch died; resubscribing")
            if self._closed:
                return
            WATCH_RESTARTS.labels("models").inc()
            await backoff.sleep()
            try:
                self._watch = await self.drt.store.watch_prefix(
                    f"{MODELS_PREFIX}/"
                )
            except Exception:
                log.warning("model watch resubscribe failed; retrying",
                            exc_info=True)
                continue
            backoff.reset()
            try:
                await self._resync(self._watch.snapshot())
            except Exception:
                log.exception("model registry resync failed")
            log.info("model watch resubscribed")

    async def _resync(self, snapshot: list) -> None:
        """Reconcile registry state against a fresh watch snapshot:
        events lost during the outage are replayed as put/delete."""
        live_keys = {e.key for e in snapshot}
        known_keys = {k for keys in self._instances.values() for k in keys}
        for key in sorted(known_keys - live_keys):
            await self._on_delete(key)
        for entry in snapshot:
            try:
                await self._on_put(entry.key, entry.value)
            except Exception:
                log.exception("bad model entry in resync: %s", entry.key)

    # -- event handling ---------------------------------------------------
    @staticmethod
    def _slug_of(key: str) -> Optional[str]:
        parts = key.split("/")
        return parts[1] if len(parts) == 3 else None

    async def _on_put(self, key: str, value: bytes) -> None:
        slug = self._slug_of(key)
        if slug is None:
            return
        keys = self._instances.setdefault(slug, set())
        keys.add(key)
        if slug in self._models:
            return
        entry = ModelEntry.from_json(value)
        await self._add_model(slug, entry)

    async def _on_delete(self, key: str) -> None:
        slug = self._slug_of(key)
        if slug is None:
            return
        keys = self._instances.get(slug)
        if keys is None:
            return
        keys.discard(key)
        if not keys:
            self._instances.pop(slug, None)
            await self._drop_model(slug)

    # -- pipeline construction --------------------------------------------
    async def _add_model(self, slug: str, entry: ModelEntry) -> None:
        from dynamo_tpu.backend import Backend
        from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
        from dynamo_tpu.runtime.pipeline import build_pipeline
        from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
        from dynamo_tpu.tokenizer import Tokenizer

        card, local_dir = await fetch_card(
            self.drt.store, entry.name, cache_dir=self.cache_dir
        )
        ns, comp, ep = parse_dyn_path(entry.endpoint)
        component = self.drt.namespace(ns).component(comp)
        client = await component.endpoint(ep).client()

        closers: list = [client]
        mode = entry.router_mode or self.router_mode
        if mode == "kv":
            from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter

            kv_router = await KvRouter.create(component, client)
            router = KvPushRouter(kv_router, admission=self.admission)
            closers.append(kv_router)
        else:
            router = PushRouter(
                client,
                RouterMode.ROUND_ROBIN if mode == "round_robin" else RouterMode.RANDOM,
                admission=self.admission,
            )

        tokenizer = Tokenizer.from_file(local_dir)
        try:
            formatter = PromptFormatter.from_model_dir(local_dir)
        except Exception:
            formatter = None
            log.warning("model %s: no chat template in card artifacts", entry.name)
        pre = OpenAIPreprocessor(tokenizer, formatter, model_name=entry.name)
        backend = Backend(tokenizer, eos_token_ids=card.model_info.eos_token_ids)
        from dynamo_tpu.preprocessor.fanout import ChoiceFanout

        pipeline = build_pipeline(
            pre, ChoiceFanout(build_pipeline(backend, router))
        )

        if entry.model_type in ("chat", "chat_completion"):
            self.manager.add_chat_model(entry.name, pipeline)
        if entry.model_type in ("completion", "chat_completion"):
            self.manager.add_completion_model(entry.name, pipeline)
        self._models[slug] = (entry.name, closers)
        log.info("model added: %s -> %s (router=%s)", entry.name, entry.endpoint, mode)

    async def _drop_model(self, slug: str) -> None:
        name_closers = self._models.pop(slug, None)
        if name_closers is None:
            return
        name, closers = name_closers
        self.manager.remove_model(name)
        for c in closers:
            try:
                await c.close()
            except Exception:
                log.debug("closer failed for %s", name, exc_info=True)
        log.info("model removed: %s", name)
