"""OpenAI-compatible HTTP frontend.

Analogue of the reference's axum HTTP service (reference:
lib/llm/src/http/service/{openai.rs:133-560, service_v2.rs:26-151,
metrics.rs:36-311}): /v1/chat/completions, /v1/completions, /v1/models,
SSE streaming, Prometheus middleware, model add/remove at runtime via the
ModelManager (fed either programmatically or by the store-driven
ModelWatcher in discovery.py).

aiohttp replaces axum (fastapi/uvicorn are unavailable in this image and
aiohttp's raw StreamResponse is lower overhead for SSE anyway).

Observability (ISSUE 2): requests carry an ``X-Request-Id`` (client's,
or generated) echoed on every response and stamped into log records
(runtime/logging.py RequestIdFilter) and the request's root span, so
logs, traces, and client reports join on one id. Metrics moved from
prometheus_client onto the unified registry (telemetry/instruments.py
— same metric names); ``/metrics`` renders the whole process registry,
engine instruments included.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import math
import time
import uuid
from typing import Optional

from aiohttp import web

from dynamo_tpu import faults
from dynamo_tpu.protocols.aggregators import ChatAggregator, CompletionAggregator
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    ModelInfo,
    ModelList,
)
from dynamo_tpu.protocols.sse import encode_done, encode_sse
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.logging import set_log_request_id
from dynamo_tpu.telemetry import (
    REGISTRY,
    capture_profile,
    collect_debug_state,
    get_tracer,
    propagation_context,
)
from dynamo_tpu.telemetry import autopsy
from dynamo_tpu.telemetry.hostplane import (
    LEDGER,
    LoopLagMonitor,
    collect_hostplane,
    register_hostplane_provider,
    unregister_hostplane_provider,
)
from dynamo_tpu.telemetry.instruments import (
    HTTP_DURATION,
    HTTP_INFLIGHT,
    HTTP_REQUESTS,
    HTTP_TTFT,
)

log = logging.getLogger("dynamo_tpu.http")

REQUEST_ID_HEADER = "X-Request-Id"
# per-request deadline budget in milliseconds (docs/robustness.md);
# --default-deadline-ms applies when the header is absent
REQUEST_TIMEOUT_HEADER = "X-Request-Timeout-Ms"
# per-request fault rules (only honored when the active DYN_FAULTS plan
# opted in with `header`; see dynamo_tpu/faults)
FAULT_HEADER = "X-Dyn-Fault"


async def _chain_first(first, rest):
    """Re-prepend the primed first chunk to the rest of the stream."""
    if first is not None:
        yield first
    async for chunk in rest:
        yield chunk


def _request_id_from(request: web.Request) -> str:
    """The client's X-Request-Id (sanitized) or a fresh one."""
    rid = request.headers.get(REQUEST_ID_HEADER, "").strip()
    if rid:
        # bounded + printable: the id lands in logs/headers verbatim
        rid = "".join(c for c in rid[:128] if c.isprintable())
    return rid or uuid.uuid4().hex


class ModelManager:
    """Live model registry: name → chat/completion pipeline engines.

    (reference: http/service/discovery.rs ModelManager — models are added
    and removed while the service runs.)
    """

    def __init__(self) -> None:
        self.chat_engines: dict[str, AsyncEngine] = {}
        self.completion_engines: dict[str, AsyncEngine] = {}
        self._created: dict[str, int] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self.chat_engines[name] = engine
        self._created.setdefault(name, int(time.time()))

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self.completion_engines[name] = engine
        self._created.setdefault(name, int(time.time()))

    def remove_model(self, name: str) -> None:
        self.chat_engines.pop(name, None)
        self.completion_engines.pop(name, None)
        self._created.pop(name, None)

    def list_models(self) -> ModelList:
        names = sorted(set(self.chat_engines) | set(self.completion_engines))
        return ModelList(
            data=[
                ModelInfo(id=n, created=self._created.get(n, 0)) for n in names
            ]
        )


class HttpService:
    def __init__(
        self,
        model_manager: Optional[ModelManager] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        admission=None,
        default_deadline_ms: Optional[float] = None,
        lag_monitor: Optional[LoopLagMonitor] = None,
    ):
        self.models = model_manager or ModelManager()
        self.host = host
        self.port = port
        # load shedding (http/admission.py AdmissionController); None =
        # every request admitted (zero-change default)
        self.admission = admission
        # deadline budget applied when X-Request-Timeout-Ms is absent
        self.default_deadline_ms = default_deadline_ms
        # host data plane (telemetry/hostplane.py): the per-stream cost
        # ledger is process-global (downstream stages stamp it by
        # request id); the loop-lag monitor is per-service — a stall
        # dumps its own flight ring + black-box bundle (loop_stall)
        self.hostplane = LEDGER
        if lag_monitor is None:
            from dynamo_tpu.telemetry.attribution import BlackBox
            from dynamo_tpu.telemetry.recorder import FlightRecorder

            rec = FlightRecorder(capacity=256)
            lag_monitor = LoopLagMonitor(
                recorder=rec, blackbox=BlackBox(recorder=rec)
            )
        self.lag_monitor = lag_monitor
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.add_routes(
            [
                web.get("/health", self._health),
                web.get("/live", self._health),
                web.get("/metrics", self._metrics),
                web.get("/debug/state", self._debug_state),
                web.get("/debug/attribution", self._debug_attribution),
                web.get("/debug/hostplane", self._debug_hostplane),
                web.get("/debug/kvfleet", self._debug_kvfleet),
                web.get("/debug/requests", self._debug_requests),
                web.get("/debug/request/{rid}", self._debug_request),
                web.get("/debug/profile", self._debug_profile),
                web.get("/v1/models", self._models),
                web.post("/v1/chat/completions", self._chat),
                web.post("/v1/completions", self._completions),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        # handler_cancellation: client disconnect cancels the handler task so
        # in-flight generation is killed promptly (off by default in aiohttp 3.9+)
        self._runner = web.AppRunner(self.app, handler_cancellation=True)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        # host data plane: heartbeat on THIS loop + the /debug/hostplane
        # provider stanza (lag window, task census, ledger rollup)
        self.lag_monitor.start()
        register_hostplane_provider("frontend", self._hostplane_stanza)
        log.info("OpenAI HTTP service on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        unregister_hostplane_provider("frontend", self._hostplane_stanza)
        await self.lag_monitor.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    async def run_forever(self) -> None:
        await self.start()
        await asyncio.Event().wait()

    # -- handlers ---------------------------------------------------------
    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "healthy", "models": [m.id for m in self.models.list_models().data]}
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=REGISTRY.render(), content_type="text/plain")

    async def _debug_state(self, request: web.Request) -> web.Response:
        """Live introspection (docs/observability.md): a JSON snapshot
        from every registered debug provider — scheduler slots, KV pool
        occupancy, flight-recorder tail, SLO attainment, HBM — plus the
        frontend's own model table. `dynamo-tpu top` polls this."""
        state = collect_debug_state()
        state["frontend"] = {
            "models": [m.id for m in self.models.list_models().data],
            "host": self.host,
            "port": self.port,
        }
        return web.json_response(state)

    async def _debug_attribution(self, request: web.Request) -> web.Response:
        """Perf attribution (docs/observability.md "Perf attribution"):
        the decode window's loss-bucket fractions, live roofline_frac,
        per-bucket tokens-lost rates, recent per-step rows, and the
        black-box capture state — the 'where do the tokens go' endpoint."""
        from dynamo_tpu.telemetry.attribution import collect_attribution

        return web.json_response(collect_attribution())

    def _hostplane_stanza(self) -> dict:
        """The frontend's /debug/hostplane provider: loop-lag window +
        task census from the monitor, per-stream cost rollup from the
        ledger (docs/observability.md "Host data plane")."""
        out = {
            "loop": self.lag_monitor.snapshot(),
            "ledger": self.hostplane.snapshot(recent=8),
        }
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out

    async def _debug_hostplane(self, request: web.Request) -> web.Response:
        """Host data-plane introspection (docs/observability.md "Host
        data plane"): event-loop lag p50/p99/max + stall count, the
        asyncio task census, and the per-stream host-cost ledger's
        rolling window — the 'is the HOST the bottleneck' endpoint.
        The provider refreshes the loop-lag gauges, so a /metrics
        scrape next to this endpoint describes the same window."""
        return web.json_response(collect_hostplane())

    async def _debug_kvfleet(self, request: web.Request) -> web.Response:
        """Fleet KV fabric introspection (docs/kvbm.md "Fleet fabric"):
        the ``kvfleet:*`` provider stanzas only — per-fabric catalog
        view size, fleet hit/fetch/demotion counters, current pressure
        scale and host-tier residency. Empty when no fabric is attached
        in this process (e.g. a pure frontend)."""
        state = collect_debug_state()
        fleet = {
            k: v for k, v in state.items() if k.startswith("kvfleet")
        }
        return web.json_response(fleet)

    async def _debug_requests(self, request: web.Request) -> web.Response:
        """Request-autopsy exemplar index (docs/observability.md
        "Request autopsy"): retention counters + one summary line per
        retained tail exemplar, via the autopsy provider registry."""
        return web.json_response(autopsy.collect_autopsy())

    async def _debug_request(self, request: web.Request) -> web.Response:
        """One request's full autopsy record: in-flight (partial) or a
        retained exemplar. 404 = never seen here, or finished fast and
        clean and was dropped by tail retention."""
        rid = request.match_info["rid"]
        rec = autopsy.get_record(rid)
        if rec is None:
            return web.json_response(
                {"error": f"no autopsy record for {rid!r} (never seen, "
                          "or dropped at finish by tail retention)"},
                status=404,
            )
        return web.json_response(rec)

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """On-demand ``jax.profiler`` capture: ``/debug/profile?ms=N``
        records N ms and returns the Perfetto-loadable trace dir."""
        try:
            ms = int(request.query.get("ms", "1000"))
        except ValueError:
            return web.json_response(
                {"error": "ms must be an integer"}, status=400
            )
        try:
            result = await capture_profile(ms)
        except RuntimeError as exc:  # capture already running
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            log.exception("profile capture failed")
            return web.json_response(
                {"error": f"{type(exc).__name__}: {exc}"}, status=500
            )
        return web.json_response(result)

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(self.models.list_models().model_dump())

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_llm(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_llm(request, kind="completion")

    async def _handle_llm(self, request: web.Request, kind: str) -> web.StreamResponse:
        endpoint = "chat_completions" if kind == "chat" else "completions"
        rid = _request_id_from(request)
        # root span of the request's trace: every downstream span
        # (preprocess, router dispatch, worker, engine, disagg) nests
        # under this one via the Context's trace ids
        span = get_tracer().span(
            "http.request",
            attrs={"service": "frontend", "endpoint": endpoint,
                   "request_id": rid},
        )
        set_log_request_id(rid, span.trace_id or None)
        # host-cost ledger record (telemetry/hostplane.py): stamped by
        # every stage below; downstream stages (preprocessor tool
        # parser, router dispatch) stamp by request id via note_stage
        self.hostplane.begin(rid, endpoint)
        # autopsy record (telemetry/autopsy.py): the per-request join
        # layer — router dials, engine segments, and fleet events land
        # on this rid; the hostplane row is adopted at finish
        autopsy.begin_request(rid, endpoint)
        autopsy.set_trace(rid, span.trace_id or None)
        try:
            if faults.ACTIVE is not None:
                # per-request chaos: the X-Dyn-Fault header arms rules
                # scoped to this request id (no-op unless the active
                # plan opted in), then the frontend's own injection
                # point fires
                hdr = request.headers.get(FAULT_HEADER)
                if hdr:
                    try:
                        faults.ACTIVE.arm_request(hdr, rid)
                    except ValueError as exc:
                        return self._error(
                            400, f"bad {FAULT_HEADER}: {exc}", "",
                            endpoint, rid,
                        )
                await faults.ACTIVE.fire_async("http.request", request_id=rid)
            # admission control (docs/robustness.md): consult live load
            # BEFORE any expensive work; shed with 429 + Retry-After
            # instead of queueing unboundedly
            if self.admission is not None:
                t_adm = time.monotonic()
                rejection = self.admission.check()
                self.hostplane.stage(
                    rid, "admission", time.monotonic() - t_adm
                )
                if rejection is not None:
                    log.warning(
                        "shedding request %s: %s", rid, rejection.detail
                    )
                    span.set_attr("shed", rejection.reason)
                    autopsy.note_event(
                        rid, "shed", flag="shed",
                        reason=rejection.reason,
                        retry_after_s=round(rejection.retry_after_s, 3),
                    )
                    return self._error(
                        429,
                        f"server overloaded ({rejection.detail}); retry "
                        "after the indicated delay",
                        "", endpoint, rid,
                        headers={
                            "Retry-After": str(
                                max(1, int(rejection.retry_after_s))
                            )
                        },
                    )
            # per-request deadline budget: header beats the configured
            # default; invalid values are a client error, not a guess
            deadline_ms: Optional[float] = self.default_deadline_ms
            raw_timeout = request.headers.get(REQUEST_TIMEOUT_HEADER)
            if raw_timeout:
                try:
                    deadline_ms = float(raw_timeout)
                    # not (x > 0) also rejects NaN, which would mint a
                    # never-expiring local deadline but ship a 0 ms
                    # budget over the wire
                    if not (deadline_ms > 0) or math.isinf(deadline_ms):
                        raise ValueError
                except ValueError:
                    return self._error(
                        400,
                        f"{REQUEST_TIMEOUT_HEADER} must be a positive "
                        "number of milliseconds",
                        "", endpoint, rid,
                    )
            t_pre = time.monotonic()
            try:
                body = await request.json()
            except json.JSONDecodeError:
                return self._error(400, "invalid JSON body", "", endpoint, rid)
            try:
                if kind == "chat":
                    req = ChatCompletionRequest.model_validate(body)
                else:
                    req = CompletionRequest.model_validate(body)
            except Exception as exc:
                return self._error(
                    400, f"invalid request: {exc}", "", endpoint, rid
                )
            # frontend share of preprocess: body read + pydantic
            # validation (the pipeline's tokenize/template forward adds
            # its share to the same stamp via note_stage)
            self.hostplane.stage(rid, "preprocess", time.monotonic() - t_pre)

            model = req.model
            span.set_attr("model", model)
            # per-request speculative-decoding opt-in/out rides the ext
            # field straight through to PreprocessedRequest.speculative
            # (the engine resolves None to its configured default);
            # stamp explicit choices on the root span so traces show
            # which requests ran speculatively
            spec_opt = req.extension().speculative
            if spec_opt is not None:
                span.set_attr("speculative", bool(spec_opt))
            # guided decoding / tool calling (docs/guided_decoding.md):
            # stamp the constraint kind and tool surface on the root
            # span so traces show which requests ran masked
            rf = getattr(req, "response_format", None)
            if isinstance(rf, dict) and rf.get("type"):
                span.set_attr("response_format", str(rf["type"]))
            tools = getattr(req, "tools", None)
            if tools:
                span.set_attr("tools", len(tools))
            engines = (
                self.models.chat_engines if kind == "chat" else self.models.completion_engines
            )
            engine = engines.get(model)
            if engine is None:
                return self._error(
                    404, f"model {model!r} not found", model, endpoint, rid
                )

            ctx = Context(id=rid)
            if deadline_ms is not None:
                # the budget starts at admission; it propagates with the
                # context (and over the worker wire) so every stage —
                # queue wait, prefill dispatch, decode — can cancel the
                # request instead of burning steps past its deadline
                ctx.set_deadline_ms(deadline_ms)
                span.set_attr("deadline_ms", deadline_ms)
                autopsy.note_event(rid, "deadline_budget", ms=deadline_ms)
            # the head's decision governs the WHOLE trace: a sampled-out
            # root propagates {"sampled": False} so downstream processes
            # don't start orphan root traces of their own
            ctx.set_trace(propagation_context(span) or {})
            start = time.monotonic()
            HTTP_INFLIGHT.labels(model).inc()
            try:
                stream = engine.generate(req, ctx)
                # dispatch stamp: building the generator is the local
                # handoff cost (routed pipelines add the instance-pick
                # share via note_stage inside the router)
                self.hostplane.stage(
                    rid, "dispatch", time.monotonic() - start
                )
                if req.stream:
                    # prime the FIRST chunk before committing to an SSE
                    # response: generation pipelines run lazily, so
                    # request-shaped failures (uncompilable guided
                    # schemas, bad token ids) surface on the first
                    # __anext__ — they must return the 400 below, not a
                    # 200 stream carrying an error event
                    aiter = stream.__aiter__()
                    t_prime = time.monotonic()
                    try:
                        first = await aiter.__anext__()
                    except StopAsyncIteration:
                        first = None
                    # first-chunk priming = the engine-side share of
                    # TTFB (the frontend TTFB-vs-engine-TTFT split)
                    self.hostplane.stage(
                        rid, "prime", time.monotonic() - t_prime
                    )
                    return await self._stream_sse(
                        request, _chain_first(first, aiter), ctx, model,
                        endpoint, start, rid,
                    )
                # aggregate to a single response object
                agg = ChatAggregator() if kind == "chat" else CompletionAggregator()
                async for chunk in stream:
                    agg.push(chunk)
                HTTP_REQUESTS.labels(model, endpoint, "200").inc()
                HTTP_DURATION.labels(model, endpoint).observe(
                    time.monotonic() - start
                )
                autopsy.finish_request(
                    rid, "200", host=self.hostplane.finish(rid, "200")
                )
                return web.json_response(
                    agg.response().model_dump(exclude_none=True),
                    headers={REQUEST_ID_HEADER: rid},
                )
            except asyncio.CancelledError:
                ctx.kill()
                span.set_attr("status", "499")
                raise
            except ValueError as exc:
                # request-shaped failures surfacing past pydantic —
                # uncompilable guided schemas, bad token ids — are the
                # CLIENT's error, not an engine failure. Logged with the
                # traceback anyway: if an internal defect ever surfaces
                # as ValueError, the 400 must not hide it from operators
                log.warning(
                    "rejecting request %s as invalid: %s", rid, exc,
                    exc_info=True,
                )
                # covers guided-rejects (uncompilable schemas): flagged
                # so the autopsy exemplar survives tail retention
                autopsy.note_event(
                    rid, "request_rejected", flag="rejected",
                    error=str(exc)[:200],
                )
                return self._error(
                    400, f"invalid request: {exc}", model, endpoint, rid
                )
            except Exception as exc:
                log.exception("engine failure for %s", model)
                return self._error(
                    500, f"engine error: {exc}", model, endpoint, rid
                )
            finally:
                HTTP_INFLIGHT.labels(model).dec()
        finally:
            # error/shed/4xx paths return before their stage reached a
            # finish() call — close the ledger record so the active
            # table can't grow (finish is idempotent: happy paths
            # already popped theirs; the autopsy close mirrors it)
            autopsy.finish_request(
                rid, "error", host=self.hostplane.finish(rid, "error")
            )
            span.end()
            set_log_request_id(None)

    async def _stream_sse(
        self,
        request: web.Request,
        stream,
        ctx: Context,
        model: str,
        endpoint: str,
        start: float,
        rid: str = "",
    ) -> web.StreamResponse:
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        }
        if rid:
            headers[REQUEST_ID_HEADER] = rid
        resp = web.StreamResponse(status=200, headers=headers)
        await resp.prepare(request)
        self.hostplane.mark_stream(rid)
        first = True
        status = "200"
        try:
            async for chunk in stream:
                if first:
                    HTTP_TTFT.labels(model).observe(time.monotonic() - start)
                    first = False
                # per-chunk cost feeds the ledger's EMA (serialize vs
                # write split: a long write is transport backpressure)
                t0 = time.monotonic()
                payload = chunk.model_dump(exclude_none=True) if hasattr(chunk, "model_dump") else chunk
                data = encode_sse(payload).encode()
                t1 = time.monotonic()
                await resp.write(data)
                self.hostplane.chunk(
                    rid, t1 - t0, time.monotonic() - t1, len(data)
                )
            await resp.write(encode_done().encode())
        except asyncio.CancelledError:
            # client went away: kill the in-flight generation, let the
            # cancellation propagate (aiohttp expects it); finally still
            # records the 499
            ctx.kill()
            status = "499"
            raise
        except ConnectionResetError:
            ctx.kill()
            status = "499"
        except Exception as exc:
            log.exception("stream failure for %s", model)
            await resp.write(
                encode_sse({"error": str(exc)}, event="error").encode()
            )
            status = "500"
        finally:
            HTTP_REQUESTS.labels(model, endpoint, status).inc()
            HTTP_DURATION.labels(model, endpoint).observe(time.monotonic() - start)
            autopsy.finish_request(
                rid, status, host=self.hostplane.finish(rid, status)
            )
        with contextlib.suppress(ConnectionResetError):
            await resp.write_eof()
        return resp

    def _error(
        self, status: int, message: str, model: str, endpoint: str,
        rid: str = "", headers: Optional[dict] = None,
    ) -> web.Response:
        HTTP_REQUESTS.labels(model, endpoint, str(status)).inc()
        all_headers = dict(headers or {})
        if rid:
            all_headers[REQUEST_ID_HEADER] = rid
        err_type = (
            "overloaded_error" if status == 429 else "invalid_request_error"
        )
        return web.json_response(
            {"error": {"message": message, "type": err_type}},
            status=status,
            headers=all_headers or None,
        )


