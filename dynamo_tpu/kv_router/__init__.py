"""KV-aware routing: radix indexer + cost-function scheduler + event plane.

TPU-native analogue of the reference's KV router (reference:
lib/llm/src/kv_router/{indexer.rs,scheduler.rs,publisher.rs,protocols.rs,
recorder.rs}). Workers publish KV cache events (block stored/removed) and
load metrics; the router maintains a global radix tree over block hashes
with per-worker ownership, scores workers as

    logit = 2·overlap_blocks − gpu_cache_usage − normalized_waiting

and dispatches to the argmax (random tie-break).
"""

from dynamo_tpu.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvCacheEvent, RouterEvent
from dynamo_tpu.kv_router.scheduler import KvScheduler, default_selector

__all__ = [
    "ForwardPassMetrics",
    "KvCacheEvent",
    "KvIndexer",
    "KvScheduler",
    "OverlapScores",
    "RadixTree",
    "RouterEvent",
    "default_selector",
]
