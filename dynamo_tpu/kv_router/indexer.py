"""Global prefix index over KV block hashes with per-worker ownership.

Analogue of the reference's radix indexer (reference:
lib/llm/src/kv_router/indexer.rs:86-876 — RadixTree, apply_event,
find_matches, KvIndexer). Because dynamo-tpu's block hashes are *chained*
sequence hashes (each hash commits to its whole prefix, tokens.py), the
radix trie collapses to a flat hash→owners map: a chain walk IS a trie
descent, with O(1) lookups and no explicit parent/child bookkeeping.

``find_matches`` returns, per worker, the longest consecutive block prefix
of the request present on that worker — the quantity the cost function
feeds on (a non-prefix match cannot be reused by a paged decode).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Iterable, Optional

from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent
from dynamo_tpu.tokens import hash_sequence

log = logging.getLogger("dynamo_tpu.kv_router.indexer")


@dataclass
class OverlapScores:
    """worker_id -> matched consecutive prefix blocks
    (reference: indexer.rs OverlapScores)."""

    scores: dict[int, int] = field(default_factory=dict)
    total_blocks: int = 0

    def best(self) -> tuple[Optional[int], int]:
        if not self.scores:
            return None, 0
        wid = max(self.scores, key=lambda w: self.scores[w])
        return wid, self.scores[wid]


class RadixTree:
    """hash → owning workers, plus per-worker hash sets for cleanup."""

    def __init__(self) -> None:
        self._owners: dict[int, set[int]] = defaultdict(set)
        self._by_worker: dict[int, set[int]] = defaultdict(set)
        self.applied_events = 0

    def apply_event(self, event: RouterEvent) -> None:
        wid = event.worker_id
        ev = event.event
        if ev.op == "stored":
            for h in ev.block_hashes:
                self._owners[h].add(wid)
                self._by_worker[wid].add(h)
        elif ev.op == "removed":
            for h in ev.block_hashes:
                owners = self._owners.get(h)
                if owners:
                    owners.discard(wid)
                    if not owners:
                        self._owners.pop(h, None)
                self._by_worker[wid].discard(h)
        elif ev.op == "cleared":
            self.remove_worker(wid)
        self.applied_events += 1

    def remove_worker(self, worker_id: int) -> None:
        for h in self._by_worker.pop(worker_id, set()):
            owners = self._owners.get(h)
            if owners:
                owners.discard(worker_id)
                if not owners:
                    self._owners.pop(h, None)

    def find_matches(self, seq_hashes: Iterable[int]) -> OverlapScores:
        hashes = list(seq_hashes)
        scores: dict[int, int] = {}
        active: Optional[set[int]] = None
        for i, h in enumerate(hashes):
            owners = self._owners.get(h)
            if not owners:
                break
            active = set(owners) if active is None else active & owners
            if not active:
                break
            for w in active:
                scores[w] = i + 1
        return OverlapScores(scores=scores, total_blocks=len(hashes))

    @property
    def num_blocks(self) -> int:
        return len(self._owners)

    def workers(self) -> set[int]:
        return set(self._by_worker)


class NativeRadixTree:
    """Same contract as :class:`RadixTree`, backed by the C++ index
    (native/src/radix.cc). The per-worker membership set stays in Python
    only for ``workers()`` introspection; match/apply hot paths run native."""

    def __init__(self) -> None:
        from dynamo_tpu.native import NativeRadix

        self._native = NativeRadix()
        self._worker_ids: set[int] = set()

    def apply_event(self, event: RouterEvent) -> None:
        ev = event.event
        if ev.op == "stored":
            self._worker_ids.add(event.worker_id)
        elif ev.op == "cleared":
            self._worker_ids.discard(event.worker_id)
        self._native.apply(event.worker_id, ev.op, ev.block_hashes)

    def remove_worker(self, worker_id: int) -> None:
        self._worker_ids.discard(worker_id)
        self._native.remove_worker(worker_id)

    def find_matches(self, seq_hashes: Iterable[int]) -> OverlapScores:
        hashes = list(seq_hashes)
        return OverlapScores(
            scores=self._native.find_matches(hashes), total_blocks=len(hashes)
        )

    @property
    def num_blocks(self) -> int:
        return self._native.num_blocks

    @property
    def applied_events(self) -> int:
        return self._native.applied_events

    def workers(self) -> set[int]:
        return set(self._worker_ids)


def make_radix_tree() -> "RadixTree | NativeRadixTree":
    """Native tree when the C++ tier is built, Python otherwise."""
    from dynamo_tpu import native

    if native.is_available():
        return NativeRadixTree()
    return RadixTree()


class KvIndexer:
    """Event-driven indexer: subscribes to worker KV events and answers
    overlap queries (reference: indexer.rs KvIndexer)."""

    def __init__(self, block_size: int = 16):
        self.tree = make_radix_tree()
        self.block_size = block_size
        self._task: Optional[asyncio.Task] = None

    # -- queries ----------------------------------------------------------
    def find_matches(self, seq_hashes: list[int]) -> OverlapScores:
        return self.tree.find_matches(seq_hashes)

    def find_matches_for_request(self, token_ids: list[int]) -> OverlapScores:
        _, seq_hashes = hash_sequence(token_ids, self.block_size)
        return self.tree.find_matches(seq_hashes)

    # -- event intake -----------------------------------------------------
    def apply(self, event: RouterEvent) -> None:
        # adopt the workers' block size: a mismatch would silently zero
        # every overlap score (hashes computed over different block sizes)
        ev_bs = event.event.token_block_size
        if ev_bs and ev_bs != self.block_size:
            log.warning(
                "adopting worker token_block_size=%d (was %d)", ev_bs, self.block_size
            )
            self.block_size = ev_bs
        self.tree.apply_event(event)

    def start_consuming(self, subscriber) -> None:
        """Consume RouterEvents from an async iterator of (subject, dict)."""

        async def pump() -> None:
            try:
                async for _subject, payload in subscriber:
                    try:
                        self.apply(RouterEvent.model_validate(payload))
                    except Exception:
                        log.exception("bad router event")
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("kv event subscription died; index is frozen")

        self._task = asyncio.get_running_loop().create_task(pump())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
