"""Global prefix index over KV block hashes with per-worker ownership.

Analogue of the reference's radix indexer (reference:
lib/llm/src/kv_router/indexer.rs:86-876 — RadixTree, apply_event,
find_matches, KvIndexer). Because dynamo-tpu's block hashes are *chained*
sequence hashes (each hash commits to its whole prefix, tokens.py), the
radix trie collapses to a flat hash→owners map: a chain walk IS a trie
descent, with O(1) lookups and no explicit parent/child bookkeeping.

``find_matches`` returns, per worker, the longest consecutive block prefix
of the request present on that worker — the quantity the cost function
feeds on (a non-prefix match cannot be reused by a paged decode).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Iterable, Optional

from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent
from dynamo_tpu.tokens import hash_sequence

log = logging.getLogger("dynamo_tpu.kv_router.indexer")


@dataclass
class OverlapScores:
    """worker_id -> matched consecutive prefix blocks
    (reference: indexer.rs OverlapScores)."""

    scores: dict[int, int] = field(default_factory=dict)
    total_blocks: int = 0

    def best(self) -> tuple[Optional[int], int]:
        if not self.scores:
            return None, 0
        wid = max(self.scores, key=lambda w: self.scores[w])
        return wid, self.scores[wid]


class RadixTree:
    """hash → owning workers, plus per-worker hash sets for cleanup."""

    def __init__(self) -> None:
        self._owners: dict[int, set[int]] = defaultdict(set)
        self._by_worker: dict[int, set[int]] = defaultdict(set)
        self.applied_events = 0

    def apply_event(self, event: RouterEvent) -> None:
        wid = event.worker_id
        ev = event.event
        if ev.op == "stored":
            for h in ev.block_hashes:
                self._owners[h].add(wid)
                self._by_worker[wid].add(h)
        elif ev.op == "removed":
            for h in ev.block_hashes:
                owners = self._owners.get(h)
                if owners:
                    owners.discard(wid)
                    if not owners:
                        self._owners.pop(h, None)
                self._by_worker[wid].discard(h)
        elif ev.op == "cleared":
            self.remove_worker(wid)
        self.applied_events += 1

    def remove_worker(self, worker_id: int) -> None:
        for h in self._by_worker.pop(worker_id, set()):
            owners = self._owners.get(h)
            if owners:
                owners.discard(worker_id)
                if not owners:
                    self._owners.pop(h, None)

    def find_matches(self, seq_hashes: Iterable[int]) -> OverlapScores:
        hashes = list(seq_hashes)
        scores: dict[int, int] = {}
        active: Optional[set[int]] = None
        for i, h in enumerate(hashes):
            owners = self._owners.get(h)
            if not owners:
                break
            active = set(owners) if active is None else active & owners
            if not active:
                break
            for w in active:
                scores[w] = i + 1
        return OverlapScores(scores=scores, total_blocks=len(hashes))

    @property
    def num_blocks(self) -> int:
        return len(self._owners)

    def workers(self) -> set[int]:
        return set(self._by_worker)


class NativeRadixTree:
    """Same contract as :class:`RadixTree`, backed by the C++ index
    (native/src/radix.cc). The per-worker membership set stays in Python
    only for ``workers()`` introspection; match/apply hot paths run native."""

    def __init__(self) -> None:
        from dynamo_tpu.native import NativeRadix

        self._native = NativeRadix()
        self._worker_ids: set[int] = set()

    def apply_event(self, event: RouterEvent) -> None:
        ev = event.event
        if ev.op == "stored":
            self._worker_ids.add(event.worker_id)
        elif ev.op == "cleared":
            self._worker_ids.discard(event.worker_id)
        self._native.apply(event.worker_id, ev.op, ev.block_hashes)

    def remove_worker(self, worker_id: int) -> None:
        self._worker_ids.discard(worker_id)
        self._native.remove_worker(worker_id)

    def find_matches(self, seq_hashes: Iterable[int]) -> OverlapScores:
        hashes = list(seq_hashes)
        return OverlapScores(
            scores=self._native.find_matches(hashes), total_blocks=len(hashes)
        )

    @property
    def num_blocks(self) -> int:
        return self._native.num_blocks

    @property
    def applied_events(self) -> int:
        return self._native.applied_events

    def workers(self) -> set[int]:
        return set(self._worker_ids)


def make_radix_tree() -> "RadixTree | NativeRadixTree":
    """Native tree when the C++ tier is built, Python otherwise."""
    from dynamo_tpu import native

    if native.is_available():
        return NativeRadixTree()
    return RadixTree()


class KvIndexerSharded:
    """Hash-index sharded BY WORKER for scale (reference: indexer.rs
    KvIndexerSharded:676 — N shard threads, workers assigned to the
    least-loaded shard on first sight, match queries broadcast to every
    shard and merged).

    Each shard owns its own tree; EVENTS are queued to the owning
    worker's shard thread (concurrent ingest from many worker streams —
    the sharding's whole point), while MATCHES run synchronously in the
    CALLER's thread against every shard under a short per-shard mutex.
    r3 queued matches through the shard threads too; the cross-thread
    round trip per match (p50 138 µs vs the single tree's 23 µs,
    p99 3.5 ms under load) erased the native win at exactly the scale
    sharding targets (VERDICT r3 weak #5). A mutex'd in-thread read
    costs one uncontended lock per shard; ingest holds the same lock
    only for the microseconds of one tree update, and with the native
    C++ tree both sides release the GIL so shards still overlap."""

    def __init__(self, num_shards: int = 4, block_size: int = 16):
        import queue
        import threading

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        self.num_shards = num_shards
        self._assignments: dict[int, int] = {}
        self._counts = [0] * num_shards
        self._trees = [make_radix_tree() for _ in range(num_shards)]
        self._locks = [threading.Lock() for _ in range(num_shards)]
        self._queues: list[queue.Queue] = [queue.Queue() for _ in range(num_shards)]
        self._threads: list[threading.Thread] = []
        self._closed = False
        for i in range(num_shards):
            t = threading.Thread(
                target=self._shard_loop, args=(i,),
                name=f"kv-indexer-shard-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    # -- shard thread (ingest only) ----------------------------------------
    def _shard_loop(self, idx: int) -> None:
        q = self._queues[idx]
        tree = self._trees[idx]
        lock = self._locks[idx]
        while True:
            item = q.get()
            kind = item[0]
            if kind == "stop":
                return
            try:
                with lock:
                    if kind == "event":
                        tree.apply_event(item[1])
                    elif kind == "remove":
                        tree.remove_worker(item[1])
            except Exception:  # keep the shard alive
                log.exception("shard %d op failed", idx)

    def _shard_for(self, worker_id: int) -> int:
        shard = self._assignments.get(worker_id)
        if shard is None:
            shard = min(range(self.num_shards), key=lambda i: self._counts[i])
            self._assignments[worker_id] = shard
            self._counts[shard] += 1
        return shard

    # -- KvIndexer-compatible API -----------------------------------------
    def apply(self, event: RouterEvent) -> None:
        ev_bs = event.event.token_block_size
        if ev_bs and ev_bs != self.block_size:
            log.warning(
                "adopting worker token_block_size=%d (was %d)",
                ev_bs, self.block_size,
            )
            self.block_size = ev_bs
        self._queues[self._shard_for(event.worker_id)].put(("event", event))

    def remove_worker(self, worker_id: int) -> None:
        shard = self._assignments.pop(worker_id, None)
        if shard is not None:
            self._counts[shard] -= 1
            self._queues[shard].put(("remove", worker_id))

    def find_matches(self, seq_hashes: list[int]) -> OverlapScores:
        if self._closed:
            raise RuntimeError("sharded indexer closed")
        hashes = list(seq_hashes)
        # in the caller's thread: no cross-thread round trip per match
        # (worker sets are disjoint across shards, so a plain union)
        if all(isinstance(t, NativeRadixTree) for t in self._trees):
            # one FFI crossing for all shards; hold every shard lock for
            # the microseconds of the batched walk (fixed acquisition
            # order; ingest threads each take a single lock — no cycle)
            from dynamo_tpu.native import radix_find_multi

            for lock in self._locks:
                lock.acquire()
            try:
                scores = radix_find_multi(
                    [t._native for t in self._trees], hashes
                )
            finally:
                for lock in reversed(self._locks):
                    lock.release()
            return OverlapScores(scores=scores, total_blocks=len(hashes))
        merged: dict[int, int] = {}
        for tree, lock in zip(self._trees, self._locks):
            with lock:
                merged.update(tree.find_matches(hashes).scores)
        return OverlapScores(scores=merged, total_blocks=len(hashes))

    def find_matches_for_request(self, token_ids: list[int]) -> OverlapScores:
        _, seq_hashes = hash_sequence(token_ids, self.block_size)
        return self.find_matches(seq_hashes)

    def start_consuming(self, subscriber) -> None:
        async def pump() -> None:
            try:
                async for _subject, payload in subscriber:
                    try:
                        self.apply(RouterEvent.model_validate(payload))
                    except Exception:
                        log.exception("bad router event")
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("kv event subscription died; index is frozen")

        self._task = asyncio.get_running_loop().create_task(pump())

    @property
    def num_blocks(self) -> int:
        """Sum of per-shard entries. A hash cached by workers living on
        different shards counts once per shard (shards are independent
        trees, matching the reference's sharded design)."""
        return sum(t.num_blocks for t in self._trees)

    @property
    def applied_events(self) -> int:
        return sum(t.applied_events for t in self._trees)

    def close_threads(self) -> None:
        if self._closed:
            return
        self._closed = True
        for q in self._queues:
            q.put(("stop",))
        for t in self._threads:
            t.join(timeout=5)

    async def close(self) -> None:
        task = getattr(self, "_task", None)
        if task is not None:
            task.cancel()
        self.close_threads()

    def __del__(self):  # best-effort thread cleanup
        try:
            self.close_threads()
        except Exception:
            pass


class KvIndexer:
    """Event-driven indexer: subscribes to worker KV events and answers
    overlap queries (reference: indexer.rs KvIndexer)."""

    def __init__(self, block_size: int = 16):
        self.tree = make_radix_tree()
        self.block_size = block_size
        self._task: Optional[asyncio.Task] = None

    # -- queries ----------------------------------------------------------
    def find_matches(self, seq_hashes: list[int]) -> OverlapScores:
        return self.tree.find_matches(seq_hashes)

    def find_matches_for_request(self, token_ids: list[int]) -> OverlapScores:
        _, seq_hashes = hash_sequence(token_ids, self.block_size)
        return self.tree.find_matches(seq_hashes)

    # -- event intake -----------------------------------------------------
    def apply(self, event: RouterEvent) -> None:
        # adopt the workers' block size: a mismatch would silently zero
        # every overlap score (hashes computed over different block sizes)
        ev_bs = event.event.token_block_size
        if ev_bs and ev_bs != self.block_size:
            log.warning(
                "adopting worker token_block_size=%d (was %d)", ev_bs, self.block_size
            )
            self.block_size = ev_bs
        self.tree.apply_event(event)

    def start_consuming(self, subscriber) -> None:
        """Consume RouterEvents from an async iterator of (subject, dict)."""

        async def pump() -> None:
            try:
                async for _subject, payload in subscriber:
                    try:
                        self.apply(RouterEvent.model_validate(payload))
                    except Exception:
                        log.exception("bad router event")
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("kv event subscription died; index is frozen")

        self._task = asyncio.get_running_loop().create_task(pump())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
