"""KV router wire types (reference: lib/llm/src/kv_router/protocols.rs)."""

from __future__ import annotations

from typing import Literal, Optional

from pydantic import BaseModel, Field


class KvCacheEvent(BaseModel):
    """One cache mutation on a worker: blocks stored or removed.

    ``block_hashes`` are chained sequence hashes (position-sensitive), so
    the radix tree can attach stored blocks under their parents.
    """

    op: Literal["stored", "removed", "cleared"]
    block_hashes: list[int] = Field(default_factory=list)
    parent_hash: Optional[int] = None  # for stored: hash chain parent
    token_block_size: int = 16


class RouterEvent(BaseModel):
    """KvCacheEvent tagged with its source worker + monotonic id."""

    worker_id: int
    event_id: int = 0
    event: KvCacheEvent


class ForwardPassMetrics(BaseModel):
    """Worker load snapshot (reference: protocols.rs ForwardPassMetrics)."""

    worker_id: int = 0
    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    # SLO/goodput signals (telemetry/slo.py; defaults keep the wire
    # compatible with workers that predate them). slo_enabled marks a
    # worker that actually evaluates targets — aggregators average
    # attainment over only those (a target-less worker's constant 1.0
    # would dilute the fleet signal).
    slo_enabled: bool = False
    slo_attainment: float = 1.0
    goodput_tokens_total: int = 0
    # perf attribution (telemetry/attribution.py): live achieved-over-
    # roofline ratio and the attribution window's dominant loss bucket.
    # -1.0 = no decode window yet; aggregators exclude it from the
    # fleet mean (`dynamo-tpu top` renders it per worker as ROOF%/LOSS).
    roofline_frac: float = -1.0
    top_loss_bucket: str = ""


class KvHitRateEvent(BaseModel):
    """Emitted by the router per scheduling decision
    (reference: scheduler.rs KVHitRateEvent)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int
