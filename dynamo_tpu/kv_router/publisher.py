"""Worker-side KV event + metrics publication.

Analogue of the reference's publishers (reference:
lib/llm/src/kv_router/publisher.rs — KvEventPublisher to the event plane,
ForwardPassMetrics on the load_metrics endpoint). Transport here is the
store's pub/sub (component subjects) instead of NATS/ZMQ.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Optional

from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvCacheEvent, RouterEvent
from dynamo_tpu.runtime.component import Component

log = logging.getLogger("dynamo_tpu.kv_router.publisher")

KV_EVENTS_SUBJECT = "kv_events"
LOAD_METRICS_SUBJECT = "load_metrics"


class KvEventPublisher:
    """Bridges the engine's allocator events onto the event plane.

    Wire it as ``engine.kv_event_sink = publisher.sink`` — the sink is
    thread-safe (the engine thread calls it; publication happens on the
    event loop).
    """

    def __init__(self, component: Component, worker_id: int, block_size: int = 16):
        self.component = component
        self.worker_id = worker_id
        self.block_size = block_size
        self._event_ids = itertools.count(1)
        self._loop = asyncio.get_event_loop()
        self._pending: set[asyncio.Task] = set()

    def sink(self, op: str, block_hashes: list[int], _block_ids: list[int]) -> None:
        """Engine-thread-safe event sink."""
        event = RouterEvent(
            worker_id=self.worker_id,
            event_id=next(self._event_ids),
            event=KvCacheEvent(
                op=op, block_hashes=list(block_hashes), token_block_size=self.block_size
            ),
        )
        self._loop.call_soon_threadsafe(self._publish, event)

    def _publish(self, event: RouterEvent) -> None:
        task = self._loop.create_task(
            self.component.publish(KV_EVENTS_SUBJECT, event.model_dump())
        )
        self._pending.add(task)
        task.add_done_callback(self._pending.discard)

    async def publish_cleared(self) -> None:
        await self.component.publish(
            KV_EVENTS_SUBJECT,
            RouterEvent(
                worker_id=self.worker_id,
                event_id=next(self._event_ids),
                event=KvCacheEvent(op="cleared"),
            ).model_dump(),
        )


class KvMetricsPublisher:
    """Periodically publishes the engine's ForwardPassMetrics."""

    def __init__(
        self,
        component: Component,
        worker_id: int,
        stats_fn,
        interval_s: float = 1.0,
    ):
        self.component = component
        self.worker_id = worker_id
        self.stats_fn = stats_fn
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                stats = self.stats_fn()
                payload = ForwardPassMetrics(
                    worker_id=self.worker_id, **stats.to_dict()
                ).model_dump()
                await self.component.publish(LOAD_METRICS_SUBJECT, payload)
            except Exception:
                log.exception("metrics publish failed")
            await asyncio.sleep(self.interval_s)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
