"""Record/replay router events as JSONL.

Analogue of the reference's recorders (reference:
lib/llm/src/{recorder.rs:38-273, kv_router/recorder.rs}): capture the KV
event stream to JSONL for offline router simulation, and replay a file
into an indexer — the test strategy for router behavior
(reference: lib/llm/tests/data/replays/).
"""

from __future__ import annotations

import json
import time
from typing import Callable, Iterator, Optional, TextIO

from dynamo_tpu.kv_router.protocols import RouterEvent


class KvRecorder:
    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = None
        self.count = 0

    def __enter__(self) -> "KvRecorder":
        self._fh = open(self.path, "a")
        return self

    def __exit__(self, *exc) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def record(self, event: RouterEvent) -> None:
        assert self._fh is not None, "use as a context manager"
        line = {"ts": time.time(), "event": event.model_dump()}
        self._fh.write(json.dumps(line) + "\n")
        self._fh.flush()
        self.count += 1


def iter_replay(path: str) -> Iterator[RouterEvent]:
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            raw = json.loads(line)
            yield RouterEvent.model_validate(raw["event"])


def replay_into(path: str, apply: Callable[[RouterEvent], None]) -> int:
    """Feed a recorded event log into e.g. ``KvIndexer.apply``."""
    n = 0
    for event in iter_replay(path):
        apply(event)
        n += 1
    return n
