"""KvRouter: the routed engine facade.

Analogue of the reference's KvRouter/KvPushRouter (reference:
lib/llm/src/kv_router.rs:54-210): subscribes to a component's KV events +
load metrics, and exposes (a) ``schedule()`` for explicit decisions and
(b) an AsyncEngine that picks a worker per request and dispatches direct.
Instance death prunes the worker from the index (liveness via discovery,
like the reference's etcd-watch-driven cleanup).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.kv_router.indexer import KvIndexer
from dynamo_tpu.kv_router.publisher import KV_EVENTS_SUBJECT, LOAD_METRICS_SUBJECT
from dynamo_tpu.kv_router.scheduler import (
    KvMetricsAggregator,
    KvScheduler,
    SchedulingDecision,
)
from dynamo_tpu.runtime.component import Client, Component
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    def __init__(self, component: Component, client: Client, block_size: int = 16):
        self.component = component
        self.client = client
        self.indexer = KvIndexer(block_size=block_size)
        self.aggregator = KvMetricsAggregator()
        self.scheduler = KvScheduler(self.indexer, self.aggregator)
        self._prune_task: Optional[asyncio.Task] = None

    @classmethod
    async def create(
        cls, component: Component, client: Client, block_size: int = 16
    ) -> "KvRouter":
        router = cls(component, client, block_size)
        router.indexer.start_consuming(
            await component.subscribe(KV_EVENTS_SUBJECT)
        )
        router.aggregator.start_consuming(
            await component.subscribe(LOAD_METRICS_SUBJECT)
        )
        # publish per-decision hit-rate events for the metrics service
        # (reference: scheduler.rs KVHitRateEvent on "kv-hit-rate")
        loop = asyncio.get_running_loop()
        pending: set[asyncio.Task] = set()

        def publish_hit_rate(ev) -> None:
            task = loop.create_task(
                component.namespace.publish("kv-hit-rate", ev.model_dump())
            )
            pending.add(task)
            task.add_done_callback(pending.discard)

        router.scheduler.on_hit_rate = publish_hit_rate
        router._prune_task = asyncio.get_running_loop().create_task(
            router._prune_dead_workers()
        )
        return router

    async def _prune_dead_workers(self) -> None:
        """Drop departed instances from index + metrics (reference:
        scheduler.rs endpoint-watch driven cleanup)."""
        known: set[int] = set()
        while True:
            live = set(self.client.instance_ids())
            for dead in known - live:
                log.info("pruning dead worker %x from kv index", dead)
                self.indexer.tree.remove_worker(dead)
                self.aggregator.remove_worker(dead)
            known = live
            await asyncio.sleep(1.0)

    def schedule(self, token_ids: list[int]) -> SchedulingDecision:
        return self.scheduler.schedule(token_ids, self.client.instance_ids())

    async def close(self) -> None:
        if self._prune_task is not None:
            self._prune_task.cancel()
        await self.indexer.close()
        await self.aggregator.close()


class KvPushRouter(AsyncEngine):
    """AsyncEngine that KV-routes each PreprocessedRequest then streams
    from the chosen worker (reference: kv_router.rs KvPushRouter)."""

    def __init__(self, router: KvRouter):
        self.router = router

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        token_ids = (
            request.token_ids if hasattr(request, "token_ids") else request["token_ids"]
        )
        await self.router.client.wait_for_instances()
        decision = self.router.schedule(list(token_ids))
        # annotate the request with the expected prefix hit (the worker's
        # disagg router uses it, reference: worker.py prefix_hit_rate)
        if hasattr(request, "annotations"):
            request.annotations = list(request.annotations) + [
                f"kv_hit_rate:{decision.prefix_hit_rate:.3f}"
            ]
        # schedule() charged this decision as optimistic in-flight load;
        # release it early when the stream finishes (expiry otherwise
        # clears it on the worker's next metrics publish)
        try:
            stream = await self.router.client.generate_direct(
                decision.worker_id, request, context
            )
            async for item in stream:
                yield item
        finally:
            self.router.scheduler.note_done(
                decision.worker_id, decision.dispatch_token
            )

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)
