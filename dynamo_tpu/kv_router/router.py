"""KvRouter: the routed engine facade.

Analogue of the reference's KvRouter/KvPushRouter (reference:
lib/llm/src/kv_router.rs:54-210): subscribes to a component's KV events +
load metrics, and exposes (a) ``schedule()`` for explicit decisions and
(b) an AsyncEngine that picks a worker per request and dispatches direct.
Instance death prunes the worker from the index (liveness via discovery,
like the reference's etcd-watch-driven cleanup).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.kv_router.indexer import KvIndexer
from dynamo_tpu.kv_router.publisher import KV_EVENTS_SUBJECT, LOAD_METRICS_SUBJECT
from dynamo_tpu.kv_router.scheduler import (
    KvMetricsAggregator,
    KvScheduler,
    SchedulingDecision,
)
from dynamo_tpu.runtime.component import Client, Component
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.runtime.migration import (
    DialFailedError,
    MigrationConfig,
    migrating_stream,
)
from dynamo_tpu.telemetry import autopsy

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    def __init__(self, component: Component, client: Client, block_size: int = 16):
        self.component = component
        self.client = client
        self.indexer = KvIndexer(block_size=block_size)
        self.aggregator = KvMetricsAggregator()
        self.scheduler = KvScheduler(self.indexer, self.aggregator)
        self._prune_task: Optional[asyncio.Task] = None

    def attach_fleet_catalog(self, catalog: Any) -> None:
        """Score fleet-fetchable prefixes (kvbm/fabric.py
        FleetPrefixCatalog) at the discounted fetch weight: blocks any
        candidate can onboard from a peer's host tier or the shared
        bucket stop reading as 'only worker A is cache-hot'."""
        self.scheduler.fleet_catalog = catalog

    @classmethod
    async def create(
        cls, component: Component, client: Client, block_size: int = 16
    ) -> "KvRouter":
        router = cls(component, client, block_size)
        router.indexer.start_consuming(
            await component.subscribe(KV_EVENTS_SUBJECT)
        )
        router.aggregator.start_consuming(
            await component.subscribe(LOAD_METRICS_SUBJECT)
        )
        # publish per-decision hit-rate events for the metrics service
        # (reference: scheduler.rs KVHitRateEvent on "kv-hit-rate")
        loop = asyncio.get_running_loop()
        pending: set[asyncio.Task] = set()

        def publish_hit_rate(ev) -> None:
            task = loop.create_task(
                component.namespace.publish("kv-hit-rate", ev.model_dump())
            )
            pending.add(task)
            task.add_done_callback(pending.discard)

        router.scheduler.on_hit_rate = publish_hit_rate
        router._prune_task = asyncio.get_running_loop().create_task(
            router._prune_dead_workers()
        )
        return router

    async def _prune_dead_workers(self) -> None:
        """Drop departed instances from index + metrics (reference:
        scheduler.rs endpoint-watch driven cleanup)."""
        known: set[int] = set()
        while True:
            # the FULL dialable view: a DRAINING worker is alive and
            # serving its in-flight streams — pruning its index on the
            # flag (instead of on departure) would misroute the very
            # resumes the drain is handing off
            live = set(self.client.instance_ids(include_draining=True))
            for dead in known - live:
                log.info("pruning dead worker %x from kv index", dead)
                self.indexer.tree.remove_worker(dead)
                self.aggregator.remove_worker(dead)
            known = live
            await asyncio.sleep(1.0)

    def schedule(
        self,
        token_ids: list[int],
        exclude: Optional[set[int]] = None,
        resume: bool = False,
    ) -> SchedulingDecision:
        """Pick a worker; ``exclude`` drops instances a failover already
        saw die (falls back to the full live set if that empties it).
        ``resume`` marks a mid-stream migration re-dispatch: the
        scheduler weighs prefix overlap more heavily so a cache-hot
        instance turns the resume's re-prefill into a cheap onboard
        (docs/robustness.md "Mid-stream migration")."""
        ids = self.client.instance_ids()
        if exclude:
            filtered = [i for i in ids if i not in exclude]
            ids = filtered or ids
        return self.scheduler.schedule(
            token_ids, ids, resume=resume,
            draining=self.client.draining_ids(),
        )

    async def close(self) -> None:
        if self._prune_task is not None:
            self._prune_task.cancel()
        await self.indexer.close()
        await self.aggregator.close()


class KvPushRouter(AsyncEngine):
    """AsyncEngine that KV-routes each PreprocessedRequest then streams
    from the chosen worker (reference: kv_router.rs KvPushRouter).

    Failover and mid-stream migration mirror PushRouter (the shared
    loop in runtime/migration.py): dial failures and streams that die
    before the first item re-schedule onto a different worker (bounded
    attempts, backoff + jitter); once items have streamed, a worker
    death re-dispatches the request as a *resume* — and because the
    resume's token_ids carry the already-delivered tokens, the KV-aware
    ``schedule(resume=True)`` prefers instances whose prefix cache is
    already hot for them. Only an exhausted (or opted-out) migration
    ends the stream with a clean WorkerStreamLostError."""

    def __init__(
        self,
        router: KvRouter,
        max_attempts: int = 3,
        migration: Optional[MigrationConfig] = None,
        admission: Any = None,
    ):
        self.router = router
        self.max_attempts = max_attempts
        self.migration = migration or MigrationConfig.from_env()
        self.admission = admission

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        async def dial(req, exclude, resume, wait_timeout_s):
            await self.router.client.wait_for_instances(wait_timeout_s)
            token_ids = (
                req.token_ids
                if hasattr(req, "token_ids")
                else req["token_ids"]
            )
            decision = self.router.schedule(
                list(token_ids), exclude=exclude, resume=resume
            )
            # request autopsy: the routing decision — worker chosen plus
            # the overlap/fleet-block score that chose it (re-dials and
            # resumes append their own entries)
            autopsy.note_router(
                context.id, decision.worker_id,
                overlap_blocks=decision.overlap_blocks,
                total_blocks=decision.total_blocks,
                fleet_blocks=decision.fleet_blocks,
                resume=resume, mode="kv",
            )
            # annotate the request with the expected prefix hit (the
            # worker's disagg router uses it, reference: worker.py
            # prefix_hit_rate)
            if hasattr(req, "annotations"):
                req.annotations = list(req.annotations) + [
                    f"kv_hit_rate:{decision.prefix_hit_rate:.3f}"
                ]
            # schedule() charged this decision as optimistic in-flight
            # load; release it when the segment ends (expiry otherwise
            # clears it on the worker's next metrics publish)
            done = lambda: self.router.scheduler.note_done(  # noqa: E731
                decision.worker_id, decision.dispatch_token
            )
            try:
                stream = await self.router.client.generate_direct(
                    decision.worker_id, req, context
                )
            except BaseException as exc:
                done()
                if isinstance(exc, (OSError, asyncio.TimeoutError, KeyError)):
                    # carry the picked worker out so the retry excludes
                    # it instead of re-scheduling onto the same corpse
                    raise DialFailedError(decision.worker_id, exc) from exc
                raise
            return decision.worker_id, stream, done

        async for item in migrating_stream(
            request, context, dial, self.migration,
            admission=self.admission,
            max_attempts=self.max_attempts,
            endpoint_name="kv-routed generate",
        ):
            yield item

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)
