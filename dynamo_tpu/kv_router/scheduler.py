"""KV-aware worker selection: metrics aggregation + cost function.

Analogue of the reference's scheduler (reference:
lib/llm/src/kv_router/scheduler.rs:88-337 — DefaultWorkerSelector:
``logit = 2*overlap − gpu_cache_usage − normalized_waiting``, random
tie-break; lib/llm/src/kv_router/{metrics_aggregator.rs,scoring.rs}).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from dynamo_tpu.kv_router.indexer import KvIndexer, OverlapScores
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvHitRateEvent
from dynamo_tpu.tokens import hash_sequence

log = logging.getLogger("dynamo_tpu.kv_router.scheduler")

# selector: (overlaps, metrics by worker, candidate ids) -> worker id
Selector = Callable[[OverlapScores, dict[int, ForwardPassMetrics], list[int]], int]


def default_selector(
    overlaps: OverlapScores,
    metrics: dict[int, ForwardPassMetrics],
    candidates: list[int],
) -> int:
    """reference: scheduler.rs DefaultWorkerSelector."""
    max_waiting = max(
        (metrics[w].num_requests_waiting for w in candidates if w in metrics),
        default=0,
    )
    best_ids: list[int] = []
    best_logit = float("-inf")
    for wid in candidates:
        m = metrics.get(wid, ForwardPassMetrics(worker_id=wid))
        overlap = overlaps.scores.get(wid, 0)
        waiting_norm = (
            m.num_requests_waiting / max_waiting if max_waiting > 0 else 0.0
        )
        logit = 2.0 * overlap - m.gpu_cache_usage_perc - waiting_norm
        if logit > best_logit:
            best_logit, best_ids = logit, [wid]
        elif logit == best_logit:
            best_ids.append(wid)
    return random.choice(best_ids)


class KvMetricsAggregator:
    """Holds the latest ForwardPassMetrics per worker, fed by pub/sub
    (reference: metrics_aggregator.rs; transport differs — the reference
    scrapes NATS service stats, we subscribe to a metrics subject)."""

    def __init__(self, stale_after_s: float = 10.0):
        self.metrics: dict[int, ForwardPassMetrics] = {}
        self._updated: dict[int, float] = {}
        self.stale_after_s = stale_after_s
        self._task: Optional[asyncio.Task] = None

    def update(self, m: ForwardPassMetrics) -> None:
        self.metrics[m.worker_id] = m
        self._updated[m.worker_id] = time.monotonic()

    def last_update(self, worker_id: int) -> float:
        """monotonic timestamp of the worker's latest snapshot (0 = never)."""
        return self._updated.get(worker_id, 0.0)

    def fresh_metrics(self) -> dict[int, ForwardPassMetrics]:
        now = time.monotonic()
        return {
            w: m
            for w, m in self.metrics.items()
            if now - self._updated.get(w, 0) < self.stale_after_s
        }

    def remove_worker(self, worker_id: int) -> None:
        self.metrics.pop(worker_id, None)
        self._updated.pop(worker_id, None)

    def start_consuming(self, subscriber) -> None:
        async def pump() -> None:
            try:
                async for _subject, payload in subscriber:
                    try:
                        self.update(ForwardPassMetrics.model_validate(payload))
                    except Exception:
                        log.exception("bad metrics payload")
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("metrics subscription died; snapshot is frozen")

        self._task = asyncio.get_running_loop().create_task(pump())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()


@dataclass
class SchedulingDecision:
    worker_id: int
    overlap_blocks: int
    total_blocks: int
    # the in-flight charge this decision placed (note_dispatch's return):
    # pass it back to note_done so completion releases THIS request's
    # charge, not some later request's (ADVICE r5: anonymous pops under
    # bursts released the wrong entry)
    dispatch_token: float = 0.0
    # leading blocks fetchable from the fleet KV fabric (peer host tier
    # or shared bucket) — 0 when no catalog is attached. Informational:
    # the logit already counted them at the discounted fetch weight.
    fleet_blocks: int = 0

    @property
    def prefix_hit_rate(self) -> float:
        return self.overlap_blocks / self.total_blocks if self.total_blocks else 0.0


class KvScheduler:
    """indexer + metrics + selector → routing decisions
    (reference: kv_router.rs KvRouter.schedule)."""

    def __init__(
        self,
        indexer: KvIndexer,
        aggregator: KvMetricsAggregator,
        selector: Selector = default_selector,
        on_hit_rate: Optional[Callable[[KvHitRateEvent], None]] = None,
        fleet_catalog: Optional[Any] = None,
    ):
        self.indexer = indexer
        self.aggregator = aggregator
        self.selector = selector
        self.on_hit_rate = on_hit_rate
        # fleet KV fabric catalog (kvbm/fabric.py FleetPrefixCatalog, or
        # anything with match_prefix(seq_hashes) -> int): blocks any
        # candidate can fetch from a peer's host tier / the shared
        # bucket instead of recomputing. Counted at fleet_hit_weight.
        self.fleet_catalog = fleet_catalog
        # optimistic in-flight accounting: published metrics lag by a
        # publish interval, so a BURST of concurrent no-overlap requests
        # would all see identical zero-load snapshots and (modulo the
        # random tie-break) pile onto few workers — measured as a 1.7x
        # first-turn TTFT p50 penalty vs round-robin on a 6-user burst
        # (benchmarks/router_ab_bench.py). Every schedule() charges its
        # decision as one waiting request, and the charge expires as
        # soon as the worker publishes a metrics snapshot NEWER than
        # the dispatch (the snapshot then reflects the request itself —
        # keeping the charge would double-count it for the whole
        # stream) or after a TTL backstop when no metrics flow at all.
        # Decision-only callers (the standalone `schedule` endpoint)
        # are covered because the charge lives here, not in the proxy.
        self.inflight: dict[int, list[float]] = {}
        self.inflight_ttl_s = 5.0

    def note_dispatch(self, worker_id: int) -> float:
        """Charge one in-flight dispatch; returns the charge's token
        (its monotonic timestamp). Keep it and hand it to note_done —
        an anonymous release under a burst would pop the OLDEST entry,
        i.e. release a later request's still-live charge."""
        token = time.monotonic()
        self.inflight.setdefault(worker_id, []).append(token)
        return token

    def note_done(self, worker_id: int, token: Optional[float] = None) -> None:
        """Optional early release (proxy paths that observe stream
        completion); expiry handles callers that never report back.
        ``token`` (note_dispatch's return) releases that SPECIFIC charge
        — a no-op if it already expired or was consumed by a newer
        metrics snapshot. token=None keeps the legacy oldest-entry pop
        for callers that didn't record one."""
        entries = self.inflight.get(worker_id)
        if not entries:
            return
        if token is None:
            entries.pop(0)
        else:
            try:
                entries.remove(token)
            except ValueError:
                return  # already expired / released by a fresher snapshot
        if not entries:
            self.inflight.pop(worker_id, None)

    def _active_inflight(self, worker_id: int) -> int:
        entries = self.inflight.get(worker_id)
        if not entries:
            return 0
        now = time.monotonic()
        seen_at = self.aggregator.last_update(worker_id)
        live = [
            t for t in entries
            if t > seen_at and now - t < self.inflight_ttl_s
        ]
        if live:
            self.inflight[worker_id] = live
        else:
            self.inflight.pop(worker_id, None)
        return len(live)

    # how much harder prefix overlap weighs for a migration resume: a
    # resume's token_ids carry the tokens already streamed, so a worker
    # holding that prefix turns the re-prefill into a cheap onboard —
    # worth crossing a load gradient for (docs/robustness.md
    # "Mid-stream migration"). Applied by scaling the overlap scores the
    # selector sees, so custom selectors keep their 3-arg signature.
    resume_overlap_boost: float = 2.0

    # discount for fleet-fetchable blocks in the overlap term: a fetch
    # from a peer's host tier / the shared bucket is far cheaper than
    # recompute but dearer than a local (G1/G2) hit. Fleet blocks count
    # for every candidate (any worker can fetch them), which NARROWS the
    # local-overlap worker's advantage to 2*(1-w)*blocks of logit — the
    # router stops thrash-pinning a loaded worker for a prefix the whole
    # fleet can onboard. Must stay < 1.0: a fleet hit must never score
    # at local weight, including under the resume boost (the boost
    # multiplies AFTER this discount, so a resume racing a demotion sees
    # boost*w*blocks, not boost*blocks).
    fleet_hit_weight: float = 0.35

    def _fleet_match(self, token_ids: list[int]) -> int:
        """Leading blocks fetchable from the fleet fabric (catalog
        membership only — no network). Never raises into routing."""
        if self.fleet_catalog is None:
            return 0
        try:
            _, seq_hashes = hash_sequence(
                list(token_ids), self.indexer.block_size
            )
            return int(self.fleet_catalog.match_prefix(seq_hashes))
        except Exception:
            log.exception("fleet catalog match failed; scoring local-only")
            return 0

    def schedule(
        self, token_ids: list[int], candidates: list[int],
        resume: bool = False,
        draining: Optional[set[int]] = None,
    ) -> SchedulingDecision:
        if not candidates:
            raise RuntimeError("no candidate workers")
        overlaps = self.indexer.find_matches_for_request(token_ids)
        true_overlaps = overlaps
        fleet_blocks = self._fleet_match(token_ids)
        if draining:
            # DRAINING workers never take fresh placement (defensive:
            # the router's candidate list already excludes them; fall
            # back only if that empties the set entirely)...
            healthy = [w for w in candidates if w not in draining]
            candidates = healthy or candidates
            # ...but their indexed prefixes don't vanish: the drain
            # publishes/retiers them into the fleet catalog before the
            # handoff, so count them as FLEET overlap (fetchable by any
            # candidate at fleet_hit_weight) rather than local — even
            # when the catalog refresh hasn't landed yet
            drain_local = max(
                (overlaps.scores.get(w, 0) for w in draining), default=0
            )
            if drain_local > fleet_blocks:
                fleet_blocks = drain_local
        if fleet_blocks or (resume and overlaps.scores):
            boost = self.resume_overlap_boost if resume else 1.0
            # effective overlap per candidate: local blocks at full
            # weight + the fleet-fetchable extension at fetch weight.
            # The resume boost scales the COMBINED score, so the fleet
            # contribution stays discounted (satellite guarantee: a
            # resume whose prefix was just demoted off every device
            # scores boost*fleet_hit_weight*blocks, never at local
            # weight as if the blocks were still resident).
            # OverlapScores is sparse (absent = 0): only workers with a
            # non-zero effective overlap get an entry, so a resume with
            # no fleet catalog scores exactly as before.
            scores = {}
            for w in set(candidates) | set(overlaps.scores):
                local = overlaps.scores.get(w, 0)
                eff = local + self.fleet_hit_weight * max(
                    0, fleet_blocks - local
                )
                if eff:
                    scores[w] = boost * eff
            overlaps = OverlapScores(
                scores=scores,
                total_blocks=overlaps.total_blocks,
            )
        fresh = self.aggregator.fresh_metrics()
        # prefer workers with a live health signal: if SOME candidates have
        # fresh metrics, a candidate without them is stale (hung publisher /
        # dead worker) — don't reward it with a default zero-load score
        with_fresh = [w for w in candidates if w in fresh]
        if with_fresh:
            candidates = with_fresh
        metrics = fresh
        if self.inflight:
            charges = {w: self._active_inflight(w) for w in candidates}
            metrics = {
                w: m.model_copy(update={
                    "num_requests_waiting": m.num_requests_waiting
                    + charges.get(w, 0)
                })
                for w, m in fresh.items()
            }
            for w, n in charges.items():
                if n > 0 and w not in metrics:
                    metrics[w] = ForwardPassMetrics(
                        worker_id=w, num_requests_waiting=n
                    )
        wid = self.selector(overlaps, metrics, candidates)
        token = self.note_dispatch(wid)
        # decision + hit-rate event report the TRUE (unboosted) overlap
        decision = SchedulingDecision(
            worker_id=wid,
            overlap_blocks=true_overlaps.scores.get(wid, 0),
            total_blocks=true_overlaps.total_blocks,
            dispatch_token=token,
            fleet_blocks=fleet_blocks,
        )
        if self.on_hit_rate is not None:
            self.on_hit_rate(
                KvHitRateEvent(
                    worker_id=wid,
                    isl_blocks=decision.total_blocks,
                    overlap_blocks=decision.overlap_blocks,
                )
            )
        return decision
