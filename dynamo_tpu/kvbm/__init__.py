"""Multi-tier KV block manager (G1 HBM / G2 host / G3 disk / G4 remote).

Reference: lib/llm/src/block_manager/ (KvBlockManager, tier pools,
layouts, offload manager). See manager.py for the TPU-native design and
fabric.py for the fleet-wide catalog + peer-onboarding plane.
"""

from dynamo_tpu.kvbm.fabric import (
    DictCatalogBackend,
    FleetKvFabric,
    FleetPrefixCatalog,
    LocalPeerRegistry,
    PeerBlockServer,
    PressureConfig,
    StoreCatalogBackend,
    TcpPeerClient,
)
from dynamo_tpu.kvbm.layout import BlockLayout
from dynamo_tpu.kvbm.manager import KvbmConfig, KvbmStats, KvBlockManager
from dynamo_tpu.kvbm.pool import TierPool
from dynamo_tpu.kvbm.storage import (
    BlockStorage,
    DiskBlockStorage,
    HostBlockStorage,
    NullBlockStorage,
)

__all__ = [
    "BlockLayout",
    "KvbmConfig",
    "KvbmStats",
    "KvBlockManager",
    "TierPool",
    "BlockStorage",
    "DiskBlockStorage",
    "HostBlockStorage",
    "NullBlockStorage",
    "DictCatalogBackend",
    "FleetKvFabric",
    "FleetPrefixCatalog",
    "LocalPeerRegistry",
    "PeerBlockServer",
    "PressureConfig",
    "StoreCatalogBackend",
    "TcpPeerClient",
]
