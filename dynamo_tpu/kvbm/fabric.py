"""Fleet KV fabric: cross-worker prefix sharing + pressure-driven tiers.

The single-worker KVBM (manager.py) gives one engine a G1→G2→G3/G4
offload ladder. This module makes the ladder *fleet-wide* (ROADMAP item
3; reference: block_manager.rs G1–G4 + NIXL transfer, offload.rs):

- **Fleet prefix catalog** — content-addressed block-chain hashes (the
  same chained sequence hashes ``tokens.py`` mints and the kv_router's
  radix indexer keys on) mapped to ``(worker, tier, bytes, last_touch)``
  locations, kept in the coordinator store. Each worker's KVBM publishes
  when ``pump()`` lands blocks in G2 and prunes on eviction, so the
  catalog is the fleet's always-current "who holds which prefix" map.
- **Peer onboarding** — at admission, prompt blocks that miss every
  local tier but hit the catalog are fetched from the owning peer's
  host tier over the store wire plane (``store/wire.py`` framing) or
  adopted from the shared G4 object bucket, then onboarded through the
  existing jitted scatter. A system prompt is prefilled ONCE fleet-wide.
- **Pressure-driven lifecycle** — host-pool watermarks drive G2→G3/G4
  demotion with popularity-weighted victim selection: hot shared
  prefixes demote to the *shared* G4 bucket (they outlive their owner),
  cold private ones to local disk. The planner's degradation ladder
  tightens the same watermark (the "demote cold KV" rung,
  ``LadderPolicy.fabric_pressure_scale``).

Thread contract: every fabric method the KVBM calls (`on_host_insert`,
``prefetch``, ``enforce_pressure``) runs on the ENGINE thread, exactly
like the manager itself. The peer block server runs on the event loop
and reads the host tier through ``KvBlockManager.export_host_blocks``,
which shares a lock with the engine-thread mutation paths.
"""

from __future__ import annotations

import abc
import asyncio
import json
import logging
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import msgpack

from dynamo_tpu.telemetry import autopsy
from dynamo_tpu.telemetry.debug import (
    register_debug_provider,
    unregister_debug_provider,
)
from dynamo_tpu.telemetry.instruments import (
    KVBM_FLEET_CATALOG_ENTRIES,
    KVBM_FLEET_DANGLING,
    KVBM_FLEET_DEMOTED_BLOCKS,
    KVBM_FLEET_FETCH_SECONDS,
    KVBM_FLEET_FETCHED_BLOCKS,
    KVBM_FLEET_HITS,
)
from dynamo_tpu.utils.clock import SYSTEM, Clock

log = logging.getLogger("dynamo_tpu.kvbm.fabric")

# tier names as published in the catalog. Only g2 (peer host tier) and
# g4 (shared object bucket) are fleet-fetchable; g3 (a worker's local
# disk) is private and exists in the catalog only so the owner's own
# restarts and the debug surface can see it.
TIER_HOST = "g2"
TIER_DISK = "g3"
TIER_SHARED = "g4"
FLEET_TIERS = (TIER_HOST, TIER_SHARED)


# ---------------------------------------------------------------------------
# Catalog backends
# ---------------------------------------------------------------------------


class CatalogBackend(abc.ABC):
    """Blocking catalog transport (the engine thread owns the pump that
    publishes; same sync-facade pattern as SyncObjectStore)."""

    @abc.abstractmethod
    def put(self, seq_hash: int, worker_id: int, entry: dict) -> None: ...

    @abc.abstractmethod
    def delete(self, seq_hash: int, worker_id: int) -> None: ...

    @abc.abstractmethod
    def snapshot(self) -> dict[int, dict[int, dict]]:
        """Full catalog view: seq_hash -> worker_id -> entry."""

    def put_many(self, items: list[tuple[int, int, dict]]) -> None:
        for h, w, e in items:
            self.put(h, w, e)


class DictCatalogBackend(CatalogBackend):
    """In-process shared catalog for tests and single-process fleets
    (every worker of the process holds the same instance)."""

    def __init__(self) -> None:
        self._data: dict[int, dict[int, dict]] = {}
        self._lock = threading.Lock()

    def put(self, seq_hash: int, worker_id: int, entry: dict) -> None:
        with self._lock:
            self._data.setdefault(seq_hash, {})[worker_id] = dict(entry)

    def delete(self, seq_hash: int, worker_id: int) -> None:
        with self._lock:
            owners = self._data.get(seq_hash)
            if owners is not None:
                owners.pop(worker_id, None)
                if not owners:
                    self._data.pop(seq_hash, None)

    def snapshot(self) -> dict[int, dict[int, dict]]:
        with self._lock:
            return {
                h: {w: dict(e) for w, e in owners.items()}
                for h, owners in self._data.items()
            }


def catalog_key_prefix(namespace: str) -> str:
    return f"{namespace}/kvfleet/catalog/"


class StoreCatalogBackend(CatalogBackend):
    """Catalog in the coordinator store's KV plane.

    Keys: ``{namespace}/kvfleet/catalog/{seq_hash:016x}/{worker_id}``,
    values: JSON entries — small enough that a full-prefix snapshot is
    one round trip, and a worker's keys can ride its lease so a dead
    worker's G2 claims vanish with it.

    Blocking bridge onto the runtime's loop with the SAME timeout
    surfacing as the G4 object adapter (kvbm/remote.py): a store
    timeout books ``dynamo_kvbm_remote_timeout_total{op=catalog}`` and
    a flight-recorder record instead of killing the engine pump.
    """

    def __init__(
        self,
        store: Any,
        namespace: str,
        loop: asyncio.AbstractEventLoop,
        timeout_s: float = 10.0,
        lease_id: int = 0,
        recorder: Any = None,
    ):
        self.store = store
        self.prefix = catalog_key_prefix(namespace)
        self.loop = loop
        self.timeout_s = timeout_s
        self.lease_id = lease_id
        self.recorder = recorder

    def _key(self, seq_hash: int, worker_id: int) -> str:
        return f"{self.prefix}{seq_hash:016x}/{worker_id}"

    def _run(self, coro, op: str):
        from dynamo_tpu.kvbm.remote import run_on_loop

        return run_on_loop(
            coro, self.loop, self.timeout_s, op=f"catalog.{op}",
            recorder=self.recorder,
        )

    def put(self, seq_hash: int, worker_id: int, entry: dict) -> None:
        self._run(
            self.store.kv_put(
                self._key(seq_hash, worker_id),
                json.dumps(entry).encode(),
                self.lease_id,
            ),
            "put",
        )

    def put_many(self, items: list[tuple[int, int, dict]]) -> None:
        if not items:
            return

        async def gather():
            await asyncio.gather(
                *[
                    self.store.kv_put(
                        self._key(h, w), json.dumps(e).encode(), self.lease_id
                    )
                    for h, w, e in items
                ]
            )

        self._run(gather(), "put_many")

    def delete(self, seq_hash: int, worker_id: int) -> None:
        self._run(self.store.kv_delete(self._key(seq_hash, worker_id)), "delete")

    def snapshot(self) -> dict[int, dict[int, dict]]:
        entries = self._run(self.store.kv_get_prefix(self.prefix), "snapshot")
        out: dict[int, dict[int, dict]] = {}
        for e in entries:
            tail = e.key[len(self.prefix):]
            try:
                hash_part, worker_part = tail.split("/", 1)
                h = int(hash_part, 16)
                w = int(worker_part)
                entry = json.loads(e.value)
            except (ValueError, json.JSONDecodeError):
                log.warning("malformed catalog key/value: %r", e.key)
                continue
            out.setdefault(h, {})[w] = entry
        return out


# ---------------------------------------------------------------------------
# Fleet prefix catalog (local view + publisher)
# ---------------------------------------------------------------------------


class FleetPrefixCatalog:
    """One participant's view of the fleet catalog.

    Workers publish/prune through the backend as their G2 tier changes;
    everyone (workers prefetching, the KV router scoring fleet hits)
    reads through ``match_prefix``/``locations`` against a locally
    cached snapshot refreshed by ``refresh()`` — membership checks stay
    off the network, exactly like the G4 tier's local index.
    """

    def __init__(
        self,
        backend: CatalogBackend,
        worker_id: int = -1,
        clock: Optional[Clock] = None,
    ):
        self.backend = backend
        self.worker_id = worker_id
        self.clock = clock or SYSTEM
        self._view: dict[int, dict[int, dict]] = {}

    # -- publishing (engine thread of the owning worker) -------------------
    def publish(
        self, seq_hash: int, tier: str, nbytes: int, addr: str = ""
    ) -> None:
        entry = {
            "tier": tier,
            "bytes": int(nbytes),
            "t": self.clock.time(),
            "addr": addr,
        }
        self.backend.put(seq_hash, self.worker_id, entry)
        self._view.setdefault(seq_hash, {})[self.worker_id] = entry

    def publish_many(
        self, hashes: list[int], tier: str, nbytes: int, addr: str = ""
    ) -> None:
        now = self.clock.time()
        items = []
        for h in hashes:
            entry = {"tier": tier, "bytes": int(nbytes), "t": now, "addr": addr}
            items.append((h, self.worker_id, entry))
            self._view.setdefault(h, {})[self.worker_id] = entry
        self.backend.put_many(items)

    def retier(self, seq_hash: int, tier: str) -> None:
        owners = self._view.get(seq_hash, {})
        entry = dict(owners.get(self.worker_id) or {"bytes": 0, "addr": ""})
        entry["tier"] = tier
        entry["t"] = self.clock.time()
        self.backend.put(seq_hash, self.worker_id, entry)
        self._view.setdefault(seq_hash, {})[self.worker_id] = entry

    def prune(self, seq_hash: int, worker_id: Optional[int] = None) -> None:
        wid = self.worker_id if worker_id is None else worker_id
        self.backend.delete(seq_hash, wid)
        owners = self._view.get(seq_hash)
        if owners is not None:
            owners.pop(wid, None)
            if not owners:
                self._view.pop(seq_hash, None)

    # -- reading ------------------------------------------------------------
    def refresh(self) -> None:
        self._view = self.backend.snapshot()
        KVBM_FLEET_CATALOG_ENTRIES.set(len(self._view))

    def locations(
        self, seq_hash: int, exclude_worker: Optional[int] = None
    ) -> list[tuple[int, dict]]:
        """Fleet-fetchable locations of a block: peers' G2 copies and
        anyone's G4 (shared-bucket) copies. A worker's own entries and
        private G3 disk copies are not fetchable by the fleet."""
        out = []
        for w, entry in (self._view.get(seq_hash) or {}).items():
            if exclude_worker is not None and w == exclude_worker:
                continue
            if entry.get("tier") in FLEET_TIERS:
                out.append((w, entry))
        # prefer shared-bucket copies (no peer round trip needed), then
        # host copies by recency
        out.sort(
            key=lambda we: (
                we[1].get("tier") != TIER_SHARED,
                -float(we[1].get("t", 0.0)),
            )
        )
        return out

    def match_prefix(
        self, seq_hashes: list[int], exclude_worker: Optional[int] = None
    ) -> int:
        """Leading consecutive blocks with at least one fleet-fetchable
        location (membership only — no network, no fetches)."""
        n = 0
        for h in seq_hashes:
            if self.locations(h, exclude_worker):
                n += 1
            else:
                break
        return n

    @property
    def num_entries(self) -> int:
        return len(self._view)

    def stats(self) -> dict:
        tiers: dict[str, int] = {}
        for owners in self._view.values():
            for entry in owners.values():
                t = entry.get("tier", "?")
                tiers[t] = tiers.get(t, 0) + 1
        return {"entries": len(self._view), "by_tier": tiers}


# ---------------------------------------------------------------------------
# Peer block plane (store wire framing)
# ---------------------------------------------------------------------------


class PeerFetcher(abc.ABC):
    """Fetches packed block bytes from a peer's host tier."""

    @abc.abstractmethod
    def fetch(
        self, addr: str, seq_hashes: list[int]
    ) -> Optional[list[Optional[bytes]]]:
        """Returns one ``bytes | None`` per hash; ``None`` overall when
        the peer is unreachable. MUST NOT raise — a flaky peer reads as
        a miss (the caller falls back to recompute)."""


class LocalPeerRegistry(PeerFetcher):
    """In-process peer plane for single-process fleets and tests:
    ``addr`` is ``local:<name>``, mapped to the exporter callable each
    worker registers (KvBlockManager.export_host_blocks)."""

    def __init__(self) -> None:
        self._exporters: dict[str, Callable[[list[int]], list[Optional[bytes]]]] = {}

    def register(
        self, name: str, exporter: Callable[[list[int]], list[Optional[bytes]]]
    ) -> str:
        addr = f"local:{name}"
        self._exporters[addr] = exporter
        return addr

    def unregister(self, addr: str) -> None:
        self._exporters.pop(addr, None)

    def fetch(
        self, addr: str, seq_hashes: list[int]
    ) -> Optional[list[Optional[bytes]]]:
        exporter = self._exporters.get(addr)
        if exporter is None:
            return None
        try:
            return exporter(seq_hashes)
        except Exception:
            log.exception("local peer fetch from %s failed", addr)
            return None


class PeerBlockServer:
    """Serves a worker's G2 host-tier blocks to peers over the store
    wire plane (length-prefixed msgpack, store/wire.py — the same
    framing the coordinator store speaks).

    Runs on the event loop; ``exporter`` must be thread-safe
    (KvBlockManager.export_host_blocks takes the host-tier lock shared
    with the engine thread's mutation paths)."""

    def __init__(
        self,
        exporter: Callable[[list[int]], list[Optional[bytes]]],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.exporter = exporter
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set[asyncio.StreamWriter] = set()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> str:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("kvfleet peer block server on %s", self.addr)
        return self.addr

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        from dynamo_tpu.store.wire import read_frame, write_frame

        self._writers.add(writer)
        try:
            while True:
                req = await read_frame(reader)
                op = req.get("op")
                if op == "fetch":
                    hashes = [int(h) for h in req.get("hashes", [])]
                    # the exporter is synchronous but lock-cheap (pure
                    # host-RAM reads); run in the default executor so a
                    # multi-MB gather doesn't stall this loop's streams
                    blocks = await asyncio.get_running_loop().run_in_executor(
                        None, self.exporter, hashes
                    )
                    write_frame(writer, {"blocks": blocks})
                elif op == "ping":
                    write_frame(writer, {"ok": True})
                else:
                    write_frame(writer, {"error": f"bad op {op!r}"})
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            log.exception("peer block connection failed")
        finally:
            self._writers.discard(writer)
            writer.close()

    async def stop(self) -> None:
        from dynamo_tpu.store.wire import shutdown_server

        await shutdown_server(self._server, self._writers)
        self._server = None


class TcpPeerClient(PeerFetcher):
    """Blocking peer fetch for the engine thread: one short-lived
    connection per fetch batch, same framing as PeerBlockServer.
    (The engine thread has no event loop; onboarding already tolerates
    multi-ms G3/G4 reads, and a fetch replaces a whole re-prefill.)"""

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s

    def fetch(
        self, addr: str, seq_hashes: list[int]
    ) -> Optional[list[Optional[bytes]]]:
        from dynamo_tpu.store.wire import MAX_FRAME

        try:
            host, port_s = addr.rsplit(":", 1)
            with socket.create_connection(
                (host, int(port_s)), timeout=self.timeout_s
            ) as sock:
                body = msgpack.packb(
                    {"op": "fetch", "hashes": list(seq_hashes)},
                    use_bin_type=True,
                )
                sock.sendall(struct.pack("<I", len(body)) + body)
                header = self._recv_exact(sock, 4)
                (length,) = struct.unpack("<I", header)
                if length > MAX_FRAME:
                    raise ValueError(f"frame too large: {length}")
                resp = msgpack.unpackb(
                    self._recv_exact(sock, length), raw=False
                )
            blocks = resp.get("blocks")
            if blocks is None or len(blocks) != len(seq_hashes):
                return None
            return list(blocks)
        except (OSError, ValueError, msgpack.exceptions.UnpackException):
            log.warning("peer fetch from %s failed", addr, exc_info=True)
            return None

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed mid-frame")
            buf.extend(chunk)
        return bytes(buf)


# ---------------------------------------------------------------------------
# Pressure-driven tier lifecycle
# ---------------------------------------------------------------------------


@dataclass
class PressureConfig:
    """G2 host-pool watermarks (docs/kvbm.md "Watermark knobs").

    When occupancy crosses ``high_watermark``, blocks demote —
    popularity-weighted victims, least-touched first — until occupancy
    falls to ``low_watermark``. Hot shared blocks (touched at least
    ``hot_min_touches`` times, or held by no other worker while fleet-
    popular) go to the shared G4 bucket; cold private ones to local G3
    disk. The planner's "demote cold KV" rung scales both watermarks
    down via ``pressure_scale`` (LadderPolicy.fabric_pressure_scale)."""

    high_watermark: float = 0.90
    low_watermark: float = 0.70
    hot_min_touches: int = 2
    # demotions are engine-thread work (host RAM copy + disk/remote
    # write); bound one pump's share of it like the offload batch
    max_demotions_per_pump: int = 32


@dataclass
class _Resident:
    nbytes: int = 0
    touches: int = 0
    last_touch: float = 0.0
    seq: int = 0  # insertion order: deterministic LRU tie-break


@dataclass
class FabricStats:
    fleet_hits_peer: int = 0
    fleet_hits_bucket: int = 0
    fetched_blocks: int = 0
    fetch_failures: int = 0
    dangling_pruned: int = 0
    demoted_shared: int = 0
    demoted_disk: int = 0
    demoted_dropped: int = 0
    published_blocks: int = 0
    pruned_blocks: int = 0


class FleetKvFabric:
    """Per-worker glue between one KvBlockManager and the fleet: the
    catalog publisher, the peer-onboarding path, and the G2 pressure
    lifecycle. Engine-thread affine (see module docstring)."""

    # throttle for catalog snapshot refreshes run from the pump (same
    # cadence discipline as the manager's G4 index refresh)
    REFRESH_S = 5.0

    def __init__(
        self,
        catalog: FleetPrefixCatalog,
        fetcher: Optional[PeerFetcher] = None,
        pressure: Optional[PressureConfig] = None,
        clock: Optional[Clock] = None,
        addr: str = "",
        name: str = "",
    ):
        self.catalog = catalog
        self.fetcher = fetcher
        self.pressure = pressure or PressureConfig()
        self.clock = clock or SYSTEM
        self.addr = addr
        self.name = name or f"worker{catalog.worker_id}"
        self.manager: Any = None
        self.stats = FabricStats()
        self._pressure_scale = 1.0
        self._resident: dict[int, _Resident] = {}
        self._seq = 0
        self._last_refresh = 0.0
        self._provider_name = ""

    # -- wiring -------------------------------------------------------------
    def attach(self, manager: Any) -> None:
        """Bind to a KvBlockManager (calls back into
        ``manager.attach_fabric``) and register the debug provider."""
        self.manager = manager
        manager.attach_fabric(self)
        self._provider_name = f"kvfleet:{self.name}"
        register_debug_provider(self._provider_name, self.debug_stanza)

    def close(self) -> None:
        if self._provider_name:
            unregister_debug_provider(self._provider_name, self.debug_stanza)
            self._provider_name = ""

    def set_pressure_scale(self, scale: float) -> None:
        """The degradation ladder's "demote cold KV" rung: scale both
        watermarks down so cold KV demotes earlier under fleet stress
        (1.0 = rung 0 baseline)."""
        self._pressure_scale = max(0.05, min(1.0, float(scale)))

    # -- manager hooks (engine thread) ---------------------------------------
    def on_host_insert(self, seq_hash: int, nbytes: int) -> None:
        self._track_resident(seq_hash, nbytes)
        self.catalog.publish(seq_hash, TIER_HOST, nbytes, addr=self.addr)
        self.stats.published_blocks += 1

    def on_host_insert_many(self, seq_hashes: list[int], nbytes: int) -> None:
        """Batched G2 landing (one catalog round trip per pump). A block
        the same batch already LRU-evicted again is skipped — its
        on_host_evict already recorded the true tier."""
        m = self.manager
        live = [
            h for h in seq_hashes if m is None or m.host.contains(h)
        ]
        for h in live:
            self._track_resident(h, nbytes)
        self.catalog.publish_many(live, TIER_HOST, nbytes, addr=self.addr)
        self.stats.published_blocks += len(live)

    def _track_resident(self, seq_hash: int, nbytes: int) -> None:
        if seq_hash not in self._resident:
            self._seq += 1
            self._resident[seq_hash] = _Resident(
                nbytes=nbytes, touches=0,
                last_touch=self.clock.monotonic(), seq=self._seq,
            )

    def on_host_evict(self, seq_hash: int, dest: Optional[str]) -> None:
        """The host pool evicted a block; ``dest`` is where the demotion
        cascade routed it (g3/g4) or ``None`` when it was dropped. The
        catalog is retiered or pruned so an entry is NEVER dangling."""
        self._resident.pop(seq_hash, None)
        if dest in (TIER_DISK, TIER_SHARED):
            self.catalog.retier(seq_hash, dest)
        else:
            self.catalog.prune(seq_hash)
            self.stats.pruned_blocks += 1

    def on_tier_move(self, seq_hash: int, dest: str) -> None:
        """A lower-tier cascade moved the block (disk LRU -> bucket)."""
        self.catalog.retier(seq_hash, dest)

    def on_block_dropped(self, seq_hash: int) -> None:
        """A lower tier lost the block for good (disk LRU overflow with
        no bucket, remote GC). Prune our claim — and every g4 claim,
        since the shared bucket's loss invalidates all of them."""
        self.catalog.prune(seq_hash)
        for w, entry in list(
            (self.catalog._view.get(seq_hash) or {}).items()
        ):
            if entry.get("tier") == TIER_SHARED:
                self.catalog.prune(seq_hash, w)
        self.stats.pruned_blocks += 1

    def note_touch(self, seq_hashes: list[int]) -> None:
        now = self.clock.monotonic()
        for h in seq_hashes:
            meta = self._resident.get(h)
            if meta is not None:
                meta.touches += 1
                meta.last_touch = now

    def maybe_refresh(self) -> None:
        now = self.clock.monotonic()
        if now - self._last_refresh >= self.REFRESH_S:
            self._last_refresh = now
            try:
                self.catalog.refresh()
            except Exception:
                log.exception("fleet catalog refresh failed")

    # -- peer onboarding (engine thread, admission path) ---------------------
    def prefetch(self, seq_hashes: list[int]) -> int:
        """Land the longest possible leading run of ``seq_hashes`` in
        locally readable tiers: blocks missing everywhere locally but
        present in the catalog are fetched from the owning peer's host
        tier (wire plane) into G2, or adopted from the shared G4 bucket
        index. Returns the number of blocks made local. A failed fetch
        prunes the dangling entry and stops — the caller's onboard plan
        truncates there and the engine recomputes, never crashes."""
        m = self.manager
        if m is None:
            return 0
        fetched = 0
        # plan the leading run of fleet-only blocks
        for h in seq_hashes:
            if m.contains_local(h):
                continue
            locs = self.catalog.locations(
                h, exclude_worker=self.catalog.worker_id
            )
            if not locs:
                break
            if not self._fetch_one(h, locs):
                break
            fetched += 1
        if fetched:
            self.stats.fetched_blocks += fetched
            KVBM_FLEET_FETCHED_BLOCKS.inc(fetched)
        # request autopsy: the admission path parks the admitting seq's
        # rid in a thread-local (engine/scheduler.py around the onboard
        # hook) — stamp this prefetch's outcome onto that request's
        # timeline (one bounded event per admission, not per block)
        rid = autopsy.current_onboard_rid()
        if rid:
            autopsy.note_event(
                rid, "kvfleet_prefetch",
                blocks=fetched, hit=bool(fetched),
                requested=len(seq_hashes),
            )
        return fetched

    def _fetch_one(self, seq_hash: int, locs: list[tuple[int, dict]]) -> bool:
        m = self.manager
        expected = m.layout.block_bytes
        for worker, entry in locs:
            tier = entry.get("tier")
            if tier == TIER_SHARED:
                # shared-bucket copy: adopt into the local G4 index, the
                # existing onboard path reads it through RemoteTier
                if m.remote is not None and m.adopt_remote(seq_hash):
                    self.stats.fleet_hits_bucket += 1
                    KVBM_FLEET_HITS.labels("bucket").inc()
                    return True
                continue
            if tier == TIER_HOST and self.fetcher is not None:
                addr = entry.get("addr") or ""
                if not addr:
                    continue
                t0 = self.clock.monotonic()
                blocks = self.fetcher.fetch(addr, [seq_hash])
                KVBM_FLEET_FETCH_SECONDS.observe(self.clock.monotonic() - t0)
                raw = blocks[0] if blocks else None
                if raw is None or len(raw) != expected:
                    self.stats.fetch_failures += 1
                    continue
                m.insert_host_bytes(seq_hash, raw)
                self.stats.fleet_hits_peer += 1
                KVBM_FLEET_HITS.labels("peer").inc()
                return True
        # every advertised location failed: the entry is dangling —
        # prune so the next request goes straight to recompute
        for worker, _ in locs:
            self.catalog.prune(seq_hash, worker)
        self.stats.dangling_pruned += 1
        KVBM_FLEET_DANGLING.inc()
        return False

    # -- pressure lifecycle (engine thread, from pump) ------------------------
    def enforce_pressure(self) -> int:
        """Demote G2 victims while occupancy exceeds the (ladder-scaled)
        high watermark, until it reaches the low watermark or the pump
        budget runs out. Victims are popularity-weighted: least-touched,
        then oldest. Returns blocks demoted."""
        m = self.manager
        if m is None:
            return 0
        total = m.host.num_blocks
        if total <= 0:
            return 0
        high = self.pressure.high_watermark * self._pressure_scale
        low = self.pressure.low_watermark * self._pressure_scale
        if m.host.num_cached <= high * total:
            return 0
        target = int(low * total)
        victims = sorted(
            (h for h in self._resident if m.host.contains(h)),
            key=lambda h: (
                self._resident[h].touches,
                self._resident[h].last_touch,
                self._resident[h].seq,
            ),
        )
        demoted = 0
        for h in victims:
            if m.host.num_cached <= target:
                break
            if demoted >= self.pressure.max_demotions_per_pump:
                break
            dest = self._route_victim(h)
            routed = m.demote_block(h, dest)
            self._resident.pop(h, None)
            if routed == TIER_SHARED:
                self.catalog.retier(h, TIER_SHARED)
                self.stats.demoted_shared += 1
                KVBM_FLEET_DEMOTED_BLOCKS.labels("shared").inc()
            elif routed == TIER_DISK:
                self.catalog.retier(h, TIER_DISK)
                self.stats.demoted_disk += 1
                KVBM_FLEET_DEMOTED_BLOCKS.labels("disk").inc()
            else:
                self.catalog.prune(h)
                self.stats.demoted_dropped += 1
                KVBM_FLEET_DEMOTED_BLOCKS.labels("dropped").inc()
            demoted += 1
        return demoted

    def _route_victim(self, seq_hash: int) -> str:
        """Hot shared prefixes -> shared G4 bucket (they stay fetchable
        fleet-wide, surviving this worker); cold private ones -> local
        disk (cheap, private)."""
        m = self.manager
        meta = self._resident.get(seq_hash)
        hot = meta is not None and meta.touches >= self.pressure.hot_min_touches
        if hot and m.remote is not None:
            return TIER_SHARED
        if m.disk is not None:
            return TIER_DISK
        if m.remote is not None:
            # no disk tier: even cold blocks beat recompute if a shared
            # bucket exists
            return TIER_SHARED
        return "drop"

    def on_drain(self, max_blocks: Optional[int] = None) -> int:
        """Graceful-drain handoff (runtime/drain.py): make this worker's
        hot prefixes outlive it. Hot G2 residents are demoted into the
        shared bucket — the only tier that survives the process — and
        their catalog claims retiered, so a resume landing on a peer
        onboards from G4 instead of recomputing. Cold/private blocks
        stay put: during the drain window peers can still fetch them
        from our host tier, and the claims ride our store lease so they
        vanish cleanly at exit instead of dangling. Engine thread;
        ``max_blocks`` keeps the sweep deadline-bounded. Returns blocks
        demoted to the bucket."""
        m = self.manager
        if m is None or m.remote is None:
            return 0
        hot = sorted(
            (
                h for h, meta in self._resident.items()
                if m.host.contains(h)
                and meta.touches >= self.pressure.hot_min_touches
            ),
            key=lambda h: (
                -self._resident[h].touches,
                self._resident[h].last_touch,
            ),
        )
        demoted = 0
        for h in hot:
            if max_blocks is not None and demoted >= max_blocks:
                break
            routed = m.demote_block(h, TIER_SHARED)
            self._resident.pop(h, None)
            if routed == TIER_SHARED:
                self.catalog.retier(h, TIER_SHARED)
                self.stats.demoted_shared += 1
                KVBM_FLEET_DEMOTED_BLOCKS.labels("shared").inc()
                demoted += 1
            else:
                # demotion fell through (bucket write failed / block
                # raced out) — never leave the claim dangling
                self.catalog.prune(h)
                self.stats.pruned_blocks += 1
        return demoted

    # -- introspection --------------------------------------------------------
    def debug_stanza(self) -> dict:
        s = self.stats
        return {
            "addr": self.addr,
            "catalog": self.catalog.stats(),
            "resident_tracked": len(self._resident),
            "pressure_scale": self._pressure_scale,
            "watermarks": {
                "high": self.pressure.high_watermark * self._pressure_scale,
                "low": self.pressure.low_watermark * self._pressure_scale,
            },
            "fleet_hits": {
                "peer": s.fleet_hits_peer,
                "bucket": s.fleet_hits_bucket,
            },
            "fetched_blocks": s.fetched_blocks,
            "fetch_failures": s.fetch_failures,
            "dangling_pruned": s.dangling_pruned,
            "demoted": {
                "shared": s.demoted_shared,
                "disk": s.demoted_disk,
                "dropped": s.demoted_dropped,
            },
            "published_blocks": s.published_blocks,
            "pruned_blocks": s.pruned_blocks,
        }
