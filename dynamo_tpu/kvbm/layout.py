"""Block layout descriptors for the multi-tier KV block manager.

A layout describes the shape of one KV block as it travels between tiers
and between workers: a *packed block* is ``[2, L, block_size, Hkv, Dh]``
(K then V, all layers together) so a block is one contiguous unit that
can be DMA'd, memmapped, or shipped over the wire as raw bytes.

The descriptor is JSON-serializable: the disaggregation transfer agent
publishes it (≈ reference ``SerializedNixlBlockLayout``,
lib/llm/src/block_manager/layout/nixl.rs) so a peer can interpret a raw
block buffer without sharing Python objects. Unlike the reference's
stride-bearing CUDA layouts (lib/llm/src/block_manager/layout.rs:128-535),
TPU-side blocks live inside logical jax.Arrays — the layout only needs
logical dims + dtype, XLA owns physical tiling.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

import numpy as np


def resolve_dtype(name: str) -> np.dtype:
    """numpy dtype from name, including ml_dtypes names (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclass(frozen=True)
class BlockLayout:
    num_layers: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"  # numpy/ml_dtypes name

    @property
    def np_dtype(self) -> np.dtype:
        return resolve_dtype(self.dtype)

    @property
    def packed_shape(self) -> tuple[int, int, int, int, int]:
        """One packed block: [2(K,V), L, block_size, Hkv, Dh]."""
        return (2, self.num_layers, self.block_size, self.num_kv_heads, self.head_dim)

    @property
    def block_elems(self) -> int:
        n = 1
        for d in self.packed_shape:
            n *= d
        return n

    @property
    def block_bytes(self) -> int:
        return self.block_elems * self.np_dtype.itemsize

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "BlockLayout":
        return cls(**json.loads(s))

    @classmethod
    def for_model(cls, model_config, block_size: int, dtype: str = "bfloat16"):
        return cls(
            num_layers=model_config.num_hidden_layers,
            block_size=block_size,
            num_kv_heads=model_config.num_key_value_heads,
            head_dim=model_config.head_dim,
            dtype=dtype,
        )
