"""KvBlockManager: multi-tier KV cache orchestration.

Composes the engine's device allocator (G1, HBM) with host (G2) and disk
(G3) tier pools (reference: lib/llm/src/block_manager.rs:60-166 +
offload.rs:43-751). Responsibilities:

- **offload** (G1→G2): device blocks that become content-addressed are
  queued; ``pump()`` — called from the engine thread between steps —
  batches them through one jitted gather and inserts into the host pool.
  Single-threaded by design: the engine donates its cache buffers every
  step, so only the engine thread may touch them (the reference gets the
  same serialization from its progress-engine actor, block_manager/pool.rs).
- **demotion** (G2→G3): host-pool eviction writes through to disk.
- **onboarding** (G2/G3→G1): at admission, prompt blocks that miss in G1
  but hit in lower tiers are copied into freshly allocated device blocks
  via one jitted scatter, extending the prefix-cache hit (reference:
  offload.rs onboarding + docs/architecture.md:91-96 — the +40% TTFT
  system-memory-tier win this tier structure exists for).
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.kvbm.layout import BlockLayout
from dynamo_tpu.kvbm.pool import TierPool
from dynamo_tpu.kvbm.storage import DiskBlockStorage, HostBlockStorage

log = logging.getLogger("dynamo_tpu.kvbm")

GatherFn = Callable[[list[int]], np.ndarray]  # device block ids -> packed
ScatterFn = Callable[[list[int], np.ndarray], None]  # packed -> device blocks
ResolveFn = Callable[[int], Optional[int]]  # seq_hash -> device block id


@dataclass
class KvbmConfig:
    host_num_blocks: int = 0
    disk_num_blocks: int = 0
    disk_path: str = ""
    offload_batch: int = 16  # max blocks gathered per pump


@dataclass
class KvbmStats:
    offloaded_blocks: int = 0
    onboarded_blocks: int = 0
    demoted_blocks: int = 0
    host_cached_blocks: int = 0
    disk_cached_blocks: int = 0


class KvBlockManager:
    def __init__(
        self,
        config: KvbmConfig,
        layout: BlockLayout,
        gather_fn: GatherFn,
        scatter_fn: ScatterFn,
        resolve_fn: ResolveFn,
    ):
        self.config = config
        if config.host_num_blocks <= 0:
            raise ValueError("host_num_blocks must be positive")
        if config.offload_batch <= 0:
            raise ValueError("offload_batch must be positive")
        # an offload batch larger than the host tier would just thrash it
        # (clamped copy: never mutate the caller's config)
        self._offload_batch = min(config.offload_batch, config.host_num_blocks)
        self.layout = layout
        self._gather = gather_fn
        self._scatter = scatter_fn
        self._resolve = resolve_fn
        self.disk: Optional[TierPool] = None
        if config.disk_num_blocks > 0:
            self.disk = TierPool(
                DiskBlockStorage(layout, config.disk_num_blocks, config.disk_path)
            )
        self.host = TierPool(
            HostBlockStorage(layout, config.host_num_blocks),
            on_evict=self._demote,
        )
        # offload candidates: seq_hash -> device block id at commit time
        self._pending: OrderedDict[int, int] = OrderedDict()
        self.stats = KvbmStats()

    # -- event intake (engine thread) -------------------------------------
    def on_block_committed(self, seq_hash: int, device_block: int) -> None:
        if self.host.contains(seq_hash):
            return
        self._pending[seq_hash] = device_block

    # -- offload pump (engine thread, between steps) -----------------------
    def pump(self) -> int:
        """Offload up to ``offload_batch`` pending blocks; returns count."""
        if not self._pending:
            return 0
        batch: list[tuple[int, int]] = []
        while self._pending and len(batch) < self._offload_batch:
            h, bid = self._pending.popitem(last=False)
            # the device block may have been evicted/reassigned since commit
            if self._resolve(h) == bid and not self.host.contains(h):
                batch.append((h, bid))
        if not batch:
            return 0
        hashes = [h for h, _ in batch]
        ids = [b for _, b in batch]
        packed = self._gather(ids)
        self.host.insert_many(hashes, packed)
        self.stats.offloaded_blocks += len(batch)
        self._refresh_gauges()
        return len(batch)

    @property
    def pending_offloads(self) -> int:
        return len(self._pending)

    def _demote(self, seq_hash: int, data: np.ndarray) -> None:
        if self.disk is not None:
            self.disk.insert(seq_hash, data)
            self.stats.demoted_blocks += 1

    # -- onboarding (engine thread, at admission) --------------------------
    def match_offloaded(self, seq_hashes: list[int]) -> int:
        """Leading consecutive blocks available in G2/G3 (no copies)."""
        n = 0
        for h in seq_hashes:
            if self.host.contains(h) or (self.disk is not None and self.disk.contains(h)):
                n += 1
            else:
                break
        return n

    def onboard(self, seq_hashes: list[int], device_blocks: list[int]) -> int:
        """Copy the longest available prefix of ``seq_hashes`` from lower
        tiers into the given (freshly allocated) device blocks. Returns the
        number of blocks onboarded."""
        # plan first (membership only — no reads, no promotions yet, so the
        # plan can't be invalidated by eviction cascades mid-loop)
        host_rows: list[tuple[int, int]] = []  # (row index, hash)
        disk_rows: list[tuple[int, int]] = []
        limit = min(len(seq_hashes), len(device_blocks))
        n = 0
        for i in range(limit):
            h = seq_hashes[i]
            if self.host.contains(h):
                host_rows.append((i, h))
            elif self.disk is not None and self.disk.contains(h):
                disk_rows.append((i, h))
            else:
                break
            n += 1
        if n == 0:
            return 0
        rows = np.zeros((n, *self.layout.packed_shape), self.layout.np_dtype)
        if host_rows:
            data = self.host.read([h for _, h in host_rows])  # one batched read
            for j, (i, _) in enumerate(host_rows):
                rows[i] = data[j]
        disk_data = None
        if disk_rows:
            assert self.disk is not None
            disk_data = self.disk.read([h for _, h in disk_rows])
            for j, (i, _) in enumerate(disk_rows):
                rows[i] = disk_data[j]
        self._scatter(device_blocks[:n], rows)
        # promote disk hits into the host tier AFTER all reads and the
        # scatter: promotion may trigger host->disk demotion evictions
        for j, (_, h) in enumerate(disk_rows):
            self.host.insert(h, disk_data[j])
        self.stats.onboarded_blocks += n
        self._refresh_gauges()
        return n

    def _refresh_gauges(self) -> None:
        self.stats.host_cached_blocks = self.host.num_cached
        self.stats.disk_cached_blocks = self.disk.num_cached if self.disk else 0

    def close(self) -> None:
        if self.disk is not None:
            self.disk.storage.close()
