"""KvBlockManager: multi-tier KV cache orchestration.

Composes the engine's device allocator (G1, HBM) with host (G2) and disk
(G3) tier pools (reference: lib/llm/src/block_manager.rs:60-166 +
offload.rs:43-751). Responsibilities:

- **offload** (G1→G2): device blocks that become content-addressed are
  queued; ``pump()`` — called from the engine thread between steps —
  batches them through one jitted gather and inserts into the host pool.
  Single-threaded by design: the engine donates its cache buffers every
  step, so only the engine thread may touch them (the reference gets the
  same serialization from its progress-engine actor, block_manager/pool.rs).
- **demotion** (G2→G3): host-pool eviction writes through to disk.
- **onboarding** (G2/G3→G1): at admission, prompt blocks that miss in G1
  but hit in lower tiers are copied into freshly allocated device blocks
  via one jitted scatter, extending the prefix-cache hit (reference:
  offload.rs onboarding + docs/architecture.md:91-96 — the +40% TTFT
  system-memory-tier win this tier structure exists for).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from dynamo_tpu.kvbm.layout import BlockLayout
from dynamo_tpu.kvbm.pool import TierPool
from dynamo_tpu.kvbm.storage import DiskBlockStorage, HostBlockStorage
from dynamo_tpu.telemetry.instruments import (
    KVBM_OFFLOADED_BLOCKS,
    KVBM_ONBOARDED_BLOCKS,
)
from dynamo_tpu.utils.clock import SYSTEM, Clock

log = logging.getLogger("dynamo_tpu.kvbm")

GatherFn = Callable[[list[int]], np.ndarray]  # device block ids -> packed
ScatterFn = Callable[[list[int], np.ndarray], None]  # packed -> device blocks
ResolveFn = Callable[[int], Optional[int]]  # seq_hash -> device block id


@dataclass
class KvbmConfig:
    host_num_blocks: int = 0
    disk_num_blocks: int = 0
    disk_path: str = ""
    offload_batch: int = 16  # max blocks gathered per pump
    # G4: remote object-storage tier (bucket in the coordinator store's
    # object plane; "" disables). Shared across workers — blocks another
    # worker demoted are onboardable here after refresh_remote_index().
    remote_bucket: str = ""


@dataclass
class KvbmStats:
    offloaded_blocks: int = 0
    onboarded_blocks: int = 0
    demoted_blocks: int = 0
    host_cached_blocks: int = 0
    disk_cached_blocks: int = 0
    remote_put_blocks: int = 0
    remote_got_blocks: int = 0


class SyncObjectStore:
    """Blocking object-plane facade the G4 tier runs on (the engine
    thread has no event loop; the coordinator client is async — see
    StoreObjectAdapter in dynamo_tpu/kvbm/remote.py for the bridge)."""

    def put(self, key: str, data: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:  # pragma: no cover
        raise NotImplementedError

    def get_many(self, keys: list[str]) -> list[Optional[bytes]]:
        """Batched fetch; backends override to overlap the round trips
        (one blocking wait instead of one per block)."""
        return [self.get(k) for k in keys]

    def list_keys(self) -> list[str]:  # pragma: no cover
        raise NotImplementedError


class RemoteTier:
    """G4: content-addressed KV blocks in remote object storage
    (reference: block_manager.rs CacheLevel::G4 — remote storage behind
    NIXL; here the coordinator store's object plane, so the tier is
    shared by every worker of the model).

    Unlike G2/G3 the capacity is remote and unbounded from the worker's
    view, so there is no LRU/slot pool — keys ARE the sequence hashes.
    ``contains`` consults a local index only (no network on the
    admission path); ``refresh_remote_index`` pulls the bucket's key
    list to discover blocks other workers demoted."""

    def __init__(self, objects: SyncObjectStore, layout: BlockLayout):
        self.objects = objects
        self.layout = layout
        self._known: set[int] = set()

    @staticmethod
    def _key(seq_hash: int) -> str:
        return f"{seq_hash:016x}"

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._known

    @property
    def num_known(self) -> int:
        return len(self._known)

    def insert(self, seq_hash: int, data: np.ndarray) -> None:
        if seq_hash in self._known:
            return
        self.objects.put(self._key(seq_hash), np.ascontiguousarray(data).tobytes())
        self._known.add(seq_hash)

    def read(self, seq_hashes: list[int]) -> Optional[np.ndarray]:
        """All-or-nothing batched read (a half-onboarded prefix is not
        usable past the first gap anyway). NEVER raises: a flaky remote
        reads as a miss — the caller truncates, it must not take the
        whole kvbm down (engine._safe_onboard disables tiers on error)."""
        try:
            raws = self.objects.get_many([self._key(h) for h in seq_hashes])
        except Exception:
            import logging

            logging.getLogger("dynamo_tpu.kvbm").exception("G4 read failed")
            return None
        out = np.zeros((len(seq_hashes), *self.layout.packed_shape),
                       self.layout.np_dtype)
        for i, (h, raw) in enumerate(zip(seq_hashes, raws)):
            if raw is None or len(raw) != self.layout.block_bytes:
                self._known.discard(h)
                return None
            out[i] = np.frombuffer(raw, self.layout.np_dtype).reshape(
                self.layout.packed_shape
            )
        return out

    def refresh_remote_index(self) -> int:
        """Adopt keys other workers wrote; returns newly-known count."""
        before = len(self._known)
        for key in self.objects.list_keys():
            try:
                self._known.add(int(key, 16))
            except ValueError:
                continue
        return len(self._known) - before


class KvBlockManager:
    def __init__(
        self,
        config: KvbmConfig,
        layout: BlockLayout,
        gather_fn: GatherFn,
        scatter_fn: ScatterFn,
        resolve_fn: ResolveFn,
        remote_objects: Optional[SyncObjectStore] = None,
        clock: Optional[Clock] = None,
    ):
        # injectable clock (utils/clock.py; DL009 vocabulary): pump()'s
        # G4 refresh throttle reads time through this seam, so tests and
        # the fleet simulator can drive the refresh deterministically
        self.clock = clock or SYSTEM
        self.config = config
        if config.host_num_blocks <= 0:
            raise ValueError("host_num_blocks must be positive")
        if config.offload_batch <= 0:
            raise ValueError("offload_batch must be positive")
        # an offload batch larger than the host tier would just thrash it
        # (clamped copy: never mutate the caller's config)
        self._offload_batch = min(config.offload_batch, config.host_num_blocks)
        self.layout = layout
        self._gather = gather_fn
        self._scatter = scatter_fn
        self._resolve = resolve_fn
        self.remote: Optional[RemoteTier] = None
        if config.remote_bucket and remote_objects is not None:
            self.remote = RemoteTier(remote_objects, layout)
        self.disk: Optional[TierPool] = None
        if config.disk_num_blocks > 0:
            self.disk = TierPool(
                DiskBlockStorage(layout, config.disk_num_blocks, config.disk_path),
                on_evict=self._on_disk_evict,
            )
        self.host = TierPool(
            HostBlockStorage(layout, config.host_num_blocks),
            on_evict=self._demote,
        )
        # offload candidates: seq_hash -> device block id at commit time
        self._pending: OrderedDict[int, int] = OrderedDict()
        self._last_remote_refresh = 0.0
        self.stats = KvbmStats()
        # fleet KV fabric (kvbm/fabric.py FleetKvFabric), late-bound via
        # attach_fabric(). The host-tier lock exists for it: the peer
        # block server exports G2 blocks from the event loop while the
        # engine thread mutates the pool, so every host-pool touch that
        # moves data goes through this lock (uncontended when no fabric
        # is attached — a few ns per pump, not per step).
        self.fabric: Any = None
        self._host_lock = threading.Lock()

    def attach_fabric(self, fabric: Any) -> None:
        """Bind the fleet fabric (idempotent; engine thread or setup
        thread, before serving). The fabric's hooks then run inside
        pump()/onboard() on the engine thread."""
        self.fabric = fabric

    def attach_remote(self, objects: SyncObjectStore) -> None:
        """Late-bind the G4 tier (the worker's store connection usually
        comes up after the engine). Idempotent. MUST NOT be called on
        the event loop a StoreObjectAdapter schedules onto — the initial
        index refresh blocks on that loop (the CLI calls this via
        run_in_executor)."""
        if self.remote is None and self.config.remote_bucket:
            self.remote = RemoteTier(objects, self.layout)
            try:
                self.remote.refresh_remote_index()
            except Exception:
                log.exception("initial G4 index refresh failed")

    # -- event intake (engine thread) -------------------------------------
    def on_block_committed(self, seq_hash: int, device_block: int) -> None:
        if self.host.contains(seq_hash):
            return
        self._pending[seq_hash] = device_block

    REMOTE_REFRESH_S = 5.0

    # -- offload pump (engine thread, between steps) -----------------------
    def pump(self, max_blocks: Optional[int] = None) -> int:
        """Offload up to ``max_blocks`` (default ``offload_batch``)
        pending blocks; returns count. ``max_blocks=0`` runs only the
        periodic G4 index refresh — the engine uses it to keep the
        refresh alive while serving is busy (each offloaded block is a
        multi-MB device->host transfer on the engine thread; measured on
        the tunneled chip, unthrottled write-through offload collapsed
        multi-turn serving 16x — benchmarks/RESULTS.md)."""
        if self.remote is not None:
            # periodic G4 index refresh: discover blocks OTHER workers
            # demoted since we attached (the cross-worker tier benefit)
            now = self.clock.monotonic()
            if now - self._last_remote_refresh >= self.REMOTE_REFRESH_S:
                self._last_remote_refresh = now
                try:
                    self.remote.refresh_remote_index()
                except Exception:
                    log.exception("G4 index refresh failed")
        if self.fabric is not None:
            # catalog snapshot refresh rides the same pump cadence as
            # the G4 index (throttled inside the fabric)
            self.fabric.maybe_refresh()
        if not self._pending or max_blocks == 0:
            self._enforce_fabric_pressure()
            return 0
        cap = self._offload_batch if max_blocks is None else min(
            max_blocks, self._offload_batch
        )
        batch: list[tuple[int, int]] = []
        while self._pending and len(batch) < cap:
            h, bid = self._pending.popitem(last=False)
            # the device block may have been evicted/reassigned since commit
            if self._resolve(h) == bid and not self.host.contains(h):
                batch.append((h, bid))
        if not batch:
            self._enforce_fabric_pressure()
            return 0
        hashes = [h for h, _ in batch]
        ids = [b for _, b in batch]
        packed = self._gather(ids)
        with self._host_lock:
            self.host.insert_many(hashes, packed)
        if self.fabric is not None:
            # publish the landed blocks to the fleet catalog (batched:
            # one store round trip per pump, not per block)
            self.fabric.on_host_insert_many(hashes, self.layout.block_bytes)
        self.stats.offloaded_blocks += len(batch)
        KVBM_OFFLOADED_BLOCKS.inc(len(batch))
        self._enforce_fabric_pressure()
        self._refresh_gauges()
        return len(batch)

    def _enforce_fabric_pressure(self) -> None:
        """Watermark-driven G2 demotion, once per pump (the fabric
        no-ops below the high watermark). A broken fabric must degrade
        to single-worker behavior, not kill the offload pump."""
        if self.fabric is None:
            return
        try:
            self.fabric.enforce_pressure()
        except Exception:
            log.exception("fleet pressure enforcement failed")

    @property
    def pending_offloads(self) -> int:
        return len(self._pending)

    def _demote(self, seq_hash: int, data: np.ndarray) -> None:
        # destination strings are the catalog tier names
        # (fabric.TIER_DISK / TIER_SHARED): the fabric retiers or prunes
        # the hash's catalog entry so it is never dangling
        dest: Optional[str] = None
        if self.disk is not None:
            self.disk.insert(seq_hash, data)
            self.stats.demoted_blocks += 1
            dest = "g3"
        elif self.remote is not None:
            # no G3: the cascade skips straight to remote
            if self._demote_remote(seq_hash, data):
                dest = "g4"
        if self.fabric is not None:
            self.fabric.on_host_evict(seq_hash, dest)

    def _demote_remote(self, seq_hash: int, data: np.ndarray) -> bool:
        if self.remote is None:
            return False
        try:
            self.remote.insert(seq_hash, data)
            self.stats.demoted_blocks += 1
            self.stats.remote_put_blocks += 1
            return True
        except Exception:
            # remote tier is best-effort cache: a flaky store must not
            # take the engine's offload pump down
            log.exception("G4 remote put failed for %x", seq_hash)
            return False

    def _on_disk_evict(self, seq_hash: int, data: np.ndarray) -> None:
        """G3's eviction cascade (disk LRU overflow -> remote)."""
        landed = self._demote_remote(seq_hash, data)
        if self.fabric is not None:
            if landed:
                self.fabric.on_tier_move(seq_hash, "g4")
            else:
                self.fabric.on_block_dropped(seq_hash)

    # -- onboarding (engine thread, at admission) --------------------------
    def match_offloaded(self, seq_hashes: list[int]) -> int:
        """Leading consecutive blocks available in G2/G3/G4 (no copies,
        no network — G4 membership is the local index)."""
        n = 0
        for h in seq_hashes:
            if (
                self.host.contains(h)
                or (self.disk is not None and self.disk.contains(h))
                or (self.remote is not None and self.remote.contains(h))
            ):
                n += 1
            else:
                break
        return n

    def onboard(self, seq_hashes: list[int], device_blocks: list[int]) -> int:
        """Copy the longest available prefix of ``seq_hashes`` from lower
        tiers into the given (freshly allocated) device blocks. Returns the
        number of blocks onboarded."""
        if self.fabric is not None:
            # fleet prefetch: blocks missing every local tier but hitting
            # the fleet catalog are pulled from the owning peer's host
            # tier / adopted from the shared bucket FIRST, so the plan
            # below sees them as local hits (a fetch replaces a whole
            # re-prefill; failures degrade to recompute, never raise)
            try:
                self.fabric.prefetch(seq_hashes[: len(device_blocks)])
            except Exception:
                log.exception("fleet prefetch failed")
        # plan first (membership only — no reads, no promotions yet, so the
        # plan can't be invalidated by eviction cascades mid-loop)
        host_rows: list[tuple[int, int]] = []  # (row index, hash)
        disk_rows: list[tuple[int, int]] = []
        remote_rows: list[tuple[int, int]] = []
        limit = min(len(seq_hashes), len(device_blocks))
        n = 0
        for i in range(limit):
            h = seq_hashes[i]
            if self.host.contains(h):
                host_rows.append((i, h))
            elif self.disk is not None and self.disk.contains(h):
                disk_rows.append((i, h))
            elif self.remote is not None and self.remote.contains(h):
                remote_rows.append((i, h))
            else:
                break
            n += 1
        # G4 reads can fail (remote eviction, another namespace's GC):
        # fetch BEFORE committing to n so a miss just truncates the
        # onboarded prefix at the first remote row
        remote_data = None
        if remote_rows:
            assert self.remote is not None
            remote_data = self.remote.read([h for _, h in remote_rows])
            if remote_data is None:
                if self.fabric is not None:
                    # the G4 read dropped whatever keys the bucket lost
                    # from the local index; prune their catalog claims so
                    # the fleet stops advertising them (never dangling)
                    for _, h in remote_rows:
                        if not self.remote.contains(h):
                            self.fabric.on_block_dropped(h)
                n = remote_rows[0][0]
                remote_rows = []
        if n == 0:
            return 0
        host_rows = [(i, h) for i, h in host_rows if i < n]
        disk_rows = [(i, h) for i, h in disk_rows if i < n]
        rows = np.zeros((n, *self.layout.packed_shape), self.layout.np_dtype)
        if host_rows:
            with self._host_lock:
                data = self.host.read([h for _, h in host_rows])  # one batched read
            for j, (i, _) in enumerate(host_rows):
                rows[i] = data[j]
        disk_data = None
        if disk_rows:
            assert self.disk is not None
            disk_data = self.disk.read([h for _, h in disk_rows])
            for j, (i, _) in enumerate(disk_rows):
                rows[i] = disk_data[j]
        for j, (i, _) in enumerate(remote_rows):
            rows[i] = remote_data[j]
        self._scatter(device_blocks[:n], rows)
        # promote lower-tier hits into the host tier AFTER all reads and
        # the scatter: promotion may trigger demotion-eviction cascades
        promoted: list[int] = []
        with self._host_lock:
            for j, (_, h) in enumerate(disk_rows):
                self.host.insert(h, disk_data[j])
                promoted.append(h)
            for j, (_, h) in enumerate(remote_rows):
                self.host.insert(h, remote_data[j])
                self.stats.remote_got_blocks += 1
                promoted.append(h)
        if self.fabric is not None:
            if promoted:
                self.fabric.on_host_insert_many(
                    promoted, self.layout.block_bytes
                )
            # popularity signal for the pressure lifecycle's
            # victim selection: every onboarded block was just used
            self.fabric.note_touch(seq_hashes[:n])
        self.stats.onboarded_blocks += n
        KVBM_ONBOARDED_BLOCKS.inc(n)
        self._refresh_gauges()
        return n

    # -- fleet fabric surface (kvbm/fabric.py) ------------------------------
    def contains_local(self, seq_hash: int) -> bool:
        """Membership across every locally readable tier (G2/G3/G4
        index) — what the fleet prefetch skips past."""
        return (
            self.host.contains(seq_hash)
            or (self.disk is not None and self.disk.contains(seq_hash))
            or (self.remote is not None and self.remote.contains(seq_hash))
        )

    def adopt_remote(self, seq_hash: int) -> bool:
        """Adopt a catalog-advertised shared-bucket block into the local
        G4 index without waiting for the periodic list refresh; the
        existing onboard path then reads it through RemoteTier (and
        un-adopts on a failed read)."""
        if self.remote is None:
            return False
        self.remote._known.add(seq_hash)
        return True

    def insert_host_bytes(self, seq_hash: int, raw: bytes) -> None:
        """Land one peer-fetched packed block in the host tier (engine
        thread; the fleet prefetch path). Publishes to the catalog like
        any other G2 landing."""
        block = np.frombuffer(raw, self.layout.np_dtype).reshape(
            self.layout.packed_shape
        )
        with self._host_lock:
            self.host.insert(seq_hash, block)
        if self.fabric is not None:
            self.fabric.on_host_insert(seq_hash, self.layout.block_bytes)

    def export_host_blocks(self, seq_hashes: list[int]) -> list[Optional[bytes]]:
        """Read G2 blocks as raw bytes for a peer (called from the peer
        block server's executor thread — the host lock is the handoff
        with the engine thread's mutation paths). Misses are None."""
        out: list[Optional[bytes]] = []
        with self._host_lock:
            for h in seq_hashes:
                if self.host.contains(h):
                    out.append(
                        np.ascontiguousarray(self.host.read([h])[0]).tobytes()
                    )
                else:
                    out.append(None)
        return out

    def demote_block(self, seq_hash: int, dest: str) -> Optional[str]:
        """Explicitly demote one G2 block (the pressure lifecycle's
        routed eviction — bypasses the LRU cascade so hot shared blocks
        can go to the shared bucket while cold ones go to disk).
        Returns where the block actually landed ("g3"/"g4") or None when
        it was dropped; the caller owns the catalog update."""
        with self._host_lock:
            if not self.host.contains(seq_hash):
                return None
            data = self.host.read([seq_hash])[0]
            self.host.evict(seq_hash)  # index-only: no on_evict cascade
        if dest == "g4" and self.remote is not None:
            if self._demote_remote(seq_hash, data):
                return "g4"
            dest = "g3"  # remote refused: fall back to disk
        if dest == "g3" and self.disk is not None:
            self.disk.insert(seq_hash, data)
            self.stats.demoted_blocks += 1
            return "g3"
        return None

    def _refresh_gauges(self) -> None:
        self.stats.host_cached_blocks = self.host.num_cached
        self.stats.disk_cached_blocks = self.disk.num_cached if self.disk else 0

    def close(self) -> None:
        if self.fabric is not None:
            try:
                self.fabric.close()
            except Exception:  # pragma: no cover - shutdown is best-effort
                log.exception("fleet fabric close failed")
        if self.disk is not None:
            self.disk.storage.close()
