"""Content-addressed block pool over a storage tier.

A tier pool is a sequence-hash → block cache with LRU eviction — the
host/disk analogue of the device allocator's inactive pool (reference:
lib/llm/src/block_manager/pool/inactive.rs — FIFO VecDeque + seq-hash
dedupe map + priority eviction order). Offloaded tiers hold no *active*
(ref-counted) blocks: every block is a cached copy whose ground truth is
re-computable, so the pool is a pure cache and eviction is always legal.

``on_evict`` is the demotion hook: when G2 evicts, the manager writes the
block down to G3 (reference offload cascade: block_manager/offload.rs).

Bookkeeping (hash→block map, free list, LRU order, victim selection) runs
in the native C++ tier when built (native/src/lru.cc); data movement stays
in the storage backend. ``_PyLruIndex`` is the drop-in pure-Python
fallback with identical semantics.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.kvbm.storage import BlockStorage
from dynamo_tpu.native import LRU_EVICTED, LRU_INSERTED, LRU_PRESENT

EvictFn = Callable[[int, np.ndarray], None]  # (seq_hash, packed_block)

PRESENT, INSERTED, EVICTED = LRU_PRESENT, LRU_INSERTED, LRU_EVICTED


class _PyLruIndex:
    """Pure-Python mirror of native.NativeLru (same insert/evict contract)."""

    def __init__(self, num_blocks: int) -> None:
        self._free: list[int] = list(range(num_blocks))
        self._map: dict[int, int] = {}
        self._lru: OrderedDict[int, None] = OrderedDict()  # first = evict first

    def lookup(self, seq_hash: int, touch: bool = True) -> Optional[int]:
        bid = self._map.get(seq_hash)
        if bid is not None and touch:
            self._lru.move_to_end(seq_hash)
        return bid

    def insert(self, seq_hash: int) -> tuple[int, int, Optional[tuple[int, int]]]:
        if seq_hash in self._map:
            self._lru.move_to_end(seq_hash)
            return PRESENT, self._map[seq_hash], None
        victim = None
        code = INSERTED
        if not self._free:
            v_hash, _ = self._lru.popitem(last=False)
            v_block = self._map.pop(v_hash)
            self._free.append(v_block)
            victim = (v_hash, v_block)
            code = EVICTED
        bid = self._free.pop()
        self._map[seq_hash] = bid
        self._lru[seq_hash] = None
        return code, bid, victim

    def evict(self, seq_hash: int) -> Optional[int]:
        bid = self._map.pop(seq_hash, None)
        if bid is None:
            return None
        self._lru.pop(seq_hash, None)
        self._free.append(bid)
        return bid

    def __len__(self) -> int:
        return len(self._map)

    def match_prefix(self, seq_hashes: list[int]) -> int:
        n = 0
        for h in seq_hashes:
            if h in self._map:
                n += 1
            else:
                break
        return n


def _make_index(num_blocks: int, use_native: Optional[bool]):
    from dynamo_tpu import native

    if use_native is False or (use_native is None and not native.is_available()):
        return _PyLruIndex(num_blocks)
    return native.NativeLru(num_blocks)


class TierPool:
    def __init__(
        self,
        storage: BlockStorage,
        on_evict: Optional[EvictFn] = None,
        use_native: Optional[bool] = None,
    ):
        self.storage = storage
        self.on_evict = on_evict
        self._idx = _make_index(storage.num_blocks, use_native)

    # -- introspection ----------------------------------------------------
    @property
    def num_cached(self) -> int:
        return len(self._idx)

    @property
    def num_blocks(self) -> int:
        return self.storage.num_blocks

    def contains(self, seq_hash: int) -> bool:
        return self._idx.lookup(seq_hash, touch=False) is not None

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Leading consecutive hits (no side effects)."""
        return self._idx.match_prefix(seq_hashes)

    # -- data path --------------------------------------------------------
    def insert(self, seq_hash: int, data: np.ndarray) -> None:
        """Cache one packed block, evicting LRU if full."""
        code, bid, victim = self._idx.insert(seq_hash)
        if code == PRESENT:
            return
        try:
            if code == EVICTED and self.on_evict is not None:
                # the victim's storage is reused for the new block, so demote
                # its data before overwriting
                v_hash, v_block = victim  # type: ignore[misc]
                self.on_evict(v_hash, self.storage.read_blocks([v_block])[0])
            self.storage.write_blocks([bid], data[None])
        except BaseException:
            # don't leave the index pointing at a block whose write failed:
            # a later read would return another sequence's stale KV bytes
            self._idx.evict(seq_hash)
            raise

    def insert_many(self, seq_hashes: list[int], data: np.ndarray) -> None:
        # write each block as it is admitted: if the batch overflows the
        # tier, a same-batch victim must already hold real data when the
        # demotion hook reads it
        for i, h in enumerate(seq_hashes):
            self.insert(h, data[i])

    def read(self, seq_hashes: list[int]) -> np.ndarray:
        """Read cached blocks (all must be present); refreshes LRU."""
        ids = []
        for h in seq_hashes:
            bid = self._idx.lookup(h, touch=True)
            if bid is None:
                raise KeyError(seq_hash_missing(h))
            ids.append(bid)
        return self.storage.read_blocks(ids)

    def evict(self, seq_hash: int) -> None:
        self._idx.evict(seq_hash)


def seq_hash_missing(h: int) -> str:
    return f"seq_hash {h:#x} not cached in this tier"
