"""Content-addressed block pool over a storage tier.

A tier pool is a sequence-hash → block cache with LRU eviction — the
host/disk analogue of the device allocator's inactive pool (reference:
lib/llm/src/block_manager/pool/inactive.rs — FIFO VecDeque + seq-hash
dedupe map + priority eviction order). Offloaded tiers hold no *active*
(ref-counted) blocks: every block is a cached copy whose ground truth is
re-computable, so the pool is a pure cache and eviction is always legal.

``on_evict`` is the demotion hook: when G2 evicts, the manager writes the
block down to G3 (reference offload cascade: block_manager/offload.rs).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.kvbm.storage import BlockStorage

EvictFn = Callable[[int, np.ndarray], None]  # (seq_hash, packed_block)


class TierPool:
    def __init__(self, storage: BlockStorage, on_evict: Optional[EvictFn] = None):
        self.storage = storage
        self.on_evict = on_evict
        self._free: list[int] = list(range(storage.num_blocks))
        self._hash_to_block: dict[int, int] = {}
        # LRU order over cached hashes: first = evict first
        self._lru: OrderedDict[int, None] = OrderedDict()

    # -- introspection ----------------------------------------------------
    @property
    def num_cached(self) -> int:
        return len(self._hash_to_block)

    @property
    def num_blocks(self) -> int:
        return self.storage.num_blocks

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._hash_to_block

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Leading consecutive hits (no side effects)."""
        n = 0
        for h in seq_hashes:
            if h in self._hash_to_block:
                n += 1
            else:
                break
        return n

    # -- data path --------------------------------------------------------
    def insert(self, seq_hash: int, data: np.ndarray) -> None:
        """Cache one packed block, evicting LRU if full."""
        if seq_hash in self._hash_to_block:
            self._lru.move_to_end(seq_hash)
            return
        if not self._free:
            self._evict_one()
        bid = self._free.pop()
        self.storage.write_blocks([bid], data[None])
        self._hash_to_block[seq_hash] = bid
        self._lru[seq_hash] = None

    def insert_many(self, seq_hashes: list[int], data: np.ndarray) -> None:
        # write each block as it is admitted: if the batch overflows the
        # tier, a same-batch victim must already hold real data when the
        # demotion hook reads it
        for i, h in enumerate(seq_hashes):
            self.insert(h, data[i])

    def read(self, seq_hashes: list[int]) -> np.ndarray:
        """Read cached blocks (all must be present); refreshes LRU."""
        ids = []
        for h in seq_hashes:
            ids.append(self._hash_to_block[h])
            self._lru.move_to_end(h)
        return self.storage.read_blocks(ids)

    def evict(self, seq_hash: int) -> None:
        bid = self._hash_to_block.pop(seq_hash, None)
        if bid is None:
            return
        self._lru.pop(seq_hash, None)
        self._free.append(bid)

    def _evict_one(self) -> None:
        victim, _ = self._lru.popitem(last=False)
        bid = self._hash_to_block.pop(victim)
        if self.on_evict is not None:
            self.on_evict(victim, self.storage.read_blocks([bid])[0])
        self._free.append(bid)
