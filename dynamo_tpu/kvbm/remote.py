"""G4 remote-tier bridge: coordinator object plane ↔ engine thread.

The engine thread (which owns the KVBM pump) has no event loop; the
coordinator store client is async. ``StoreObjectAdapter`` schedules the
client's object-plane calls onto the runtime's loop and blocks the
engine thread on the result — exactly the pattern the reference uses
for its remote tier behind blocking NIXL calls
(reference: block_manager.rs CacheLevel::G4, block/transfer/nixl.rs).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import time
from typing import Any, Optional

from dynamo_tpu.kvbm.manager import SyncObjectStore
from dynamo_tpu.telemetry.instruments import KVBM_REMOTE_TIMEOUTS

log = logging.getLogger("dynamo_tpu.kvbm.remote")


class StoreRoundTripTimeout(TimeoutError):
    """A blocking store round trip from the engine thread hit its
    deadline. Carries the operation context a bare
    ``concurrent.futures.TimeoutError`` swallows — op name, deadline,
    elapsed — so the flight recorder and logs can say WHICH plane
    stalled instead of killing the pump with an anonymous traceback."""

    def __init__(self, op: str, timeout_s: float, elapsed_s: float):
        self.op = op
        self.timeout_s = timeout_s
        self.elapsed_s = elapsed_s
        super().__init__(
            f"store round trip {op!r} exceeded {timeout_s:.1f}s "
            f"deadline ({elapsed_s:.1f}s elapsed)"
        )


def run_on_loop(
    coro,
    loop: asyncio.AbstractEventLoop,
    timeout_s: float,
    op: str,
    recorder: Any = None,
):
    """Schedule ``coro`` onto the runtime's loop and block the calling
    (engine) thread on the result. A deadline miss books the
    ``dynamo_kvbm_remote_timeout_total{op=...}`` counter and a
    flight-recorder record, cancels the in-flight coroutine, and raises
    :class:`StoreRoundTripTimeout` — callers (RemoteTier.read, the
    fabric catalog) already treat any exception as a tier miss, so the
    pump degrades instead of dying on a bare TimeoutError."""
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    t0 = time.monotonic()
    try:
        return fut.result(timeout=timeout_s)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        elapsed = time.monotonic() - t0
        KVBM_REMOTE_TIMEOUTS.labels(op).inc()
        if recorder is not None:
            try:
                recorder.record(
                    "kvbm_remote_timeout",
                    duration_s=elapsed,
                    op=op,
                    timeout_s=timeout_s,
                )
            except Exception:  # pragma: no cover - recorder is best-effort
                log.exception("flight record for store timeout failed")
        log.warning(
            "store round trip %r timed out after %.1fs (deadline %.1fs)",
            op, elapsed, timeout_s,
        )
        raise StoreRoundTripTimeout(op, timeout_s, elapsed) from None


class StoreObjectAdapter(SyncObjectStore):
    def __init__(self, store, bucket: str, loop: asyncio.AbstractEventLoop,
                 timeout_s: float = 30.0, recorder: Any = None):
        self.store = store
        self.bucket = bucket
        self.loop = loop
        self.timeout_s = timeout_s
        self.recorder = recorder

    def _run(self, coro, op: str):
        return run_on_loop(
            coro, self.loop, self.timeout_s, op=op, recorder=self.recorder
        )

    def put(self, key: str, data: bytes) -> None:
        self._run(self.store.obj_put(self.bucket, key, data), "put")

    def get(self, key: str) -> Optional[bytes]:
        return self._run(self.store.obj_get(self.bucket, key), "get")

    def get_many(self, keys: list[str]) -> list[Optional[bytes]]:
        """One blocking wait for the whole batch: the gets overlap on
        the loop instead of serializing engine-thread round trips."""

        async def gather():
            import asyncio as aio

            return await aio.gather(
                *[self.store.obj_get(self.bucket, k) for k in keys]
            )

        return list(self._run(gather(), "get_many"))

    def list_keys(self) -> list[str]:
        return list(self._run(self.store.obj_list(self.bucket), "list"))


class DictObjectStore(SyncObjectStore):
    """In-process fake for tests and single-process serving."""

    def __init__(self) -> None:
        self.data: dict[str, bytes] = {}

    def put(self, key: str, data: bytes) -> None:
        self.data[key] = data

    def get(self, key: str) -> Optional[bytes]:
        return self.data.get(key)

    def list_keys(self) -> list[str]:
        return list(self.data)
