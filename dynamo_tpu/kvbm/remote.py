"""G4 remote-tier bridge: coordinator object plane ↔ engine thread.

The engine thread (which owns the KVBM pump) has no event loop; the
coordinator store client is async. ``StoreObjectAdapter`` schedules the
client's object-plane calls onto the runtime's loop and blocks the
engine thread on the result — exactly the pattern the reference uses
for its remote tier behind blocking NIXL calls
(reference: block_manager.rs CacheLevel::G4, block/transfer/nixl.rs).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.kvbm.manager import SyncObjectStore


class StoreObjectAdapter(SyncObjectStore):
    def __init__(self, store, bucket: str, loop: asyncio.AbstractEventLoop,
                 timeout_s: float = 30.0):
        self.store = store
        self.bucket = bucket
        self.loop = loop
        self.timeout_s = timeout_s

    def _run(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout=self.timeout_s)

    def put(self, key: str, data: bytes) -> None:
        self._run(self.store.obj_put(self.bucket, key, data))

    def get(self, key: str) -> Optional[bytes]:
        return self._run(self.store.obj_get(self.bucket, key))

    def get_many(self, keys: list[str]) -> list[Optional[bytes]]:
        """One blocking wait for the whole batch: the gets overlap on
        the loop instead of serializing engine-thread round trips."""

        async def gather():
            import asyncio as aio

            return await aio.gather(
                *[self.store.obj_get(self.bucket, k) for k in keys]
            )

        return list(self._run(gather()))

    def list_keys(self) -> list[str]:
        return list(self._run(self.store.obj_list(self.bucket)))


class DictObjectStore(SyncObjectStore):
    """In-process fake for tests and single-process serving."""

    def __init__(self) -> None:
        self.data: dict[str, bytes] = {}

    def put(self, key: str, data: bytes) -> None:
        self.data[key] = data

    def get(self, key: str) -> Optional[bytes]:
        return self.data.get(key)

    def list_keys(self) -> list[str]:
        return list(self.data)
