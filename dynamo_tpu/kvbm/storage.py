"""Storage backends for KV block tiers.

Tier ladder (≈ reference G1-G4, lib/llm/src/block_manager.rs:60-78):
G1 = device HBM (owned by the engine as jax.Arrays — not stored here),
G2 = host DRAM (``HostBlockStorage``), G3 = local disk
(``DiskBlockStorage`` via np.memmap), G4 = remote (the disaggregation
transfer agent, dynamo_tpu/disagg/).

Each storage holds ``num_blocks`` packed blocks of ``layout.packed_shape``
(reference Storage trait: lib/llm/src/block_manager/storage.rs:212-310;
``NullBlockStorage`` ≈ the Null test allocators at storage.rs:431-520
that let pool/layout logic run without real memory).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dynamo_tpu.kvbm.layout import BlockLayout


class BlockStorage:
    """num_blocks packed blocks; read/write by block index."""

    def __init__(self, layout: BlockLayout, num_blocks: int):
        self.layout = layout
        self.num_blocks = num_blocks

    def write_blocks(self, ids: list[int], data: np.ndarray) -> None:
        raise NotImplementedError

    def read_blocks(self, ids: list[int]) -> np.ndarray:
        """Returns [len(ids), *layout.packed_shape]."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class HostBlockStorage(BlockStorage):
    """G2: host-DRAM block pool (one contiguous ndarray)."""

    def __init__(self, layout: BlockLayout, num_blocks: int):
        super().__init__(layout, num_blocks)
        self._buf = np.zeros((num_blocks, *layout.packed_shape), layout.np_dtype)

    def write_blocks(self, ids: list[int], data: np.ndarray) -> None:
        self._buf[np.asarray(ids, np.int64)] = data

    def read_blocks(self, ids: list[int]) -> np.ndarray:
        return self._buf[np.asarray(ids, np.int64)]


class DiskBlockStorage(BlockStorage):
    """G3: local-disk block pool (np.memmap file)."""

    def __init__(self, layout: BlockLayout, num_blocks: int, path: str):
        super().__init__(layout, num_blocks)
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._mm = np.memmap(
            path,
            dtype=layout.np_dtype,
            mode="w+",
            shape=(num_blocks, *layout.packed_shape),
        )

    def write_blocks(self, ids: list[int], data: np.ndarray) -> None:
        self._mm[np.asarray(ids, np.int64)] = data

    def read_blocks(self, ids: list[int]) -> np.ndarray:
        return np.array(self._mm[np.asarray(ids, np.int64)])

    def close(self) -> None:
        mm = self._mm
        self._mm = None
        if mm is not None:
            del mm
        try:
            os.unlink(self.path)
        except OSError:
            pass


class NullBlockStorage(BlockStorage):
    """Metadata-only storage: pool/eviction logic without allocation."""

    def write_blocks(self, ids: list[int], data: np.ndarray) -> None:
        pass

    def read_blocks(self, ids: list[int]) -> np.ndarray:
        return np.zeros((len(ids), *self.layout.packed_shape), self.layout.np_dtype)
