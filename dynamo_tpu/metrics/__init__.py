"""Cluster metrics aggregation service (reference: components/metrics)."""

from dynamo_tpu.metrics.service import MetricsService

__all__ = ["MetricsService"]
