"""Metrics aggregation service: worker load → Prometheus text endpoint.

Reference: components/metrics/src/lib.rs:145-612 — scrape worker
ForwardPassMetrics, aggregate (avg/std load, active blocks/slots),
serve Prometheus ``/metrics``, and watch KV hit-rate events. Transport
here: subscribe to the component's ``load_metrics`` subject (same feed
as router and planner) and the frontend's KV hit-rate events.

Exposition rides the unified telemetry registry (telemetry/metrics.py):
the gauges below are declared once on a per-service Registry and
re-populated from a fresh aggregator snapshot at each scrape, so the
text format (HELP/TYPE pairs, label escaping, series dedup) is produced
by one implementation shared with the HTTP frontend.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import math
from typing import Optional

from aiohttp import web

from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.telemetry.debug import capture_profile, collect_debug_state
from dynamo_tpu.telemetry.metrics import Registry
from dynamo_tpu.telemetry.slo import aggregate_slo
from dynamo_tpu.utils.tasks import spawn

log = logging.getLogger("dynamo_tpu.metrics")

KV_HIT_RATE_SUBJECT = "kv-hit-rate"


class MetricsService:
    def __init__(
        self,
        component: Component,
        host: str = "0.0.0.0",
        port: int = 9091,
    ):
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = KvMetricsAggregator()
        self._hit_events = 0
        self._isl_sum = 0.0
        self._overlap_sum = 0.0
        self._runner: Optional[web.AppRunner] = None
        self._hit_task: Optional[asyncio.Task] = None
        # per-service registry (gauge names ≈ reference
        # components/metrics/src/lib.rs:339-545)
        self.registry = Registry()
        r = self.registry
        self._g_load_avg = r.gauge(
            "llm_kv_load_avg", "mean KV cache usage across workers")
        self._g_load_std = r.gauge(
            "llm_kv_load_std", "stddev of KV cache usage")
        self._g_blocks_active = r.gauge(
            "llm_kv_blocks_active", "total active KV blocks")
        self._g_blocks_total = r.gauge(
            "llm_kv_blocks_total", "total KV blocks")
        self._g_active_slots = r.gauge(
            "llm_requests_active_slots", "busy request slots")
        self._g_total_slots = r.gauge(
            "llm_requests_total_slots", "total request slots")
        self._g_waiting = r.gauge(
            "llm_requests_waiting", "queued requests")
        self._g_workers = r.gauge(
            "llm_workers_reporting", "workers with fresh metrics")
        self._g_worker_usage = r.gauge(
            "llm_worker_kv_cache_usage", "per-worker KV cache usage",
            labels=("worker",),
        )
        self._g_hit_events = r.gauge(
            "llm_kv_hit_rate_events", "KV hit rate events seen")
        self._g_avg_hit = r.gauge(
            "llm_kv_avg_hit_rate", "mean prefix overlap fraction")
        # SLO/goodput rollup (telemetry/slo.py signals riding the same
        # load_metrics feed — the Planner scales on these)
        self._g_slo_attainment = r.gauge(
            "llm_slo_attainment", "mean rolling SLO attainment across "
            "workers reporting targets")
        self._g_goodput = r.gauge(
            "llm_goodput_tokens", "total goodput tokens (SLO-met "
            "completion tokens) across workers")
        # perf-attribution rollup (telemetry/attribution.py signals on
        # the same feed): fleet-mean achieved/roofline ratio over the
        # workers that have a decode window (roofline_frac >= 0)
        self._g_roofline = r.gauge(
            "llm_roofline_frac", "mean live roofline fraction across "
            "workers with decode activity")

    def build_app(self) -> web.Application:
        """The debug/metrics route table, separable from ``start()`` so
        the endpoint-parity test can compare it against the HTTP
        frontend's without binding a socket. The ``/debug/*`` surface
        mirrors the frontend: an operator mid-incident must not have to
        remember which port grew which endpoint."""
        app = web.Application()
        app.router.add_get("/metrics", self._handle_metrics)
        app.router.add_get("/debug/state", self._handle_debug_state)
        app.router.add_get("/debug/attribution", self._handle_debug_attribution)
        app.router.add_get("/debug/hostplane", self._handle_debug_hostplane)
        app.router.add_get("/debug/kvfleet", self._handle_debug_kvfleet)
        app.router.add_get("/debug/requests", self._handle_debug_requests)
        app.router.add_get("/debug/request/{rid}", self._handle_debug_request)
        app.router.add_get("/debug/profile", self._handle_debug_profile)
        return app

    async def start(self) -> None:
        sub = await self.component.subscribe("load_metrics")
        self.aggregator.start_consuming(sub)
        hit_sub = await self.component.namespace.subscribe(KV_HIT_RATE_SUBJECT)

        async def pump_hits() -> None:
            async for _subject, payload in hit_sub:
                try:
                    self._hit_events += 1
                    self._isl_sum += float(payload.get("isl_blocks", 0))
                    self._overlap_sum += float(payload.get("overlap_blocks", 0))
                except Exception:
                    log.exception("bad kv-hit-rate payload")

        # spawn (not bare create_task): a crash in the hit-rate pump is
        # logged instead of dying silently with hit-rate gauges frozen
        self._hit_task = spawn(pump_hits(), name="metrics-hit-pump")
        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            # public API (no aiohttp private internals): the runner
            # exposes every site's bound (host, port)
            self.port = self._runner.addresses[0][1]
        log.info("metrics service on :%d/metrics", self.port)

    def render(self) -> str:
        """Prometheus text exposition from a fresh aggregator snapshot."""
        fresh = self.aggregator.fresh_metrics()
        loads = [m.gpu_cache_usage_perc for m in fresh.values()]
        mean = sum(loads) / len(loads) if loads else 0.0
        std = (
            math.sqrt(sum((x - mean) ** 2 for x in loads) / len(loads))
            if loads
            else 0.0
        )
        self._g_load_avg.set(mean)
        self._g_load_std.set(std)
        self._g_blocks_active.set(
            float(sum(m.kv_active_blocks for m in fresh.values()))
        )
        self._g_blocks_total.set(
            float(sum(m.kv_total_blocks for m in fresh.values()))
        )
        self._g_active_slots.set(
            float(sum(m.request_active_slots for m in fresh.values()))
        )
        self._g_total_slots.set(
            float(sum(m.request_total_slots for m in fresh.values()))
        )
        self._g_waiting.set(
            float(sum(m.num_requests_waiting for m in fresh.values()))
        )
        self._g_workers.set(float(len(fresh)))
        # per-worker series re-seed from the snapshot: a worker that
        # stopped reporting must drop out of the payload, not go stale
        self._g_worker_usage.clear()
        for wid, m in sorted(fresh.items()):
            self._g_worker_usage.labels(f"{wid:x}").set(m.gpu_cache_usage_perc)
        avg_hit = (
            self._overlap_sum / self._isl_sum if self._isl_sum > 0 else 0.0
        )
        self._g_hit_events.set(float(self._hit_events))
        self._g_avg_hit.set(avg_hit)
        attainment, goodput = aggregate_slo(fresh.values())
        self._g_slo_attainment.set(attainment)
        self._g_goodput.set(goodput)
        roofs = [
            m.roofline_frac for m in fresh.values()
            if getattr(m, "roofline_frac", -1.0) >= 0.0
        ]
        self._g_roofline.set(sum(roofs) / len(roofs) if roofs else 0.0)
        return self.registry.render()

    async def _handle_metrics(self, _req: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")

    async def _handle_debug_state(self, _req: web.Request) -> web.Response:
        """Fleet-side /debug/state: the aggregator's per-worker load
        view plus any local debug providers (an in-process engine's
        snapshot shows up here when the metrics server shares the
        worker process)."""
        state = collect_debug_state()
        fresh = self.aggregator.fresh_metrics()
        state["workers"] = {
            f"{wid:x}": m.model_dump() if hasattr(m, "model_dump")
            else dict(m.__dict__)
            for wid, m in sorted(fresh.items())
        }
        return web.json_response(state)

    async def _handle_debug_attribution(
        self, _req: web.Request
    ) -> web.Response:
        """Worker-side perf attribution (in-process engines register
        providers) plus the fleet's per-worker roofline/loss view from
        the load feed."""
        from dynamo_tpu.telemetry.attribution import collect_attribution

        state = collect_attribution()
        fresh = self.aggregator.fresh_metrics()
        state["workers"] = {
            f"{wid:x}": {
                "roofline_frac": getattr(m, "roofline_frac", -1.0),
                "top_loss_bucket": getattr(m, "top_loss_bucket", ""),
            }
            for wid, m in sorted(fresh.items())
        }
        return web.json_response(state)

    async def _handle_debug_hostplane(
        self, _req: web.Request
    ) -> web.Response:
        """Host data-plane view (telemetry/hostplane.py): event-loop
        lag, asyncio task census, and the per-stream cost ledger of
        whatever co-located services registered a provider."""
        from dynamo_tpu.telemetry.hostplane import collect_hostplane

        return web.json_response(collect_hostplane())

    async def _handle_debug_kvfleet(self, _req: web.Request) -> web.Response:
        """Fleet KV fabric introspection (docs/kvbm.md "Fleet fabric"):
        the ``kvfleet:*`` provider stanzas only — mirrors the HTTP
        frontend's endpoint for processes that co-locate a fabric with
        the metrics server (a worker). Empty when no fabric is attached
        here."""
        state = collect_debug_state()
        fleet = {
            k: v for k, v in state.items() if k.startswith("kvfleet")
        }
        return web.json_response(fleet)

    async def _handle_debug_requests(self, _req: web.Request) -> web.Response:
        """Request-autopsy exemplar index for THIS process (docs/
        observability.md "Request autopsy") — on a worker that is the
        pending engine-side segments plus any records finished here."""
        from dynamo_tpu.telemetry import autopsy

        return web.json_response(autopsy.collect_autopsy())

    async def _handle_debug_request(self, req: web.Request) -> web.Response:
        """One request's autopsy record, mirroring the frontend route."""
        from dynamo_tpu.telemetry import autopsy

        rid = req.match_info["rid"]
        rec = autopsy.get_record(rid)
        if rec is None:
            return web.json_response(
                {"error": f"no autopsy record for {rid!r} (never seen, "
                          "or dropped at finish by tail retention)"},
                status=404,
            )
        return web.json_response(rec)

    async def _handle_debug_profile(self, req: web.Request) -> web.Response:
        try:
            ms = int(req.query.get("ms", "1000"))
        except ValueError:
            return web.json_response(
                {"error": "ms must be an integer"}, status=400
            )
        try:
            return web.json_response(await capture_profile(ms))
        except RuntimeError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            log.exception("profile capture failed")
            return web.json_response(
                {"error": f"{type(exc).__name__}: {exc}"}, status=500
            )

    async def close(self) -> None:
        if self._hit_task is not None:
            self._hit_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._hit_task
        await self.aggregator.close()
        if self._runner is not None:
            await self._runner.cleanup()
