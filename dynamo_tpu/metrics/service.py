"""Metrics aggregation service: worker load → Prometheus text endpoint.

Reference: components/metrics/src/lib.rs:145-612 — scrape worker
ForwardPassMetrics, aggregate (avg/std load, active blocks/slots),
serve Prometheus ``/metrics``, and watch KV hit-rate events. Transport
here: subscribe to the component's ``load_metrics`` subject (same feed
as router and planner) and the frontend's KV hit-rate events.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import math
from typing import Optional

from aiohttp import web

from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator
from dynamo_tpu.runtime.component import Component

log = logging.getLogger("dynamo_tpu.metrics")

KV_HIT_RATE_SUBJECT = "kv-hit-rate"


class MetricsService:
    def __init__(
        self,
        component: Component,
        host: str = "0.0.0.0",
        port: int = 9091,
    ):
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = KvMetricsAggregator()
        self._hit_events = 0
        self._isl_sum = 0.0
        self._overlap_sum = 0.0
        self._runner: Optional[web.AppRunner] = None
        self._hit_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        sub = await self.component.subscribe("load_metrics")
        self.aggregator.start_consuming(sub)
        hit_sub = await self.component.namespace.subscribe(KV_HIT_RATE_SUBJECT)

        async def pump_hits() -> None:
            async for _subject, payload in hit_sub:
                try:
                    self._hit_events += 1
                    self._isl_sum += float(payload.get("isl_blocks", 0))
                    self._overlap_sum += float(payload.get("overlap_blocks", 0))
                except Exception:
                    log.exception("bad kv-hit-rate payload")

        self._hit_task = asyncio.create_task(pump_hits())
        app = web.Application()
        app.router.add_get("/metrics", self._handle_metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        log.info("metrics service on :%d/metrics", self.port)

    def render(self) -> str:
        """Prometheus text exposition (gauge names ≈ reference
        components/metrics/src/lib.rs:339-545)."""
        fresh = self.aggregator.fresh_metrics()
        lines: list[str] = []

        def gauge(name: str, help_: str, value: float, labels: str = "") -> None:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{labels} {value}")

        loads = [m.gpu_cache_usage_perc for m in fresh.values()]
        mean = sum(loads) / len(loads) if loads else 0.0
        std = (
            math.sqrt(sum((x - mean) ** 2 for x in loads) / len(loads))
            if loads
            else 0.0
        )
        gauge("llm_kv_load_avg", "mean KV cache usage across workers", mean)
        gauge("llm_kv_load_std", "stddev of KV cache usage", std)
        gauge(
            "llm_kv_blocks_active",
            "total active KV blocks",
            float(sum(m.kv_active_blocks for m in fresh.values())),
        )
        gauge(
            "llm_kv_blocks_total",
            "total KV blocks",
            float(sum(m.kv_total_blocks for m in fresh.values())),
        )
        gauge(
            "llm_requests_active_slots",
            "busy request slots",
            float(sum(m.request_active_slots for m in fresh.values())),
        )
        gauge(
            "llm_requests_total_slots",
            "total request slots",
            float(sum(m.request_total_slots for m in fresh.values())),
        )
        gauge(
            "llm_requests_waiting",
            "queued requests",
            float(sum(m.num_requests_waiting for m in fresh.values())),
        )
        gauge("llm_workers_reporting", "workers with fresh metrics", float(len(fresh)))
        for wid, m in sorted(fresh.items()):
            gauge(
                "llm_worker_kv_cache_usage",
                "per-worker KV cache usage",
                m.gpu_cache_usage_perc,
                labels=f'{{worker="{wid:x}"}}',
            )
        avg_hit = (
            self._overlap_sum / self._isl_sum if self._isl_sum > 0 else 0.0
        )
        gauge("llm_kv_hit_rate_events", "KV hit rate events seen", float(self._hit_events))
        gauge("llm_kv_avg_hit_rate", "mean prefix overlap fraction", avg_hit)
        return "\n".join(lines) + "\n"

    async def _handle_metrics(self, _req: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")

    async def close(self) -> None:
        if self._hit_task is not None:
            self._hit_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._hit_task
        await self.aggregator.close()
        if self._runner is not None:
            await self._runner.cleanup()
