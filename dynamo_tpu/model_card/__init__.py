"""Model deployment cards: publish/discover model artifacts on the store.

Analogue of the reference's model-card layer (reference:
lib/llm/src/model_card/model.rs:58-541 — ModelDeploymentCard with
move_to_nats/move_from_nats artifact shipping, and lib/llm/src/http/
service/discovery.rs:46-383 — ModelWatcher-driven model add/remove).
"""

from dynamo_tpu.model_card.card import (
    ModelDeploymentCard,
    default_model_name,
    ModelEntry,
    ModelInfo,
    fetch_card,
    list_entries,
    publish_card,
    register_llm,
    unregister_model,
)

__all__ = [
    "ModelDeploymentCard",
    "default_model_name",
    "ModelEntry",
    "ModelInfo",
    "fetch_card",
    "list_entries",
    "publish_card",
    "register_llm",
    "unregister_model",
]
