"""ModelDeploymentCard + ModelEntry: the model discovery data plane.

Two-level scheme mirroring the reference (reference:
lib/llm/src/model_card/model.rs — the card + artifact shipping via the
NATS object store; lib/llm/src/discovery/model_entry and
http/service/discovery.rs — per-instance ModelEntry keys in etcd):

- ``mdc/{slug}`` (KV, unleased, create-if-absent): the card JSON — model
  metadata plus object-store references for its artifacts
  (tokenizer.json, tokenizer_config.json, config.json). Artifacts live in
  object-store bucket ``mdc`` under ``{slug}/{filename}``.
- ``models/{slug}/{lease_hex}`` (KV, attached to the worker's primary
  lease): one ModelEntry per serving instance. Worker death revokes the
  lease, the entry vanishes, and frontends drop the model when its last
  entry is gone.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

from dynamo_tpu.store.base import NO_LEASE, Store

MDC_PREFIX = "mdc"
MODELS_PREFIX = "models"
MDC_BUCKET = "mdc"

# artifact files shipped with a card, in preference order
ARTIFACT_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "config.json",
    "generation_config.json",
    "preprocessor_config.json",
)


def default_model_name(model_path: str) -> str:
    """Service name derived from a model directory path (shared by serving
    and registration so the two can never diverge)."""
    return model_path.rstrip("/").rsplit("/", 1)[-1]


def slugify(name: str) -> str:
    """Store-safe slug of a service name (reference: runtime slug.rs)."""
    out = []
    for ch in name:
        if ch.isalnum() or ch in "-_.":
            out.append(ch)
        else:
            out.append("--")
    return "".join(out)


@dataclass
class ModelInfo:
    """Subset of the model config a frontend needs without the weights."""

    context_length: Optional[int] = None
    vocab_size: Optional[int] = None
    eos_token_ids: list[int] = field(default_factory=list)
    architecture: Optional[str] = None

    @classmethod
    def from_config_json(cls, path: str) -> "ModelInfo":
        try:
            with open(path) as f:
                cfg = json.load(f)
        except (OSError, json.JSONDecodeError):
            return cls()
        eos = cfg.get("eos_token_id")
        if eos is None:
            eos_ids = []
        elif isinstance(eos, list):
            eos_ids = [int(e) for e in eos]
        else:
            eos_ids = [int(eos)]
        archs = cfg.get("architectures") or []
        return cls(
            context_length=cfg.get("max_position_embeddings"),
            vocab_size=cfg.get("vocab_size"),
            eos_token_ids=eos_ids,
            architecture=archs[0] if archs else cfg.get("model_type"),
        )


@dataclass
class ModelDeploymentCard:
    """The shippable description of a deployable model
    (reference: model_card/model.rs:100-128)."""

    display_name: str
    service_name: str
    model_info: ModelInfo = field(default_factory=ModelInfo)
    artifacts: list[str] = field(default_factory=list)  # object names in MDC_BUCKET
    # filename -> sha256 of content; makes the card content-addressed so
    # frontends can cache artifacts immutably and re-publishes are detected
    artifact_hashes: dict[str, str] = field(default_factory=dict)
    revision: int = 0
    last_published: float = 0.0

    @property
    def slug(self) -> str:
        return slugify(self.service_name)

    def fingerprint(self) -> str:
        """Content identity: metadata + artifact hashes (not timestamps)."""
        ident = json.dumps(
            [
                self.display_name,
                self.service_name,
                asdict(self.model_info),
                self.artifacts,
                self.artifact_hashes,
            ],
            sort_keys=True,
        )
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelDeploymentCard":
        d = json.loads(data)
        d["model_info"] = ModelInfo(**d.get("model_info") or {})
        return cls(**d)

    @classmethod
    def from_local(cls, model_dir: str, service_name: str) -> "ModelDeploymentCard":
        """Build a card from a local HF-style model directory
        (reference: model_card/create.rs from_local_path)."""
        artifacts = []
        hashes = {}
        for f in ARTIFACT_FILES:
            path = os.path.join(model_dir, f)
            if os.path.exists(path):
                artifacts.append(f)
                with open(path, "rb") as fh:
                    hashes[f] = hashlib.sha256(fh.read()).hexdigest()
        info = ModelInfo.from_config_json(os.path.join(model_dir, "config.json"))
        return cls(
            display_name=service_name,
            service_name=service_name,
            model_info=info,
            artifacts=artifacts,
            artifact_hashes=hashes,
        )


@dataclass
class ModelEntry:
    """One serving instance of a model: name -> endpoint mapping
    (reference: discovery ModelEntry registered by llmctl / register_llm)."""

    name: str
    endpoint: str  # dyn://{ns}.{component}.{endpoint}
    model_type: str = "chat_completion"  # chat | completion | chat_completion | backend
    lease_id: int = NO_LEASE
    router_mode: str = ""  # hint: "" = frontend default, else random|round_robin|kv

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelEntry":
        return cls(**json.loads(data))


# ---------------------------------------------------------------------------
# store operations


def _card_key(slug: str) -> str:
    return f"{MDC_PREFIX}/{slug}"


def entry_key(slug: str, lease_id: int) -> str:
    return f"{MODELS_PREFIX}/{slug}/{lease_id:x}"


async def publish_card(
    store: Store, card: ModelDeploymentCard, model_dir: str
) -> bool:
    """Upload the card + artifacts (reference model.rs move_to_nats:233).

    Idempotent on identical content; a card whose fingerprint (metadata +
    artifact hashes) differs from the stored one *replaces* it (last
    writer wins) so re-registering a model with updated artifacts is not
    silently ignored. Returns True if this call published content."""
    existing = await store.kv_get(_card_key(card.slug))
    if existing is not None:
        try:
            old = ModelDeploymentCard.from_json(existing.value)
            if old.fingerprint() == card.fingerprint():
                return False
            card.revision = old.revision + 1
        except (json.JSONDecodeError, TypeError):
            card.revision += 1
    else:
        card.revision += 1
    card.last_published = time.time()
    # artifacts are stored content-addressed (by sha256), so concurrent
    # fetches of the old card version keep working during an update
    for fname in card.artifacts:
        with open(os.path.join(model_dir, fname), "rb") as f:
            await store.obj_put(
                MDC_BUCKET, _obj_name(card, fname), f.read()
            )
    await store.kv_put(_card_key(card.slug), card.to_json())
    return True


def _obj_name(card: ModelDeploymentCard, fname: str) -> str:
    h = card.artifact_hashes.get(fname, "v0")
    return f"{card.slug}/{h[:16]}/{fname}"


async def fetch_card(
    store: Store, service_name: str, cache_dir: Optional[str] = None
) -> tuple[ModelDeploymentCard, str]:
    """Fetch a card and materialize its artifacts into a local directory
    (reference: model.rs move_from_nats:282). Returns (card, local_dir)."""
    slug = slugify(service_name)
    entry = await store.kv_get(_card_key(slug))
    if entry is None:
        raise KeyError(f"no model card for {service_name!r}")
    card = ModelDeploymentCard.from_json(entry.value)
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.expanduser(os.environ.get("DYN_CACHE_DIR", "~/.cache/dynamo_tpu")),
            "mdc",
        )
    # fingerprint in the path makes the cache content-addressed: a
    # re-published card with different artifacts lands in a fresh dir, so
    # skip-if-exists can never serve stale tokenizer/config files
    local_dir = os.path.join(cache_dir, slug, card.fingerprint())
    os.makedirs(local_dir, exist_ok=True)
    for fname in card.artifacts:
        dest = os.path.join(local_dir, fname)
        if os.path.exists(dest):
            continue
        data = await store.obj_get(MDC_BUCKET, _obj_name(card, fname))
        if data is None:
            # card published by an older writer without hashed object names
            data = await store.obj_get(MDC_BUCKET, f"{slug}/{fname}")
        if data is None:
            raise KeyError(f"artifact {fname} missing for model {service_name!r}")
        tmp = dest + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dest)
    return card, local_dir


async def register_llm(
    store: Store,
    model_dir: str,
    service_name: str,
    endpoint: str,
    lease_id: int,
    model_type: str = "chat_completion",
    router_mode: str = "",
) -> ModelDeploymentCard:
    """Publish card (if absent) + this instance's ModelEntry.

    The analogue of the reference's ``register_llm`` binding
    (lib/bindings/python rust/lib.rs) / ``llmctl http add``: after this,
    discovery-driven frontends serve the model.
    """
    card = ModelDeploymentCard.from_local(model_dir, service_name)
    await publish_card(store, card, model_dir)
    entry = ModelEntry(
        name=service_name,
        endpoint=endpoint,
        model_type=model_type,
        lease_id=lease_id,
        router_mode=router_mode,
    )
    await store.kv_put(entry_key(card.slug, lease_id), entry.to_json(), lease_id=lease_id)
    return card


async def unregister_model(store: Store, service_name: str) -> int:
    """Remove every instance entry + the card + artifacts (llmctl remove)."""
    slug = slugify(service_name)
    n = await store.kv_delete_prefix(f"{MODELS_PREFIX}/{slug}/")
    if await store.kv_delete(_card_key(slug)):
        n += 1
    for name in await store.obj_list(MDC_BUCKET):
        if name.startswith(f"{slug}/"):
            await store.obj_delete(MDC_BUCKET, name)
    return n


async def list_entries(store: Store) -> list[ModelEntry]:
    entries = await store.kv_get_prefix(f"{MODELS_PREFIX}/")
    out = []
    for e in entries:
        try:
            out.append(ModelEntry.from_json(e.value))
        except (json.JSONDecodeError, TypeError):
            continue
    return out
