"""Model families: pure-JAX decoder implementations with mesh shardings.

The reference delegates model execution to external engines (vLLM/SGLang/
TRT-LLM, reference: SURVEY.md §1 L3); dynamo-tpu's flagship engine is
native: functional JAX models (params as pytrees), lax.scan over layers for
fast compiles, paged KV cache, and named-axis shardings so pjit/XLA place
the collectives.
"""

from dynamo_tpu.models.config import ModelConfig

__all__ = ["ModelConfig"]
