"""Model architecture config, loaded from HF-format config.json."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ModelConfig:
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    tie_word_embeddings: bool = False
    bos_token_id: int = 1
    eos_token_id: int | list[int] = 2
    # qwen2-family: bias on q/k/v projections
    attention_bias: bool = False
    # mistral-family: attend only to the last `sliding_window` positions
    sliding_window: Optional[int] = None
    # mlp activation: "silu" (llama et al) or "gelu" (gemma)
    hidden_act: str = "silu"
    # gemma-family: x *= sqrt(hidden_size) after embedding lookup, and
    # rmsnorm weights are stored as (w - 1) so the norm multiplies (1+w)
    scale_embeddings: bool = False
    norm_bias_one: bool = False
    # MoE (Mixtral-style)
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # multimodal (filled for vision-language models)
    vision_config: Optional[dict] = None
    # token id the processor substitutes per image patch slot (LLaVA's
    # image_token_index); None = resolve via the tokenizer
    image_token_index: Optional[int] = None

    def __post_init__(self) -> None:
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    @property
    def is_moe(self) -> bool:
        return self.num_local_experts > 0

    @property
    def eos_token_ids(self) -> list[int]:
        e = self.eos_token_id
        return list(e) if isinstance(e, list) else [e]

    @classmethod
    def from_dir(cls, path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            raw = json.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "ModelConfig":
        # VLM configs (LLaVA layout) nest the language model under
        # text_config; hoist it and keep the vision_config alongside
        # (reference: examples/multimodal serves such checkpoints)
        if "text_config" in raw:
            merged = dict(raw["text_config"])
            if raw.get("vision_config") is not None:
                merged["vision_config"] = raw["vision_config"]
            if "image_token_index" in raw:
                merged["image_token_index"] = raw["image_token_index"]
            structural = {
                "hidden_size", "num_hidden_layers",
                "num_attention_heads", "intermediate_size",
            }
            missing = structural - set(merged)
            if missing:
                # real llava-hf text_configs are often sparse and lean
                # on transformers' LlamaConfig (7B) defaults — which
                # this dataclass happens to share. Weight loading
                # validates every shape, so a wrong guess fails loudly
                # there; random-weight runs would not, hence the warning.
                import logging

                logging.getLogger("dynamo_tpu.models").warning(
                    "text_config omits %s; assuming Llama-7B-shaped "
                    "defaults (weight loading validates shapes)",
                    sorted(missing),
                )
            raw = merged
        known = {f.name for f in cls.__dataclass_fields__.values()}  # type: ignore[attr-defined]
        kwargs = {k: v for k, v in raw.items() if k in known}
        # qwen2 checkpoints always use qkv bias but don't say so in config
        if raw.get("model_type") == "qwen2" and "attention_bias" not in raw:
            kwargs["attention_bias"] = True
        # normalize HF gelu variants onto the one gelu we implement
        if kwargs.get("hidden_act") in ("gelu_pytorch_tanh", "gelu_new"):
            kwargs["hidden_act"] = "gelu"
        # gemma semantics are implied by the model_type, not config keys
        if raw.get("model_type") == "gemma":
            kwargs["scale_embeddings"] = True
            kwargs["norm_bias_one"] = True
            kwargs.setdefault("hidden_act", "gelu")
            kwargs.setdefault("tie_word_embeddings", True)
        # qwen2 configs carry sliding_window but HF defaults
        # use_sliding_window to FALSE: the window only applies when the
        # flag is explicitly true (mistral-family configs have no such
        # flag and the window always applies)
        if raw.get("model_type") == "qwen2" and not raw.get("use_sliding_window", False):
            kwargs["sliding_window"] = None
        elif raw.get("use_sliding_window") is False:
            kwargs["sliding_window"] = None
        return cls(**kwargs)
