"""Model hub resolution: repo-id → local checkpoint directory.

Analogue of the reference's hub download path (reference:
lib/llm/src/hub.rs:92 from_hf + local_model.rs — resolve a HF repo id,
download into a cache, serve from the local copy). Downloading is
OFF by default: serving nodes in zero-egress deployments must not
dial out, so a repo id only resolves when ``DYN_ALLOW_HUB_DOWNLOAD=1``
(or ``allow_download=True``). Already-cached models resolve without
network either way.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("dynamo_tpu.models.hub")

_WEIGHT_PATTERNS = [
    "*.safetensors", "*.json", "tokenizer.model", "*.txt",
]


def is_repo_id(path: str) -> bool:
    """'org/name'-shaped and not plausibly a local path. A nonexistent
    two-segment path whose FIRST segment exists as a local directory is
    treated as a local-path typo, not a hub repo — a mistyped
    ``ckpts/llama3`` must error as a missing path, not dial the hub."""
    if not path or os.path.exists(path):
        return False
    parts = path.split("/")
    if len(parts) != 2 or not all(p and not p.startswith(".") for p in parts):
        return False
    return not os.path.isdir(parts[0])


def cache_dir() -> str:
    return os.environ.get(
        "DYN_HUB_CACHE",
        os.path.join(os.path.expanduser("~"), ".dynamo_tpu", "hub"),
    )


def resolve_hub_model(
    path: str, allow_download: Optional[bool] = None
) -> str:
    """repo id or local path → local directory.

    Local paths pass through. Repo ids resolve from the local HF cache
    when present; a network download happens only when explicitly
    allowed. Raises with a actionable message otherwise."""
    if not is_repo_id(path):
        return path
    if allow_download is None:
        allow_download = os.environ.get("DYN_ALLOW_HUB_DOWNLOAD", "") in (
            "1", "true", "yes",
        )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as exc:
        raise ValueError(
            f"{path!r} looks like a hub repo id but huggingface_hub is "
            "not installed; mount the checkpoint locally instead"
        ) from exc
    if not allow_download:
        # cache-only resolution keeps zero-egress nodes offline
        try:
            return snapshot_download(
                path, local_files_only=True, cache_dir=cache_dir(),
                allow_patterns=_WEIGHT_PATTERNS,
            )
        except Exception as exc:
            raise ValueError(
                f"{path!r} is not cached locally and hub downloads are "
                "disabled; set DYN_ALLOW_HUB_DOWNLOAD=1 to fetch it, or "
                "mount the checkpoint and pass its path"
            ) from exc
    log.info("downloading %s from the hub", path)
    return snapshot_download(
        path, cache_dir=cache_dir(), allow_patterns=_WEIGHT_PATTERNS
    )
