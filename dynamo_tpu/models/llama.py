"""Llama-family decoder in pure functional JAX with paged KV cache.

Covers the Llama-architecture family the reference serves through its
engines: Llama/DeepSeek-R1-Distill, Mistral (sliding-window attention),
Qwen2 (QKV bias), and Mixtral-style MoE — one decoder, config-driven.

The flagship native engine model (reference analogue: the external vLLM
engine the reference shells out to — here the model is first-class,
SURVEY.md §7 step 4). Design choices for TPU:

- params are a flat pytree with layers **stacked on a leading L axis** and
  the transformer body is a single `lax.scan` over layers: one layer gets
  compiled once regardless of depth — fast compiles, identical performance.
- one **unified step function** serves prefill and decode: write new K/V
  into the paged cache at `slot_mapping`, gather each sequence's pages via
  its block table, and do masked attention. Decode is the T=1 special case.
  (The Pallas paged-attention kernel in ops/ replaces the gather on TPU.)
- GQA with head_dim-scaled RoPE; RMSNorm in f32; weights/activations bf16;
  attention softmax in f32.
- TP sharding over the "tp" mesh axis: q/k/v/o heads and MLP hidden are
  sharded; the KV cache is sharded on its KV-head axis so paged attention
  is fully local to each TP shard; XLA inserts the psum on o_proj/down_proj
  output via sharding propagation.
"""

from __future__ import annotations

import math
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map
from dynamo_tpu.models.config import ModelConfig

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Parameter init / sharding specs
# ---------------------------------------------------------------------------


def param_shapes(cfg: ModelConfig) -> dict[str, tuple[tuple[int, ...], Any]]:
    """name -> (shape, dtype). Layer params carry a leading L axis."""
    L = cfg.num_hidden_layers
    D = cfg.hidden_size
    H = cfg.num_attention_heads
    Hk = cfg.num_key_value_heads
    Dh = cfg.head_dim
    F = cfg.intermediate_size
    V = cfg.vocab_size
    bf16 = jnp.bfloat16
    shapes = {
        "embed": ((V, D), bf16),
        "attn_norm": ((L, D), jnp.float32),
        "wq": ((L, D, H * Dh), bf16),
        "wk": ((L, D, Hk * Dh), bf16),
        "wv": ((L, D, Hk * Dh), bf16),
        "wo": ((L, H * Dh, D), bf16),
        "mlp_norm": ((L, D), jnp.float32),
        "final_norm": ((D,), jnp.float32),
        "lm_head": ((D, V), bf16),
    }
    if cfg.attention_bias:
        shapes.update(
            {
                "bq": ((L, H * Dh), bf16),
                "bk": ((L, Hk * Dh), bf16),
                "bv": ((L, Hk * Dh), bf16),
            }
        )
    if cfg.is_moe:
        E = cfg.num_local_experts
        shapes.update(
            {
                "router": ((L, D, E), bf16),
                "w_gate": ((L, E, D, F), bf16),
                "w_up": ((L, E, D, F), bf16),
                "w_down": ((L, E, F, D), bf16),
            }
        )
    else:
        shapes.update(
            {
                "w_gate": ((L, D, F), bf16),
                "w_up": ((L, D, F), bf16),
                "w_down": ((L, F, D), bf16),
            }
        )
    return shapes


def param_specs(cfg: ModelConfig) -> dict[str, P]:
    """PartitionSpecs per param (tp shards heads/hidden, ep shards experts)."""
    specs = {
        "embed": P("tp", None),
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "mlp_norm": P(None, None),
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }
    if cfg.attention_bias:
        specs.update(
            {"bq": P(None, "tp"), "bk": P(None, "tp"), "bv": P(None, "tp")}
        )
    if cfg.is_moe:
        specs.update(
            {
                "router": P(None, None, None),
                "w_gate": P(None, "ep", None, "tp"),
                "w_up": P(None, "ep", None, "tp"),
                "w_down": P(None, "ep", "tp", None),
            }
        )
    else:
        specs.update(
            {
                "w_gate": P(None, None, "tp"),
                "w_up": P(None, None, "tp"),
                "w_down": P(None, "tp", None),
            }
        )
    return specs


def init_params(cfg: ModelConfig, seed: int = 0, mesh: Optional[Mesh] = None,
                specs: Optional[dict] = None) -> Params:
    """Random init (for tests / benchmarks without weights). ``specs``
    overrides the default TP PartitionSpecs (e.g. pp-sharded stacks)."""
    shapes = param_shapes(cfg)
    specs = specs if specs is not None else param_specs(cfg)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(shapes))
    params: Params = {}
    for (name, (shape, dtype)), k in zip(shapes.items(), keys):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        scale = 1.0 / math.sqrt(max(1, fan_in))
        if name.endswith("norm"):
            arr = jnp.ones(shape, dtype=dtype)
        else:
            arr = (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, specs[name]))
        params[name] = arr
    return params


def cache_shape(
    cfg: ModelConfig, num_blocks: int, block_size: int
) -> tuple[int, int, int, int]:
    """KV cache per K and V: [L, num_blocks*block_size, Hkv, Dh]."""
    return (
        cfg.num_hidden_layers,
        num_blocks * block_size,
        cfg.num_key_value_heads,
        cfg.head_dim,
    )


CACHE_SPEC = P(None, None, "tp", None)
# int8-cache scale arrays [L, N, Hk, bs]: tp shards the head axis
SCALE_SPEC = P(None, None, "tp", None)


def kv_cache_is_quantized(cache) -> bool:
    """True when ``cache`` is an int8 (values, scales) pair rather than
    a plain float array. The quantized cache threads through jit/scan/
    donation as a pytree; only code that indexes into it branches."""
    return isinstance(cache, tuple)


def init_cache(
    cfg: ModelConfig,
    num_blocks: int,
    block_size: int,
    mesh: Optional[Mesh] = None,
    dtype=jnp.bfloat16,
    spec: Optional[P] = None,
):
    """Zeroed paged KV cache: (k_cache, v_cache). Float dtypes give
    plain arrays (fp8 e4m3 = scale-free quantized storage); int8 gives
    (values, scales) pairs with per-(slot, head) f32 scales
    (ops/kv_quant.py documents the scale layout)."""
    shape = cache_shape(cfg, num_blocks, block_size)
    k = jnp.zeros(shape, dtype=dtype)
    v = jnp.zeros(shape, dtype=dtype)
    if mesh is not None:
        sh = NamedSharding(mesh, spec if spec is not None else CACHE_SPEC)
        k, v = jax.device_put(k, sh), jax.device_put(v, sh)
    if jnp.dtype(dtype) != jnp.int8:
        return k, v
    from dynamo_tpu.ops.kv_quant import kv_scale_shape

    sshape = kv_scale_shape(
        cfg.num_hidden_layers, num_blocks, block_size,
        cfg.num_key_value_heads,
    )
    ks = jnp.ones(sshape, jnp.float32)
    vs = jnp.ones(sshape, jnp.float32)
    if mesh is not None:
        ssh = NamedSharding(mesh, SCALE_SPEC)
        ks, vs = jax.device_put(ks, ssh), jax.device_put(vs, ssh)
    return (k, ks), (v, vs)


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rmsnorm(x: jax.Array, w: jax.Array, eps: float,
            bias_one: bool = False) -> jax.Array:
    """RMSNorm in f32. ``bias_one``: gemma stores weights as (w - 1) and
    the norm multiplies by (1 + w)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    scale = (1.0 + w) if bias_one else w
    out = xf * jax.lax.rsqrt(var + eps) * scale
    return out.astype(x.dtype)


def mlp_act(cfg: ModelConfig, g: jax.Array) -> jax.Array:
    """Gate activation: silu (llama family) or tanh-gelu (gemma).
    Unknown activations fail loudly — a silent silu fallback would serve
    corrupted logits for checkpoints we don't actually support."""
    if cfg.hidden_act == "gelu":
        return jax.nn.gelu(g, approximate=True)
    if cfg.hidden_act == "silu":
        return jax.nn.silu(g)
    raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")


def scale_embed(cfg: ModelConfig, x: jax.Array) -> jax.Array:
    """Gemma-family sqrt(hidden) embedding scaling (no-op otherwise)."""
    if not cfg.scale_embeddings:
        return x
    return (x.astype(jnp.float32) * math.sqrt(cfg.hidden_size)).astype(x.dtype)


def matmul_impl() -> str:
    """Quantized-matmul implementation: DYN_MATMUL_IMPL =
    auto|reference|pallas (mirrors DYN_ATTN_IMPL).

    auto = the fused dequant Pallas kernels (ops/qmatmul.py) on TPU for
    single-device serving (jax.device_count() == 1, or an engine-
    registered size-1 mesh), the XLA mixed-dtype dot elsewhere. Off-TPU
    the kernels run interpreted (correct but slow — tests only).
    Multi-device meshes stay on the reference path: wo/w_down contract
    a tp-sharded axis, and the kernels carry no psum story."""
    impl = os.environ.get("DYN_MATMUL_IMPL", "auto")
    if impl == "auto":
        if jax.default_backend() == "tpu" and _single_device_matmul():
            return "pallas"
        return "reference"
    return impl


def _single_device_matmul() -> bool:
    return jax.device_count() == 1 or (
        _ATTN_MESH is not None and _ATTN_MESH.size == 1
    )


def pallas_matmul_active() -> bool:
    """True when quantized matmuls will ACTUALLY dispatch the Pallas
    dequant kernels — impl choice AND an unsharded-weights
    configuration (the same shape of predicate as
    pallas_attention_active)."""
    return matmul_impl() == "pallas" and _single_device_matmul()


def _qmm_interpret() -> bool:
    return jax.default_backend() != "tpu"


def mm(p: Params, name: str, x: jax.Array) -> jax.Array:
    """x @ p[name], transparently handling int8 weight-only quantization
    (models/quant.py). Reference epilogue: a mixed-dtype dot (bf16
    activations × int8 weight, f32 accumulation) keeps HBM reads
    int8-sized — measured ~1.3-2× decode speedup over bf16 on v5e —
    then the per-output-channel scale applies to the f32 product before
    casting back. Under DYN_MATMUL_IMPL=pallas the fused dequant kernel
    (ops/qmatmul.py) does the same math with the upcast in-register,
    which is what actually reaches int8-byte-bound weight reads."""
    w = p[name]
    if w.dtype == jnp.int8:
        if pallas_matmul_active():
            from dynamo_tpu.ops.qmatmul import qmm

            return qmm(
                x, w, p[name + "_scale"], interpret=_qmm_interpret()
            )
        y = jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * p[name + "_scale"]).astype(x.dtype)
    return x @ w


def embed_lookup(p: Params, tokens: jax.Array) -> jax.Array:
    """Token embedding rows, rescaled per row when the table is int8."""
    w = p["embed"]
    x = jnp.take(w, tokens, axis=0)
    if w.dtype == jnp.int8:
        s = jnp.take(p["embed_scale"], tokens, axis=0)  # [B, T]
        x = x.astype(jnp.bfloat16) * s[..., None].astype(jnp.bfloat16)
    return x


def rope(q: jax.Array, k: jax.Array, positions: jax.Array, theta: float) -> tuple[jax.Array, jax.Array]:
    """Rotary embeddings; q/k: [B, T, H, Dh], positions: [B, T]."""
    dh = q.shape[-1]
    half = dh // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x: jax.Array) -> jax.Array:
        x1, x2 = x[..., :half], x[..., half:]
        xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
        return jnp.concatenate(
            [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
        ).astype(x.dtype)

    return rot(q), rot(k)


def paged_attention_reference(
    q: jax.Array,  # [B, T, H, Dh]
    k_cache_l: jax.Array,  # [n_slots, Hkv, Dh] (one layer)
    v_cache_l: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks] int32 block ids
    positions: jax.Array,  # [B, T] absolute positions of the queries
    context_lens: jax.Array,  # [B] total valid tokens per sequence
    block_size: int,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Gather-then-attend paged attention (XLA reference path).

    Works on any backend; the Pallas kernel (ops/paged_attention.py) is the
    TPU fast path with identical semantics. ``sliding_window`` masks keys
    older than the window (Mistral-family).
    """
    if kv_cache_is_quantized(k_cache_l):
        # int8 cache: dequantize each layer-slice pair in f32, then run
        # the plain path (test oracle; the kernels scale in-register)
        from dynamo_tpu.ops.kv_quant import gather_slot_scales

        (kv_l, ks_l), (vv_l, vs_l) = k_cache_l, v_cache_l
        Hk = kv_l.shape[-2]
        B = q.shape[0]
        S = block_tables.shape[1] * block_size
        slot_ids = (
            block_tables[:, :, None] * block_size
            + jnp.arange(block_size, dtype=block_tables.dtype)[None, None, :]
        ).reshape(B, S)
        ksc = gather_slot_scales(ks_l, slot_ids, block_size, Hk)
        vsc = gather_slot_scales(vs_l, slot_ids, block_size, Hk)
        keys = (
            kv_l[slot_ids].astype(jnp.float32) * ksc[..., None]
        ).astype(q.dtype)
        vals = (
            vv_l[slot_ids].astype(jnp.float32) * vsc[..., None]
        ).astype(q.dtype)
        return _reference_attend(
            q, keys, vals, positions, context_lens, sliding_window
        )
    B, T, H, Dh = q.shape
    Hk = k_cache_l.shape[-2]
    S = block_tables.shape[1] * block_size
    # gather pages: [B, S] flat slot ids
    slot_ids = (
        block_tables[:, :, None] * block_size
        + jnp.arange(block_size, dtype=block_tables.dtype)[None, None, :]
    ).reshape(B, S)
    keys = k_cache_l[slot_ids]  # [B, S, Hk, Dh]
    vals = v_cache_l[slot_ids]
    if keys.dtype != q.dtype:
        # quantized (fp8) cache: dequantize for the einsum (exact cast)
        keys = keys.astype(q.dtype)
        vals = vals.astype(q.dtype)
    return _reference_attend(
        q, keys, vals, positions, context_lens, sliding_window
    )


def _reference_attend(
    q: jax.Array,  # [B, T, H, Dh]
    keys: jax.Array,  # [B, S, Hk, Dh] gathered (and dequantized) pages
    vals: jax.Array,
    positions: jax.Array,
    context_lens: jax.Array,
    sliding_window: Optional[int],
) -> jax.Array:
    """Masked-attention tail of the XLA reference path.

    GQA via grouped einsum — no [B, S, H, Dh] materialization of
    group-expanded keys/values (the repeat would multiply attention's
    HBM traffic by H/Hk)."""
    B, T, H, Dh = q.shape
    Hk = keys.shape[-2]
    S = keys.shape[1]
    group = H // Hk
    qg = q.reshape(B, T, Hk, group, Dh)
    scale = 1.0 / math.sqrt(Dh)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, keys, preferred_element_type=jnp.float32
    ) * scale  # [B, Hk, G, T, S]
    key_pos = jnp.arange(S, dtype=jnp.int32)[None, None, None, None, :]
    pos_q = positions[:, None, None, :, None]
    mask = (key_pos <= pos_q) & (
        key_pos < context_lens[:, None, None, None, None]
    )
    if sliding_window is not None:
        mask = mask & (key_pos > pos_q - sliding_window)
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, vals)
    return out.reshape(B, T, H, Dh)


# Mesh for multi-device Pallas attention: attention is local per
# KV-head shard, so the decode kernel runs under shard_map over "tp"
# (one kernel instance per shard, no collectives). Set by the engine
# BEFORE tracing its step functions (module state is captured at trace
# time); pp engines leave it unset — inside the pp stage rotation "tp"
# is a GSPMD auto axis that a manual shard_map can't claim.
_ATTN_MESH: Optional[Mesh] = None


def set_attention_mesh(mesh: Optional[Mesh]) -> None:
    global _ATTN_MESH
    _ATTN_MESH = mesh


def get_attention_mesh() -> Optional[Mesh]:
    return _ATTN_MESH


def pallas_attention_active() -> bool:
    """True when the model will ACTUALLY dispatch the Pallas attention
    kernels (the predicate attend_mlp uses) — impl choice AND a usable
    device/mesh configuration. The engine's HBM auto-sizing keys off
    this same predicate: sizing on attn_impl() alone would zero the
    XLA-path scores-transient budget in configurations (e.g. pp meshes,
    where the attention mesh is deliberately unset) that still run the
    reference path."""
    return attn_impl() == "pallas" and (
        jax.device_count() == 1 or _ATTN_MESH is not None
    )


def attn_impl() -> str:
    """Attention implementation: DYN_ATTN_IMPL = auto|reference|pallas.

    auto = the Pallas decode kernel on TPU (single device, or any tp
    mesh registered via set_attention_mesh), XLA gather path elsewhere
    (Pallas runs interpreted off-TPU: correct but slow — tests only).
    """
    impl = os.environ.get("DYN_ATTN_IMPL", "auto")
    if impl == "auto":
        if jax.default_backend() == "tpu" and (
            jax.device_count() == 1 or _ATTN_MESH is not None
        ):
            return "pallas"
        return "reference"
    return impl


# ---------------------------------------------------------------------------
# The unified forward step
# ---------------------------------------------------------------------------


def fused_mlp_ok(cfg: ModelConfig, lp: Params) -> bool:
    """The fused dequant epilogues serve this layer: dense MLP with
    every hot-path weight int8-quantized and a kernel-supported gate
    activation, under the Pallas matmul impl."""
    return (
        not cfg.is_moe
        and pallas_matmul_active()
        and cfg.hidden_act in ("silu", "gelu")
        and all(
            n in lp and lp[n].dtype == jnp.int8
            for n in ("wo", "w_gate", "w_up", "w_down")
        )
    )


def post_attn_mlp(
    cfg: ModelConfig, lp: Params, x: jax.Array, a: jax.Array
) -> jax.Array:
    """Everything after attention: output projection + MLP/MoE residual
    — ONE copy shared by every attention variant AND the bench's
    per-phase microbenches (bench.py --phases), so the measured matmul
    composition can never drift from the served one. ``a`` is the
    flattened attention output [B, T, H*Dh].

    Under the Pallas matmul impl (int8 weights) the decode hot path
    runs three fused kernels instead of five ops: wo with the residual
    add in-epilogue, ONE gate/up pass with SiLU·mul in-kernel (the
    [.., F] intermediates never hit HBM), and w_down with the second
    residual add in-epilogue — the rounding points match the reference
    composition exactly (ops/qmatmul.py)."""
    if fused_mlp_ok(cfg, lp):
        from dynamo_tpu.ops.qmatmul import qmm, qmm_gate_up

        interp = _qmm_interpret()
        x = qmm(a, lp["wo"], lp["wo_scale"], residual=x, interpret=interp)
        h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
        hh = qmm_gate_up(
            h, lp["w_gate"], lp["w_gate_scale"],
            lp["w_up"], lp["w_up_scale"],
            act=cfg.hidden_act, interpret=interp,
        )
        return qmm(
            hh, lp["w_down"], lp["w_down_scale"], residual=x,
            interpret=interp,
        )
    x = x + mm(lp, "wo", a).astype(x.dtype)
    h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
    if cfg.is_moe:
        x = x + _moe_mlp(cfg, lp, h).astype(x.dtype)
    else:
        mlp_out = mm(
            lp, "w_down", mlp_act(cfg, mm(lp, "w_gate", h)) * mm(lp, "w_up", h)
        )
        x = x + mlp_out.astype(x.dtype)
    return x


def make_layer_parts(
    cfg: ModelConfig,
    positions: jax.Array,  # [B, T]
    block_tables: jax.Array,  # [B, max_blocks]
    context_lens: jax.Array,  # [B]
    block_size: int,
):
    """The layer math in two halves so callers choose WHERE the KV write
    lands (layer slice vs full carried stack) without duplicating it:

      qkv(lp, x)                 -> (q, k, v) roped, [B, T, H*, Dh]
      attend_mlp(lp, x, q, kcl, vcl) -> new x (reads the layer cache
                                    AFTER the caller wrote k/v into it)
    """
    H, Hk, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    def qkv(lp, x):
        B, T = x.shape[0], x.shape[1]
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
        q = mm(lp, "wq", h)
        k = mm(lp, "wk", h)
        v = mm(lp, "wv", h)
        if cfg.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(B, T, H, Dh)
        k = k.reshape(B, T, Hk, Dh)
        v = v.reshape(B, T, Hk, Dh)
        q, k = rope(q, k, positions, cfg.rope_theta)
        return q, k, v

    # one predicate for dispatch AND the engine's HBM sizing
    _use_pallas_decode = pallas_attention_active

    def _pallas_decode_attn(q, stacked_args):
        """Run the flash-decode kernel (shard_mapped per tp shard on
        multi-device meshes). ``stacked_args`` = (k_cache, v_cache,
        layer_idx) over the stacked [L, ...] cache — the single kernel
        body serves the per-layer API too (ops/paged_attention.py)."""
        import functools as _ft

        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_stacked,
        )

        k_cache, v_cache, layer_idx = stacked_args
        ksc = vsc = None
        if kv_cache_is_quantized(k_cache):
            (k_cache, ksc), (v_cache, vsc) = k_cache, v_cache
        base = _ft.partial(
            paged_attention_decode_stacked,
            block_size=block_size,
            sliding_window=cfg.sliding_window,
            interpret=jax.default_backend() != "tpu",
        )
        if ksc is None:
            kern = base
        else:
            def kern(q_, kc_, vc_, li_, bt_, cl_, ks_, vs_):
                return base(
                    q_, kc_, vc_, li_, bt_, cl_, k_scale=ks_, v_scale=vs_
                )
        mesh = _ATTN_MESH
        if mesh is not None and mesh.size > 1:
            # one kernel per tp shard: q heads and the cache's KV-head
            # axis (dim 2 of the stacked layout) are tp-sharded; layer
            # index, tables and ctx ride replicated. Other mesh axes
            # (dp/ep/sp) are unmapped (replicated through the kernel).
            # int8 scale arrays shard on their hk-major minor dim —
            # contiguous tp chunks are exactly each shard's heads
            # (SCALE_SPEC).
            in_specs = (
                P(None, "tp", None),
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(),
                P(None, None),
                P(None),
            )
            if ksc is not None:
                in_specs += (SCALE_SPEC, SCALE_SPEC)
            kern = shard_map(
                kern,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(None, "tp", None),
                axis_names={"tp"},
                check_vma=False,
            )
        args = (q[:, 0], k_cache, v_cache, layer_idx, block_tables,
                context_lens)
        if ksc is not None:
            args += (ksc, vsc)
        return kern(*args)[:, None]  # [B, 1, H, Dh]

    def _pallas_prefill_attn(q, stacked_args):
        """Flash prefill over the paged cache (T > 1): tile×page grid,
        online softmax — no [T, S] score materialization (the XLA
        reference path's [B, Hk, G, T, S] tensor is ~400 MB at
        T=1024/S=3072 and its HBM traffic dominates long-prompt TTFT).
        Prefill rows are contiguous token runs, so the kernel derives
        per-token positions from positions[:, 0]."""
        import functools as _ft

        from dynamo_tpu.ops.paged_attention import (
            paged_attention_prefill_stacked,
        )

        k_cache, v_cache, layer_idx = stacked_args
        ksc = vsc = None
        if kv_cache_is_quantized(k_cache):
            (k_cache, ksc), (v_cache, vsc) = k_cache, v_cache
        base = _ft.partial(
            paged_attention_prefill_stacked,
            block_size=block_size,
            sliding_window=cfg.sliding_window,
            interpret=jax.default_backend() != "tpu",
        )
        if ksc is None:
            kern = base
        else:
            def kern(q_, kc_, vc_, li_, bt_, st_, cl_, ks_, vs_):
                return base(
                    q_, kc_, vc_, li_, bt_, st_, cl_,
                    k_scale=ks_, v_scale=vs_,
                )
        mesh = _ATTN_MESH
        if mesh is not None and mesh.size > 1:
            in_specs = (
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(),
                P(None, None),
                P(None),
                P(None),
            )
            if ksc is not None:
                in_specs += (SCALE_SPEC, SCALE_SPEC)
            kern = shard_map(
                kern,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(None, None, "tp", None),
                axis_names={"tp"},
                check_vma=False,
            )
        args = (q, k_cache, v_cache, layer_idx, block_tables,
                positions[:, 0], context_lens)
        if ksc is not None:
            args += (ksc, vsc)
        return kern(*args)  # [B, T, H, Dh]

    def _post_attn(lp, x, attn):
        B, T = x.shape[0], x.shape[1]
        return post_attn_mlp(cfg, lp, x, attn.reshape(B, T, H * Dh))

    def _expand1(cache_l):
        """Per-layer cache -> 1-layer stack (free expand-dims), for
        plain arrays and int8 (values, scales) pairs alike."""
        if kv_cache_is_quantized(cache_l):
            return (cache_l[0][None], cache_l[1][None])
        return cache_l[None]

    def attend_mlp(lp, x, q, k_cache_l, v_cache_l):
        T = x.shape[1]
        if T == 1 and _use_pallas_decode():
            # per-layer cache: run as a 1-layer stack (free expand-dims)
            attn = _pallas_decode_attn(
                q, (_expand1(k_cache_l), _expand1(v_cache_l), jnp.int32(0))
            )
        elif _use_pallas_decode():
            attn = _pallas_prefill_attn(
                q, (_expand1(k_cache_l), _expand1(v_cache_l), jnp.int32(0))
            )
        else:
            attn = paged_attention_reference(
                q, k_cache_l, v_cache_l, block_tables, positions,
                context_lens, block_size, cfg.sliding_window,
            )
        return _post_attn(lp, x, attn)

    def attend_mlp_stacked(lp, x, q, k_cache, v_cache, layer_idx):
        """attend_mlp over layer ``layer_idx`` of the FULL stacked cache.

        The decode hot path: slicing the layer out of the carried cache
        before a pallas_call materializes a full-layer copy at the
        custom-call boundary (measured ~11 ms/step on a 4.7 GB cache,
        linear in cache size — the r3 closed-batch regression). The
        stacked kernel indexes the layer inside its BlockSpec instead,
        so only referenced pages move (ops/paged_attention.py
        paged_attention_decode_stacked). Non-decode shapes and the XLA
        reference path slice the layer as before — XLA fuses that slice
        into its own gather."""
        T = x.shape[1]
        if _use_pallas_decode():
            attn = (
                _pallas_decode_attn(q, (k_cache, v_cache, layer_idx))
                if T == 1
                else _pallas_prefill_attn(q, (k_cache, v_cache, layer_idx))
            )
            return _post_attn(lp, x, attn)
        def slice_layer(cache):
            if kv_cache_is_quantized(cache):
                return tuple(
                    jax.lax.dynamic_index_in_dim(c, layer_idx, 0, keepdims=False)
                    for c in cache
                )
            return jax.lax.dynamic_index_in_dim(
                cache, layer_idx, 0, keepdims=False
            )

        return attend_mlp(lp, x, q, slice_layer(k_cache), slice_layer(v_cache))

    return qkv, attend_mlp, attend_mlp_stacked


def make_layer_fn(
    cfg: ModelConfig,
    positions: jax.Array,  # [B, T]
    slot_mapping: jax.Array,  # [B*T]
    block_tables: jax.Array,  # [B, max_blocks]
    context_lens: jax.Array,  # [B]
    block_size: int,
):
    """Per-layer scan body: (x, (layer_params, k_cache_l, v_cache_l)) -> ...

    Shared by the plain lax.scan forward and the pipeline-parallel stage
    loop (parallel/pipeline.py), which calls it with per-microbatch args.
    """
    Hk, Dh = cfg.num_key_value_heads, cfg.head_dim
    qkv, attend_mlp, _ = make_layer_parts(
        cfg, positions, block_tables, context_lens, block_size
    )

    def layer_fn(x, scanned):
        B, T = x.shape[0], x.shape[1]
        lp, k_cache_l, v_cache_l = scanned
        if kv_cache_is_quantized(k_cache_l):
            raise NotImplementedError(
                "int8 KV cache is not supported on the pipeline-parallel "
                "path (per-layer xs/ys cache layout); use bfloat16 or "
                "float8_e4m3fn with pipeline_parallel_size > 1"
            )
        q, k, v = qkv(lp, x)
        # write new kv into the paged cache (layer slice); astype is the
        # quantization step for fp8 caches (RN convert), a no-op for bf16
        k_cache_l = k_cache_l.at[slot_mapping].set(
            k.reshape(B * T, Hk, Dh).astype(k_cache_l.dtype)
        )
        v_cache_l = v_cache_l.at[slot_mapping].set(
            v.reshape(B * T, Hk, Dh).astype(v_cache_l.dtype)
        )
        x = attend_mlp(lp, x, q, k_cache_l, v_cache_l)
        return x, (k_cache_l, v_cache_l)

    return layer_fn


_GLOBAL_PARAMS = (
    "embed", "final_norm", "lm_head", "embed_scale", "lm_head_scale",
)


def layer_param_names(params: Params) -> list[str]:
    return [k for k in params if k not in _GLOBAL_PARAMS]


def forward(
    cfg: ModelConfig,
    params: Params,
    k_cache: jax.Array,  # [L, n_slots, Hkv, Dh]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B, T] int32 (padded)
    positions: jax.Array,  # [B, T] int32 absolute positions (padded: 0)
    slot_mapping: jax.Array,  # [B*T] int32 flat cache slots (padded: slot 0)
    block_tables: jax.Array,  # [B, max_blocks] int32 (padded: block 0)
    context_lens: jax.Array,  # [B] int32 valid tokens incl. new ones
    last_token_idx: jax.Array,  # [B] int32 index of last real token in T
    block_size: int,
    extra_embeds: Optional[jax.Array] = None,  # [B, T, D] injected embeds
    embeds_mask: Optional[jax.Array] = None,  # [B, T] bool: use injected
    logits_all: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One model step. Returns (logits[B, V], new_k_cache, new_v_cache).

    ``extra_embeds``/``embeds_mask`` splice precomputed embeddings (image
    patches from models/vision.py) over the token embeddings at masked
    positions — the multimodal injection point (reference:
    examples/multimodal encode-worker → LLM embedding handoff).

    ``logits_all=True`` (trace-time constant) returns logits at EVERY
    fed position — [B, T, V] instead of [B, V] — the speculative-decode
    verify step needs the target distribution at each draft position
    (dynamo_tpu/spec). Only sensible for small T: the lm_head matmul and
    the [B, T, V] f32 output scale linearly with T.
    """
    x = scale_embed(cfg, embed_lookup(params, tokens))  # [B, T, D]
    if extra_embeds is not None:
        assert embeds_mask is not None
        x = jnp.where(embeds_mask[..., None], extra_embeds.astype(x.dtype), x)

    layer_params = {k: params[k] for k in layer_param_names(params)}

    # The KV cache rides the scan CARRY with the new k/v scattered
    # DIRECTLY into the full stack at [layer, slots] — NOT the xs/ys
    # stream. Scanned-over caches make XLA materialize a re-stacked
    # copy of the ENTIRE cache (an HLO temp of cache size — with an
    # auto-sized multi-GB cache that alone OOMs the chip, and it costs
    # a read+write of all cache bytes per step); a carried cache
    # aliases in place, and the direct scatter touches only the
    # written rows (a slice-copy+DUS variant still moved one full
    # layer slice per layer). Measured on v5e (8B int8, fused K=32):
    # 24.6 xs/ys -> 20.7 slice-DUS -> 19.3 direct-scatter ms/step;
    # engine 882 -> 1022 -> 1090 tok/s. Prefill (T>1) uses the same
    # formulation: its chunk amortizes the scatter and the peak-memory
    # profile stays flat (pipeline-parallel stages keep the xs/ys
    # layout over their L/pp slice — parallel/pipeline.py).
    Hk, Dh = cfg.num_key_value_heads, cfg.head_dim
    qkv, _attend_mlp, attend_mlp_stacked = make_layer_parts(
        cfg, positions, block_tables, context_lens, block_size
    )
    B, T = tokens.shape

    quantized = kv_cache_is_quantized(k_cache)
    if quantized:
        from dynamo_tpu.ops.kv_quant import (
            quantize_kv,
            scale_scatter_indices,
        )

        n_idx, off_idx = scale_scatter_indices(slot_mapping, block_size)

    def write_kv(cache, new, i):
        """Scatter this layer's fresh K or V rows [B*T, Hk, Dh] into the
        carried cache at ``slot_mapping`` — the int8 path quantizes
        per (token, head) and scatters the scales alongside; the astype
        is the fp8 quantization step (bf16 no-op).

        Scale-write forms matter enormously here: only the CANONICAL
        scatter (one indexed axis + suffix window — the values write's
        form) updates the carried array in place. The decode path
        (T=1) therefore read-modify-writes whole [Hk, bs] page tiles —
        safe because decode rows own distinct tail pages (padded rows
        all hit the garbage page 0, where racing writes are harmless).
        The indexed-slice form (``.at[i, n, :, off]``) makes XLA
        materialize + copy the full scale plane per layer at the
        Pallas custom-call boundary (measured: +2 ms/step at a
        500-block cache, scaling with cache size) — prefill keeps it
        because a chunk writes many slots per page (tile RMW would
        race) and its cost amortizes over the chunk's tokens."""
        if not quantized:
            return cache.at[i, slot_mapping].set(new.astype(cache.dtype))
        q8, sc = quantize_kv(new)
        vals, scales = cache
        vals = vals.at[i, slot_mapping].set(q8)
        if T == 1:
            bs_ = scales.shape[-1]
            page = scales[i, n_idx]  # [M, Hk, bs] gather
            col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, bs_), 2)
            page = jnp.where(
                col == off_idx[:, None, None], sc[:, :, None], page
            )
            scales = scales.at[i, n_idx].set(page)
        else:
            scales = scales.at[i, n_idx, :, off_idx].set(sc)
        return (vals, scales)

    def body(carry, inp):
        x, kc, vc = carry
        lp, i = inp
        q, k, v = qkv(lp, x)
        kc = write_kv(kc, k.reshape(B * T, Hk, Dh), i)
        vc = write_kv(vc, v.reshape(B * T, Hk, Dh), i)
        # attention reads the layer THROUGH the stacked cache (no layer
        # slice materialized — see attend_mlp_stacked)
        x = attend_mlp_stacked(lp, x, q, kc, vc, i)
        return (x, kc, vc), None

    (x, new_k, new_v), _ = jax.lax.scan(
        body, (x, k_cache, v_cache),
        (layer_params, jnp.arange(cfg.num_hidden_layers)),
    )

    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
    if logits_all:
        # every position's logits (speculative verify) — [B, T, V]
        return lm_head(params, x), new_k, new_v
    # logits only at each sequence's last real token
    x_last = jnp.take_along_axis(
        x, last_token_idx[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    return lm_head(params, x_last), new_k, new_v  # [B, V]


def lm_head(p: Params, x: jax.Array) -> jax.Array:
    """Final-hidden → f32 logits. Int8 tables under the Pallas impl go
    through the vocab-tiled kernel variant (its own tune key — at
    V=128256 the LM head is the single largest weight read of a decode
    step); either path rounds through the activation dtype before the
    f32 upcast, so the logits grid is identical."""
    w = p["lm_head"]
    if w.dtype == jnp.int8 and pallas_matmul_active():
        from dynamo_tpu.ops.qmatmul import qmm_lm_head

        return qmm_lm_head(
            x, w, p["lm_head_scale"], interpret=_qmm_interpret()
        ).astype(jnp.float32)
    return mm(p, "lm_head", x).astype(jnp.float32)


def moe_impl() -> str:
    """MoE formulation: DYN_MOE_IMPL = auto|dense|sparse.

    auto = sparse top-k routing (grouped matmul — FLOPs and expert
    weight reads scale with k/E). dense evaluates every expert and
    masks: compute-correct and useful as the parity oracle, but a real
    Mixtral-8x7B top-2 pays E/k = 4× the FLOPs and streams ALL expert
    weights every step (VERDICT r2 weak #4).
    """
    return os.environ.get("DYN_MOE_IMPL", "auto")


def _moe_mlp(cfg: ModelConfig, lp: Params, h: jax.Array) -> jax.Array:
    if moe_impl() == "dense":
        return _moe_mlp_dense(cfg, lp, h)
    return _moe_mlp_sparse(cfg, lp, h)


def _moe_mlp_dense(cfg: ModelConfig, lp: Params, h: jax.Array) -> jax.Array:
    """Mixtral-style sparse MoE MLP (dense-compute formulation).

    Computes router softmax over E experts, selects top-k, and evaluates
    via einsum over the expert axis with a top-k weight mask — the
    MXU-friendly formulation: no scatter/gather, experts sharded on "ep".
    """
    B, T, D = h.shape
    E, k = cfg.num_local_experts, cfg.num_experts_per_tok
    logits = (h @ lp["router"]).astype(jnp.float32)  # [B, T, E]
    weights = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(weights, k)  # [B, T, k]
    topw = topw / jnp.sum(topw, axis=-1, keepdims=True)
    # dense routing mask [B, T, E] of normalized top-k weights
    routing = (
        jnp.zeros((B, T, E), jnp.float32)
        .at[
            jnp.arange(B)[:, None, None],
            jnp.arange(T)[None, :, None],
            topi,
        ]
        .set(topw)
    ).astype(h.dtype)
    # expert compute: g/u/d per expert; einsum keeps everything batched.
    # int8 expert weights upcast in the dot with trailing-aligned
    # per-channel scales ([E, F] / [E, D] broadcast over [B, T, ...]).
    def qeinsum(eq: str, x: jax.Array, name: str) -> jax.Array:
        w = lp[name]
        if w.dtype == jnp.int8:
            y = jnp.einsum(eq, x, w.astype(x.dtype))
            return y * lp[name + "_scale"].astype(y.dtype)
        return jnp.einsum(eq, x, w)

    ge = qeinsum("btd,edf->btef", h, "w_gate")
    ue = qeinsum("btd,edf->btef", h, "w_up")
    he = jax.nn.silu(ge) * ue  # [B, T, E, F]
    oe = qeinsum("btef,efd->bted", he, "w_down")
    return jnp.einsum("bted,bte->btd", oe, routing)


def _moe_routing(cfg: ModelConfig, lp: Params, x: jax.Array):
    """Shared router: x [N, D] -> (top weights [N, k], top ids [N, k])."""
    k = cfg.num_experts_per_tok
    logits = (x @ lp["router"]).astype(jnp.float32)  # [N, E]
    weights = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(weights, k)
    topw = topw / jnp.sum(topw, axis=-1, keepdims=True)
    return topw, topi


def _grouped_mlp(
    lp: Params,
    xs: jax.Array,  # [M, D] tokens sorted by expert
    group_sizes: jax.Array,  # [E_local (+1 dead)] rows per expert
    expert_of_row: jax.Array,  # [M] expert id per sorted row (scale gather)
    pad_dead_expert: bool = False,
) -> jax.Array:
    """gate/up/down through per-expert grouped matmuls
    (jax.lax.ragged_dot): each expert's weights are read once per step
    and only its assigned rows are computed — the megablocks-style
    formulation, FLOPs/bytes ∝ assigned rows, not E.

    int8 expert weights upcast inside the dot (XLA fuses the convert
    into the operand read) with per-expert per-channel scales gathered
    per ROW. ``pad_dead_expert`` appends a zero expert for rows owned
    by other ep shards.
    """

    # bf16 ragged_dot inside a manual shard_map region crashes XLA:CPU
    # ("Invalid binary instruction opcode copy"); the virtual-mesh test
    # rung upcasts to f32 (strictly more precise), TPU stays bf16
    cpu = jax.default_backend() == "cpu"

    def gdot(name: str, inp: jax.Array) -> jax.Array:
        w = lp[name]  # [E, D, F] / [E, F, D]
        out_dtype = inp.dtype
        if pad_dead_expert:
            w = jnp.concatenate(
                [w, jnp.zeros((1, *w.shape[1:]), w.dtype)], axis=0
            )
        if w.dtype == jnp.int8:
            y = jax.lax.ragged_dot(
                inp.astype(jnp.float32) if cpu else inp,
                w.astype(jnp.float32 if cpu else inp.dtype),
                group_sizes,
                preferred_element_type=jnp.float32,
            )
            scale = lp[name + "_scale"]  # [E, out]
            if pad_dead_expert:
                scale = jnp.concatenate(
                    [scale, jnp.zeros((1, scale.shape[1]), scale.dtype)],
                    axis=0,
                )
            y = y * jnp.take(scale, expert_of_row, axis=0)
            return y.astype(out_dtype)
        if cpu:
            return jax.lax.ragged_dot(
                inp.astype(jnp.float32), w.astype(jnp.float32), group_sizes
            ).astype(out_dtype)
        return jax.lax.ragged_dot(inp, w, group_sizes)

    g = gdot("w_gate", xs)
    u = gdot("w_up", xs)
    return gdot("w_down", jax.nn.silu(g) * u)  # [M, D]


def _moe_mlp_sparse(cfg: ModelConfig, lp: Params, h: jax.Array) -> jax.Array:
    """Top-k routed MoE: sort token-expert assignments by expert, run
    grouped matmuls over contiguous per-expert row ranges, unsort and
    combine. Under an "ep" mesh axis the computation runs inside
    shard_map: each shard keeps its E/ep local experts' rows (remote
    rows go to a zero 'dead' expert) and the combine psums over "ep" —
    expert weights never leave their shard (reference analogue: the
    role of EP in SURVEY §2.6; BASELINE config 4)."""
    B, T, D = h.shape
    E, k = cfg.num_local_experts, cfg.num_experts_per_tok
    N = B * T
    x = h.reshape(N, D)
    topw, topi = _moe_routing(cfg, lp, x)

    mesh = _ATTN_MESH
    ep = mesh.shape.get("ep", 1) if mesh is not None else 1

    def local_compute(lp_l, x_l, topw_l, topi_l, shard: Optional[int]):
        """One shard's contribution. ``shard`` None = all experts."""
        e_loc = E // ep if shard is not None else E
        flat_e = topi_l.reshape(-1)  # [N*k] global expert ids
        if shard is not None:
            e0 = shard * e_loc
            local = (flat_e >= e0) & (flat_e < e0 + e_loc)
            flat_e = jnp.where(local, flat_e - e0, e_loc)  # dead = e_loc
        order = jnp.argsort(flat_e)  # stable: ties keep token order
        sorted_e = flat_e[order]
        tok_of_row = (jnp.arange(N * k) // k)[order]
        xs = jnp.take(x_l, tok_of_row, axis=0)  # [N*k, D]
        n_groups = e_loc + (1 if shard is not None else 0)
        group_sizes = jnp.bincount(sorted_e, length=n_groups)
        o = _grouped_mlp(
            lp_l, xs, group_sizes, sorted_e,
            pad_dead_expert=shard is not None,
        )  # [N*k, D]
        # unsort back to [N, k] assignment order and combine
        inv = jnp.zeros_like(order).at[order].set(jnp.arange(N * k))
        o = jnp.take(o, inv, axis=0).reshape(N, k, D)
        w = topw_l
        if shard is not None:
            keep = (topi_l >= e0) & (topi_l < e0 + e_loc)
            w = jnp.where(keep, w, 0.0)
        return jnp.sum(o * w[..., None].astype(o.dtype), axis=1)  # [N, D]

    if mesh is not None and mesh.size > 1 and E % max(ep, 1) == 0:
        # Fully-manual shard_map over BOTH "ep" and "tp": the expert
        # stacks are tp-sharded on their hidden axis too (param_specs),
        # and a partial-manual region with tp left auto crashes the
        # partitioner around ragged_dot. gate/up contract the unsharded
        # D (outputs F/tp-local, no collective); down contracts the
        # tp-sharded F, so the final psum sums over ("tp", "ep") — one
        # collective for both the hidden reduction and the expert
        # combine.
        expert_specs = {
            "w_gate": P("ep", None, "tp"),
            "w_up": P("ep", None, "tp"),
            "w_down": P("ep", "tp", None),
            "w_gate_scale": P("ep", "tp"),
            "w_up_scale": P("ep", "tp"),
            "w_down_scale": P("ep", None),
        }
        expert_keys = tuple(n for n in expert_specs if n in lp)
        lp_experts = {n: lp[n] for n in expert_keys}
        lp_specs = {n: expert_specs[n] for n in expert_keys}
        x_in = x
        if jax.default_backend() == "cpu":
            # XLA:CPU dies on bf16 operands inside this manual region
            # ("Invalid binary instruction opcode copy") — the virtual-
            # mesh test rung converts OUTSIDE the shard_map (strictly
            # more precise); TPU runs bf16 as-is
            lp_experts = {
                n: (a.astype(jnp.float32) if a.dtype == jnp.bfloat16 else a)
                for n, a in lp_experts.items()
            }
            x_in = x.astype(jnp.float32)

        def shard_fn(lp_e, x_r, topw_r, topi_r):
            shard = jax.lax.axis_index("ep")
            out = local_compute(lp_e, x_r, topw_r, topi_r, shard)
            return jax.lax.psum(out, ("ep", "tp"))

        out = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(lp_specs, P(None, None), P(None, None), P(None, None)),
            out_specs=P(None, None),
            axis_names={"ep", "tp"},
            check_vma=False,
        )(lp_experts, x_in, topw, topi).astype(h.dtype)
    else:
        out = local_compute(lp, x, topw, topi, None)
    return out.reshape(B, T, D)
