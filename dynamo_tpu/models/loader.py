"""Load HF-format (safetensors) checkpoints into the stacked-layer pytree.

Analogue of the reference's model resolution path (reference:
lib/llm/src/local_model.rs, hub.rs — resolve local dir / download), minus
the hub download (deployments mount weights locally; zero-egress builds use
random init). Torch checkpoints store linear weights as [out, in]; our
params are [in, out], so projections are transposed on load. Per-layer
tensors are stacked onto the leading L axis to match the lax.scan layout.
"""

from __future__ import annotations

import glob
import json
import logging
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import Params, param_shapes, param_specs

log = logging.getLogger("dynamo_tpu.models.loader")

# our-name -> (hf per-layer template | hf global name, transpose?)
_LAYER_MAP = {
    "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
    "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
    # qwen2-family QKV biases (only read when cfg.attention_bias)
    "bq": ("model.layers.{i}.self_attn.q_proj.bias", False),
    "bk": ("model.layers.{i}.self_attn.k_proj.bias", False),
    "bv": ("model.layers.{i}.self_attn.v_proj.bias", False),
}
# Mixtral-style MoE: router + per-expert w1(gate)/w3(up)/w2(down)
_MOE_LAYER_MAP = {
    "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
    "router": ("model.layers.{i}.block_sparse_moe.gate.weight", True),
    "w_gate": ("model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight", True),
    "w_up": ("model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight", True),
    "w_down": ("model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight", True),
    "bq": ("model.layers.{i}.self_attn.q_proj.bias", False),
    "bk": ("model.layers.{i}.self_attn.k_proj.bias", False),
    "bv": ("model.layers.{i}.self_attn.v_proj.bias", False),
}
_GLOBAL_MAP = {
    "embed": ("model.embed_tokens.weight", False),
    "final_norm": ("model.norm.weight", False),
    "lm_head": ("lm_head.weight", True),
}


def has_weights(model_dir: str) -> bool:
    return bool(glob.glob(os.path.join(model_dir, "*.safetensors")))


def resolve_model(
    model_path: str,
    model_config: Optional[ModelConfig] = None,
    random_weights: bool = False,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    specs_fn: Optional[Any] = None,
    quantize: Optional[str] = None,
):
    """Single entry for model bring-up: (ModelConfig, Params) from a
    single-file GGUF, an HF-format directory, or random init. The one
    copy of the load-priority cascade — the engine and the
    sequence-parallel prefill worker both go through here. ``specs_fn``
    maps the resolved ModelConfig to PartitionSpec overrides (e.g.
    pp-sharded layer stacks) and may validate/raise before any weight
    loads. ``quantize="int8"`` applies weight-only int8 at load
    (models/quant.py) regardless of source."""
    from dynamo_tpu.models.llama import init_params

    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantization {quantize!r}")
    if model_path and not random_weights:
        # repo-id paths resolve through the (gated) hub cache
        from dynamo_tpu.models.hub import resolve_hub_model

        model_path = resolve_hub_model(model_path)
    is_gguf = bool(model_path) and model_path.endswith(".gguf")
    reader = None
    try:
        if is_gguf and (model_config is None or not random_weights):
            # one reader for config AND weights: header parsing decodes
            # the full embedded vocab, don't pay it twice — and don't
            # pay it at all when neither is needed
            from dynamo_tpu.gguf import GGUFReader

            reader = GGUFReader(model_path)
        if model_config is None:
            if reader is not None:
                from dynamo_tpu.gguf import config_from_gguf

                model_config = config_from_gguf(reader)
            else:
                model_config = ModelConfig.from_dir(model_path)
        specs = specs_fn(model_config) if specs_fn is not None else None
        if not random_weights and reader is not None:
            from dynamo_tpu.gguf import load_params_from_gguf

            params = load_params_from_gguf(
                model_config, reader, mesh, specs, quantize=quantize
            )
        elif not random_weights and model_path and has_weights(model_path):
            # multi-process bring-up defaults to the shard-aware loader:
            # every rank materializing the full stacked weights would
            # need ~model-size host RAM per host (70B int8 = ~70 GB).
            # Force on/off with DYN_SHARDED_LOAD=1/0.
            knob = os.environ.get("DYN_SHARDED_LOAD", "")
            sharded = (
                knob == "1"
                or (knob != "0" and mesh is not None
                    and jax.process_count() > 1)
            )
            if sharded and mesh is not None:
                params = load_params_sharded(
                    model_config, model_path, mesh, specs, quantize=quantize
                )
            else:
                params = load_params(
                    model_config, model_path, mesh, specs, quantize=quantize
                )
        elif quantize == "int8":
            # host-side quantized random init: the bf16 pytree must
            # never materialize on device (8B bf16 > one 16 GB chip)
            log.warning("initializing RANDOM int8 weights (no checkpoint)")
            from dynamo_tpu.models.quant import init_params_quantized

            params = init_params_quantized(model_config, seed, mesh, specs)
        else:
            log.warning("initializing RANDOM weights (no checkpoint found)")
            params = init_params(model_config, seed, mesh, specs)
        return model_config, params
    finally:
        if reader is not None:
            reader.close()


class _ShardedCheckpoint:
    """Lazily reads tensors across sharded safetensors files."""

    def __init__(self, model_dir: str):
        self.files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
        if not self.files:
            raise FileNotFoundError(f"no *.safetensors under {model_dir}")
        index_path = os.path.join(model_dir, "model.safetensors.index.json")
        self._name_to_file: dict[str, str] = {}
        if os.path.exists(index_path):
            with open(index_path) as f:
                weight_map = json.load(f)["weight_map"]
            self._name_to_file = {
                k: os.path.join(model_dir, v) for k, v in weight_map.items()
            }
        else:
            from safetensors import safe_open

            for path in self.files:
                with safe_open(path, framework="np") as f:
                    for name in f.keys():
                        self._name_to_file[name] = path
        self._open_handles: dict[str, Any] = {}
        # VLM checkpoints (LLaVA layout) prefix the language model's
        # weights: the standard llama maps resolve transparently
        self._prefix = (
            "language_model."
            if "language_model.model.embed_tokens.weight" in self._name_to_file
            else ""
        )

    def names(self) -> set[str]:
        if not self._prefix:
            return set(self._name_to_file)
        return {
            n[len(self._prefix):] if n.startswith(self._prefix) else n
            for n in self._name_to_file
        }

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        if name not in self._name_to_file:
            name = self._prefix + name
        path = self._name_to_file[name]
        handle = self._open_handles.get(path)
        if handle is None:
            handle = safe_open(path, framework="np")
            self._open_handles[path] = handle
        return handle.get_tensor(name)


def _to_jax(arr: np.ndarray, dtype) -> jnp.ndarray:
    if arr.dtype == np.uint16:
        # numpy has no bfloat16: reinterpret via jax
        return jax.lax.bitcast_convert_type(jnp.asarray(arr), jnp.bfloat16).astype(dtype)
    return jnp.asarray(arr, dtype=dtype)


def load_params(
    cfg: ModelConfig, model_dir: str, mesh: Optional[Mesh] = None,
    specs: Optional[dict] = None, quantize: Optional[str] = None,
) -> Params:
    """Load and stack weights; device_put with shardings as we go so the
    full f32 copy never materializes on one device. ``specs`` overrides
    the default TP PartitionSpecs (e.g. pp-sharded layer stacks).
    ``quantize="int8"`` quantizes matmul weights per layer ON THE HOST
    (models/quant.py) so the device only ever holds int8 + scales — the
    real 8B flagship fits one 16 GB chip this way."""
    from dynamo_tpu.models import quant

    ckpt = _ShardedCheckpoint(model_dir)
    shapes = param_shapes(cfg)
    specs = specs if specs is not None else param_specs(cfg)
    params: Params = {}

    def quantizing(name: str) -> bool:
        return quantize == "int8" and name in quant.QUANT_AXIS

    def put(name: str, arr: jnp.ndarray) -> jnp.ndarray:
        shape, dtype = shapes[name]
        arr = arr.astype(dtype)
        if arr.shape != shape:
            raise ValueError(f"{name}: expected {shape}, got {arr.shape}")
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, specs[name]))
        return arr

    def put_q(name: str, q_np: np.ndarray, s_np: np.ndarray) -> None:
        shape, _ = shapes[name]
        if q_np.shape != shape:
            raise ValueError(f"{name}: expected {shape}, got {q_np.shape}")
        qa, sa = jnp.asarray(q_np), jnp.asarray(s_np)
        if mesh is not None:
            wspec = specs[name]
            qa = jax.device_put(qa, NamedSharding(mesh, wspec))
            sa = jax.device_put(
                sa,
                NamedSharding(
                    mesh, quant.scale_spec(wspec, quant.QUANT_AXIS[name])
                ),
            )
        params[name] = qa
        params[name + quant.SCALE_SUFFIX] = sa

    def host_f32(hf_name: str, transpose: bool) -> np.ndarray:
        arr = quant.np_to_f32(ckpt.get(hf_name))
        return arr.T if transpose else arr

    for name, (hf_name, transpose) in _GLOBAL_MAP.items():
        if name == "lm_head" and hf_name not in ckpt.names():
            # tied embeddings. Quantized: lm_head = embed.T per-row
            # scales == embed's per-row scales (both reduce over D)
            if quantizing(name):
                put_q(
                    name,
                    np.asarray(params["embed"]).T,
                    np.asarray(params["embed" + quant.SCALE_SUFFIX]),
                )
            else:
                params[name] = put(name, params["embed"].T)
            continue
        if quantizing(name):
            q, s = quant.quantize_array(
                host_f32(hf_name, transpose), quant.QUANT_AXIS[name]
            )
            put_q(name, q, s)
            continue
        arr = _to_jax(ckpt.get(hf_name), shapes[name][1])
        if transpose:
            arr = arr.T
        params[name] = put(name, arr)

    L = cfg.num_hidden_layers
    layer_map = _MOE_LAYER_MAP if cfg.is_moe else _LAYER_MAP
    for name, (tmpl, transpose) in layer_map.items():
        if name not in shapes:
            continue
        if quantizing(name):
            # per-layer host quantization == quantizing the stacked
            # tensor (scales reduce only the contraction axis), with
            # peak host memory of one layer's f32 copy
            qs, ss = [], []
            for i in range(L):
                if "{e}" in tmpl:
                    eq, es = [], []
                    for e in range(cfg.num_local_experts):
                        q, s = quant.quantize_array(
                            host_f32(tmpl.format(i=i, e=e), transpose), -2
                        )
                        eq.append(q)
                        es.append(s)
                    qs.append(np.stack(eq))
                    ss.append(np.stack(es))
                else:
                    q, s = quant.quantize_array(
                        host_f32(tmpl.format(i=i), transpose), -2
                    )
                    qs.append(q)
                    ss.append(s)
            put_q(name, np.stack(qs), np.stack(ss))
            continue
        per_layer = []
        for i in range(L):
            if "{e}" in tmpl:
                # stack experts: [E, in, out]
                per_expert = []
                for e in range(cfg.num_local_experts):
                    arr = _to_jax(ckpt.get(tmpl.format(i=i, e=e)), shapes[name][1])
                    per_expert.append(arr.T if transpose else arr)
                per_layer.append(jnp.stack(per_expert))
            else:
                arr = _to_jax(ckpt.get(tmpl.format(i=i)), shapes[name][1])
                per_layer.append(arr.T if transpose else arr)
        params[name] = put(name, jnp.stack(per_layer))
    missing = set(shapes) - {k for k in params if not quant.is_quantized_name(k)}
    if missing:
        raise ValueError(
            f"checkpoint {model_dir} missing params: {sorted(missing)}"
        )
    log.info("loaded %d params from %s", len(params), model_dir)
    return params


def load_params_sharded(
    cfg: ModelConfig, model_dir: str, mesh: Mesh,
    specs: Optional[dict] = None, quantize: Optional[str] = None,
) -> Params:
    """Shard-aware checkpoint load for big models (the 70B ladder,
    BASELINE config 3): each process materializes ONLY the weight
    slices its addressable devices own, via safetensors partial reads
    driven by ``jax.make_array_from_callback`` — no host ever holds a
    full stacked tensor. Peak host memory:

    - unquantized: one SHARD of one stacked tensor at a time;
    - int8: one LAYER's f32 copy (global per-channel scales need the
      full contraction axis — e.g. wo/w_down shard the contraction
      dim, and slice-local scales would change the numerics) plus the
      accumulated local int8 shards — for 70B int8 on a 16-process
      v5e-16 that is ~0.9 GB transient + ~4.4 GB/process of shards vs
      ~70 GB/process for the stacked loader (docs/multihost.md has the
      full budget math).

    Produces arrays indistinguishable from ``load_params`` (same
    global values, same shardings). Reference role: multi-node engine
    bring-up where each rank loads its slice
    (launch/dynamo-run/src/lib.rs:141-160 MultiNodeConfig)."""
    from dynamo_tpu.models import quant

    ckpt = _ShardedCheckpoint(model_dir)
    shapes = param_shapes(cfg)
    specs = specs if specs is not None else param_specs(cfg)
    params: Params = {}
    L = cfg.num_hidden_layers
    names = ckpt.names()

    def read_slice(hf_name: str, transpose: bool, idx: tuple) -> np.ndarray:
        """Partial-read one tensor's [idx] in OUR orientation (HF linear
        weights are [out, in]; ours [in, out] — swap the slices, read,
        transpose)."""
        from safetensors import safe_open

        if hf_name not in ckpt._name_to_file:
            hf_name = ckpt._prefix + hf_name
        path = ckpt._name_to_file[hf_name]
        handle = ckpt._open_handles.get(path)
        if handle is None:
            handle = safe_open(path, framework="np")
            ckpt._open_handles[path] = handle
        sl = handle.get_slice(hf_name)
        if transpose:
            assert len(idx) == 2
            arr = sl[idx[1], idx[0]]
            arr = np.ascontiguousarray(np.asarray(arr).T)
        else:
            arr = np.asarray(sl[idx])
        return arr

    def to_np_dtype(arr: np.ndarray, dtype) -> np.ndarray:
        if arr.dtype == np.uint16:  # bf16 raw bits
            arr = quant.np_to_f32(arr)
        return np.asarray(
            jnp.asarray(arr).astype(dtype)
        )

    def build(name: str, shape, dtype, cb) -> jnp.ndarray:
        sharding = NamedSharding(mesh, specs.get(name) or P_EMPTY)
        return jax.make_array_from_callback(shape, sharding, cb)

    def add_plain(name: str, tmpl: str, transpose: bool) -> None:
        shape, dtype = shapes[name]
        E = cfg.num_local_experts

        def cb(index):
            if "{e}" in tmpl:
                # expert stack [L, E, in, out]: dims 0/1 = layer/expert;
                # each (layer, expert) is its own checkpoint tensor, so
                # the ep×tp shard reads only its expert slices' slices
                l_sl, e_sl = index[0], index[1]
                rest = tuple(index[2:])
                out = np.stack([
                    np.stack([
                        read_slice(
                            tmpl.format(i=i, e=e), transpose, rest
                        )
                        for e in range(*e_sl.indices(E))
                    ])
                    for i in range(*l_sl.indices(L))
                ])
            elif "{i}" in tmpl:  # stacked per-layer tensor: dim 0 = layer
                l_sl = index[0]
                rest = tuple(index[1:])
                layers = range(*l_sl.indices(L))
                parts = [
                    read_slice(tmpl.format(i=i), transpose, rest)
                    for i in layers
                ]
                out = np.stack(parts)
            else:
                out = read_slice(tmpl, transpose, tuple(index))
            return to_np_dtype(out, dtype)

        params[name] = build(name, shape, dtype, cb)

    def _assemble(shape, sharding, fill) -> jax.Array:
        """Build a sharded array by filling each LOCAL shard from
        ``fill(global_index) -> np.ndarray`` and assembling — the
        slicing orientation of make_array_from_callback without its
        one-callback-invocation-per-array structure (which would force
        re-deriving expensive intermediates per shard)."""
        dev_map = sharding.addressable_devices_indices_map(shape)
        arrays = [
            jax.device_put(fill(idx), d) for d, idx in dev_map.items()
        ]
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays
        )

    def add_quantized(name: str, tmpl: str, transpose: bool,
                      tied_embed: bool = False) -> None:
        """int8 path: quantize each (layer) tensor exactly ONCE — global
        per-channel scales need the full contraction axis, which tp
        shards for wo/w_down — then hand every local shard its slice.
        Host transient: one layer's f32 + the local int8 shards."""
        shape, _ = shapes[name]
        # QUANT_AXIS is relative to the UNSTACKED tensor (e.g. -2 = the
        # contraction dim of one layer); for stacked tensors negative
        # axes line up unchanged
        axis = quant.QUANT_AXIS[name]
        wspec = specs[name]
        s_axis = axis if axis >= 0 else len(shape) + axis
        s_shape = shape[:s_axis] + shape[s_axis + 1 :]
        q_sh = NamedSharding(mesh, wspec)
        s_sh = NamedSharding(mesh, quant.scale_spec(wspec, axis))
        if "{i}" not in tmpl:
            full = quant.np_to_f32(ckpt.get(tmpl))
            if transpose or tied_embed:
                full = full.T
            q, s = quant.quantize_array(full, axis)
            del full
            params[name] = _assemble(shape, q_sh, lambda idx: q[idx])
            params[name + quant.SCALE_SUFFIX] = _assemble(
                s_shape, s_sh, lambda idx: s[idx]
            )
            return
        # stacked per-layer (and per-expert): quantize tensor-by-tensor,
        # append each local shard's slice as we go. Expert stacks
        # [L, E, ...] iterate (layer, expert) pairs layer-major; the
        # local parts list reshapes back to its [l_local, e_local, ...]
        # block. Host transient stays ONE unstacked tensor's f32.
        E = cfg.num_local_experts
        experts = "{e}" in tmpl
        q_map = q_sh.addressable_devices_indices_map(shape)
        s_map = s_sh.addressable_devices_indices_map(s_shape)
        q_parts: dict = {d: [] for d in q_map}
        s_parts: dict = {d: [] for d in s_map}
        pairs = (
            [(i, e) for i in range(L) for e in range(E)]
            if experts else [(i, None) for i in range(L)]
        )
        for i, e in pairs:
            raw = ckpt.get(
                tmpl.format(i=i, e=e) if experts else tmpl.format(i=i)
            )
            full = quant.np_to_f32(raw)
            if transpose:
                full = full.T
            q, s = quant.quantize_array(full, axis)
            del full
            lead = 2 if experts else 1

            def want(idx) -> bool:
                if i not in range(*idx[0].indices(L)):
                    return False
                return not experts or e in range(*idx[1].indices(E))

            for d, idx in q_map.items():
                if want(idx):
                    q_parts[d].append(q[tuple(idx[lead:])])
            for d, idx in s_map.items():
                if want(idx):
                    s_parts[d].append(s[tuple(idx[lead:])])

        def assemble(parts_map, index_map, full_shape, sharding):
            arrays = []
            for d, idx in index_map.items():
                stacked = np.stack(parts_map[d])
                if experts:
                    n_l = len(range(*idx[0].indices(L)))
                    n_e = len(range(*idx[1].indices(E)))
                    stacked = stacked.reshape(
                        n_l, n_e, *stacked.shape[1:]
                    )
                arrays.append(jax.device_put(stacked, d))
            return jax.make_array_from_single_device_arrays(
                full_shape, sharding, arrays
            )

        params[name] = assemble(q_parts, q_map, shape, q_sh)
        params[name + quant.SCALE_SUFFIX] = assemble(
            s_parts, s_map, s_shape, s_sh
        )

    def quantizing(name: str) -> bool:
        return quantize == "int8" and name in quant.QUANT_AXIS

    from jax.sharding import PartitionSpec as P_CLS

    P_EMPTY = P_CLS()

    for name, (hf_name, transpose) in _GLOBAL_MAP.items():
        if name == "lm_head" and hf_name not in names:
            # tied embeddings: lm_head[idx] = embed.T[idx]
            e_tmpl, _ = _GLOBAL_MAP["embed"]
            shape, dtype = shapes[name]
            if quantizing(name):
                # embed is [V, D]; tied lm_head is its transpose
                add_quantized(name, e_tmpl, transpose=False, tied_embed=True)
            else:

                def cb_t(index):
                    # swap slices: embed is [V, D], lm_head [D, V]
                    arr = read_slice(e_tmpl, True, tuple(index))
                    return to_np_dtype(arr, dtype)

                params[name] = build(name, shape, dtype, cb_t)
            continue
        if quantizing(name):
            add_quantized(name, hf_name, transpose)
        else:
            add_plain(name, hf_name, transpose)

    layer_map = _MOE_LAYER_MAP if cfg.is_moe else _LAYER_MAP
    for name, (tmpl, transpose) in layer_map.items():
        if name not in shapes:
            continue
        if quantizing(name):
            add_quantized(name, tmpl, transpose)
        else:
            add_plain(name, tmpl, transpose)
    missing = set(shapes) - {
        k for k in params if not quant.is_quantized_name(k)
    }
    if missing:
        raise ValueError(
            f"checkpoint {model_dir} missing params: {sorted(missing)}"
        )
    log.info(
        "sharded-loaded %d params from %s (local shards only)",
        len(params), model_dir,
    )
    return params
