"""Weight-only int8 quantization for the serving path.

Symmetric per-channel int8: each matmul weight stores an int8 tensor plus
an f32 scale per output channel (per vocab row for the embedding table).
The matmul runs in bf16 on the MXU with the int8 weight upcast on the fly
— HBM reads halve, which directly doubles the decode-throughput roofline
of a bandwidth-bound engine, and the real 8B flagship shape fits a single
16 GB v5e chip (bf16 does not).

The reference reaches the same operating point externally (FP8/AWQ
checkpoints served through vLLM/TRT-LLM, e.g. the
R1-Distill-Llama-70B-FP8-dynamic benchmark model,
examples/llm/benchmarks/README.md); here quantization is a first-class
engine knob (EngineConfig.quantization = "int8") applied at load time to
any bf16/f32 checkpoint.

Numerics: scale = amax/127 over the contraction axis, round-to-nearest,
error ~0.4% per weight — logits track bf16 closely (see
tests/test_quantization.py for the bound enforced in CI).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

# weight name -> contraction axis reduced over when computing scales
# (the scale then broadcasts over the matmul output's channel axis)
QUANT_AXIS: dict[str, int] = {
    "wq": -2,
    "wk": -2,
    "wv": -2,
    "wo": -2,
    "w_gate": -2,
    "w_up": -2,
    "w_down": -2,
    "lm_head": -2,
    # embedding rows are gathered, not contracted: per-row scales,
    # applied to the gathered rows after lookup
    "embed": -1,
}

SCALE_SUFFIX = "_scale"


def is_quantized_name(name: str) -> bool:
    return name.endswith(SCALE_SUFFIX)


def np_to_f32(arr: np.ndarray) -> np.ndarray:
    """Checkpoint array -> f32, handling bf16 stored as raw uint16."""
    if arr.dtype == np.uint16:
        return (arr.astype(np.uint32) << 16).view(np.float32)
    return np.asarray(arr, np.float32)


def quantize_array(
    arr: np.ndarray, axis: int
) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-channel int8: -> (int8 values, f32 scales with
    ``axis`` dropped)."""
    a = np_to_f32(arr)
    amax = np.max(np.abs(a), axis=axis, keepdims=True)
    scale = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, np.squeeze(scale, axis=axis).astype(np.float32)


def scale_spec(weight_spec, axis: int):
    """PartitionSpec for a scale tensor: the weight's spec with the
    contraction axis dropped (scales follow the output-channel sharding)."""
    from jax.sharding import PartitionSpec as P

    entries = list(weight_spec)
    del entries[axis]
    return P(*entries)


def init_params_quantized(
    cfg,
    seed: int = 0,
    mesh=None,
    specs: Optional[dict] = None,
):
    """Random-init already-quantized params (bench/tests without a
    checkpoint). Unlike init_params→quantize, the full bf16 pytree is
    NEVER materialized — the 8B flagship shape in bf16 would not fit the
    single 16 GB chip that int8 serving targets. Weights generate AND
    quantize on device, one leading slice at a time (f32 transient ≈ one
    layer), so nothing big crosses the (slow, tunneled) host↔device
    link."""
    import math

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from dynamo_tpu.models.llama import param_shapes, param_specs

    shapes = param_shapes(cfg)
    specs = specs if specs is not None else param_specs(cfg)
    key = jax.random.PRNGKey(seed)
    params: dict[str, Any] = {}

    def gen_slice(k, shape, std):
        return jax.random.normal(k, shape, jnp.float32) * std

    def dev_quantize(arr, axis):
        amax = jnp.max(jnp.abs(arr), axis=axis, keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(arr / scale), -127, 127).astype(jnp.int8)
        return q, jnp.squeeze(scale, axis=axis)

    def put(name: str, arr, spec) -> Any:
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, spec))
        return arr

    for i, (name, (shape, dtype)) in enumerate(shapes.items()):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        std = 1.0 / math.sqrt(max(1, fan_in))
        k_name = jax.random.fold_in(key, i)
        if name not in QUANT_AXIS:
            if name.endswith("norm"):
                arr = jnp.ones(shape, dtype)
            else:
                arr = gen_slice(k_name, shape, std).astype(dtype)
            params[name] = put(name, arr, specs[name])
            continue
        axis = QUANT_AXIS[name]
        gq = jax.jit(lambda k: dev_quantize(gen_slice(k, shape[1:], std), axis)) \
            if len(shape) >= 3 else None
        if len(shape) >= 3:
            # stacked (leading L / L,E): slice-wise to bound the f32
            # transient to one layer
            qs, ss = [], []
            for j in range(shape[0]):
                q, s = gq(jax.random.fold_in(k_name, j))
                qs.append(q)
                ss.append(s)
            q_arr, s_arr = jnp.stack(qs), jnp.stack(ss)
        else:
            q_arr, s_arr = jax.jit(
                lambda k: dev_quantize(gen_slice(k, shape, std), axis)
            )(k_name)
        params[name] = put(name, q_arr, specs[name])
        params[name + SCALE_SUFFIX] = put(
            name + SCALE_SUFFIX, s_arr, scale_spec(specs[name], axis)
        )
    return params


def quantize_params_pytree(
    params: dict[str, Any],
    mesh=None,
    specs: Optional[dict] = None,
) -> dict[str, Any]:
    """Quantize an already-materialized (e.g. random-init) param pytree.
    Device arrays round-trip through the host; use the loader's streaming
    path for real checkpoints."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    out: dict[str, Any] = {}
    for name, arr in params.items():
        if name not in QUANT_AXIS:
            out[name] = arr
            continue
        axis = QUANT_AXIS[name]
        host = np.asarray(jnp.asarray(arr, jnp.float32))
        q, s = quantize_array(host, axis)
        qj, sj = jnp.asarray(q), jnp.asarray(s)
        if mesh is not None and specs is not None:
            wspec = specs[name]
            qj = jax.device_put(qj, NamedSharding(mesh, wspec))
            sj = jax.device_put(
                sj, NamedSharding(mesh, scale_spec(wspec, axis))
            )
        out[name] = qj
        out[name + SCALE_SUFFIX] = sj
    return out
