"""ViT vision tower + projector for vision-language serving.

The native analogue of the reference's multimodal pipeline (reference:
examples/multimodal — a dedicated encode worker runs a vision encoder
and ships image embeddings to the LLM worker, which injects them at
``<image>`` placeholder positions). Here the tower is a functional JAX
ViT in the same style as models/llama.py: layers stacked on a leading
axis, one ``lax.scan`` over the transformer body, bf16 matmuls with f32
layernorms/softmax. Patchify is a reshape + one matmul (not a conv):
that is the MXU-native formulation.

A two-layer GELU MLP projector maps vision hidden size to the language
model's hidden size (LLaVA-style), so ``encode_images`` output can be
spliced directly into the decoder's embedding stream.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclass
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    layer_norm_eps: float = 1e-5
    projection_dim: int = 4096  # language-model hidden size
    # CLIP prepends a learned class token (sees attention, dropped from
    # the patch features afterwards — LLaVA's feature-select semantics)
    use_class_token: bool = False
    # whether the final layernorm applies before the projector: LLaVA's
    # default vision_feature_layer=-2 taps the PENULTIMATE hidden state,
    # bypassing post_layernorm
    apply_post_ln: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size

    @classmethod
    def from_dict(cls, raw: dict) -> "VisionConfig":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in raw.items() if k in known})


def vision_param_shapes(cfg: VisionConfig) -> dict[str, tuple[tuple[int, ...], Any]]:
    L, D, F = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
    P = cfg.projection_dim
    bf16 = jnp.bfloat16
    n_pos = cfg.num_patches + (1 if cfg.use_class_token else 0)
    shapes_head = (
        {"class_embed": ((D,), jnp.float32)} if cfg.use_class_token else {}
    )
    return {
        **shapes_head,
        "patch_embed": ((cfg.patch_dim, D), bf16),
        "pos_embed": ((n_pos, D), jnp.float32),
        "ln_pre": ((2, D), jnp.float32),  # [scale, bias]
        "wq": ((L, D, D), bf16),
        "bq": ((L, D), bf16),
        "wk": ((L, D, D), bf16),
        "bk": ((L, D), bf16),
        "wv": ((L, D, D), bf16),
        "bv": ((L, D), bf16),
        "wo": ((L, D, D), bf16),
        "bo": ((L, D), bf16),
        "ln1": ((L, 2, D), jnp.float32),
        "ln2": ((L, 2, D), jnp.float32),
        "mlp_up": ((L, D, F), bf16),
        "mlp_up_b": ((L, F), bf16),
        "mlp_down": ((L, F, D), bf16),
        "mlp_down_b": ((L, D), bf16),
        "ln_post": ((2, D), jnp.float32),
        "proj_1": ((D, P), bf16),
        "proj_1_b": ((P,), bf16),
        "proj_2": ((P, P), bf16),
        "proj_2_b": ((P,), bf16),
    }


def init_vision_params(cfg: VisionConfig, seed: int = 0) -> Params:
    shapes = vision_param_shapes(cfg)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(shapes))
    params: Params = {}
    for (name, (shape, dtype)), k in zip(shapes.items(), keys):
        if name.startswith("ln"):
            # [scale=1, bias=0]
            arr = jnp.stack(
                [jnp.ones(shape[-1:], dtype), jnp.zeros(shape[-1:], dtype)]
            )
            arr = jnp.broadcast_to(arr, shape).astype(dtype)
        elif name.endswith("_b") or name.startswith("b"):
            arr = jnp.zeros(shape, dtype)
        else:
            fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
            scale = 1.0 / math.sqrt(max(1, fan_in))
            arr = (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)
        params[name] = arr
    return params


def load_vision_params(cfg: VisionConfig, path: str) -> Params:
    """Load tower weights from an .npz archive keyed by
    ``vision_param_shapes`` names (the projector-merged export format;
    HF CLIP checkpoints convert offline with a rename+stack script)."""
    import numpy as np

    shapes = vision_param_shapes(cfg)
    with np.load(path) as data:
        missing = set(shapes) - set(data.files)
        if missing:
            raise ValueError(f"{path} missing vision params: {sorted(missing)}")
        params: Params = {}
        for name, (shape, dtype) in shapes.items():
            arr = data[name]
            if arr.shape != shape:
                raise ValueError(
                    f"{name}: expected {shape}, got {arr.shape}"
                )
            params[name] = jnp.asarray(arr, dtype=dtype)
    return params


def load_vision_hf(model_dir: str) -> tuple[VisionConfig, Params]:
    """Load the vision tower + projector from a REAL VLM checkpoint
    directory (LLaVA layout: CLIP tower under
    ``vision_tower.vision_model.*``, projector under
    ``multi_modal_projector.*`` — reference: examples/multimodal serves
    such checkpoints through its encode worker).

    Mapping notes:
    - the conv patch embedding [D, 3, p, p] becomes our reshape-matmul
      patch_embed [p*p*3, D] (pixels patchify row-major (p, p, 3));
    - the class token participates in attention exactly as in CLIP and
      is dropped from the features afterwards (LLaVA feature select);
    - ``vision_feature_layer`` (default -2) is honored by truncating
      the layer stack and skipping post_layernorm — HF taps the
      PENULTIMATE hidden state for the projector;
    - projection_dim comes from the projector weight itself, not the
      config (real llava text_configs are sparse, and CLIP's own
      ``projection_dim`` key means its contrastive head);
    - nn.Linear weights are [out, in] and transpose into our [in, out].
    """
    import json
    import os

    import numpy as np

    with open(os.path.join(model_dir, "config.json")) as f:
        raw = json.load(f)
    vraw = dict(raw.get("vision_config") or raw)
    vraw.pop("projection_dim", None)  # CLIP's contrastive head, not ours
    vcfg = VisionConfig.from_dict(vraw)
    vcfg.use_class_token = True

    from dynamo_tpu.models.loader import _ShardedCheckpoint

    ckpt = _ShardedCheckpoint(model_dir)
    names = ckpt.names()
    vt = "vision_tower.vision_model."
    if not any(n.startswith(vt) for n in names):
        raise ValueError(
            f"{model_dir} has no {vt}* weights — not a LLaVA-layout VLM"
        )
    # vision_feature_layer: -2 = penultimate hidden state, no post-LN
    feature_layer = int(raw.get("vision_feature_layer", -2))
    if feature_layer < 0:
        n_layers = vcfg.num_hidden_layers + 1 + feature_layer
    else:
        n_layers = feature_layer
    if not 0 < n_layers <= vcfg.num_hidden_layers:
        raise ValueError(
            f"vision_feature_layer={feature_layer} out of range for "
            f"{vcfg.num_hidden_layers} layers"
        )
    # HF's hidden_states tuple is always PRE-post_layernorm — LLaVA
    # feature select never applies it, not even for the last layer
    vcfg.apply_post_ln = False
    vcfg.num_hidden_layers = n_layers

    def t(name: str) -> np.ndarray:
        from dynamo_tpu.models.quant import np_to_f32

        return np_to_f32(ckpt.get(name))

    def lin(prefix: str):  # nn.Linear -> (w [in, out], b [out])
        return t(prefix + ".weight").T, t(prefix + ".bias")

    def ln(prefix: str) -> np.ndarray:  # [2, D] = [scale, bias]
        return np.stack([t(prefix + ".weight"), t(prefix + ".bias")])

    p: dict = {}
    conv = t(vt + "embeddings.patch_embedding.weight")  # [D, 3, p, p]
    p["patch_embed"] = conv.transpose(2, 3, 1, 0).reshape(
        vcfg.patch_dim, vcfg.hidden_size
    )
    p["class_embed"] = t(vt + "embeddings.class_embedding").reshape(-1)
    p["pos_embed"] = t(vt + "embeddings.position_embedding.weight")
    # CLIP's attribute really is spelled "pre_layrnorm"
    p["ln_pre"] = ln(vt + "pre_layrnorm")
    p["ln_post"] = ln(vt + "post_layernorm")
    per_layer: dict[str, list] = {
        k: [] for k in (
            "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
            "ln1", "ln2", "mlp_up", "mlp_up_b", "mlp_down", "mlp_down_b",
        )
    }
    for i in range(vcfg.num_hidden_layers):
        lp = f"{vt}encoder.layers.{i}."
        for ours, theirs in (
            ("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj"),
            ("o", "out_proj"),
        ):
            w, b = lin(lp + "self_attn." + theirs)
            per_layer["w" + ours].append(w)
            per_layer["b" + ours].append(b)
        per_layer["ln1"].append(ln(lp + "layer_norm1"))
        per_layer["ln2"].append(ln(lp + "layer_norm2"))
        w, b = lin(lp + "mlp.fc1")
        per_layer["mlp_up"].append(w)
        per_layer["mlp_up_b"].append(b)
        w, b = lin(lp + "mlp.fc2")
        per_layer["mlp_down"].append(w)
        per_layer["mlp_down_b"].append(b)
    for k, v in per_layer.items():
        p[k] = np.stack(v)
    w, b = lin("multi_modal_projector.linear_1")
    p["proj_1"], p["proj_1_b"] = w, b
    # projection dim = the projector's actual output width (the
    # language hidden size); sparse real-world configs don't carry it
    vcfg.projection_dim = int(w.shape[1])
    w, b = lin("multi_modal_projector.linear_2")
    p["proj_2"], p["proj_2_b"] = w, b

    shapes = vision_param_shapes(vcfg)
    params: Params = {}
    for name, (shape, dtype) in shapes.items():
        arr = p[name]
        if tuple(arr.shape) != shape:
            raise ValueError(f"{name}: expected {shape}, got {arr.shape}")
        params[name] = jnp.asarray(arr, dtype=dtype)
    return vcfg, params


def _layernorm(x: jax.Array, ln: jax.Array, eps: float) -> jax.Array:
    """ln: [2, D] = [scale, bias]."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * ln[0] + ln[1]
    return out.astype(x.dtype)


def patchify(cfg: VisionConfig, pixels: jax.Array) -> jax.Array:
    """[B, H, W, 3] -> [B, n_patches, patch_dim] (reshape-only, no conv)."""
    B = pixels.shape[0]
    g = cfg.image_size // cfg.patch_size
    p = cfg.patch_size
    x = pixels.reshape(B, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, g, g, p, p, 3]
    return x.reshape(B, g * g, p * p * 3)


def encode_images(cfg: VisionConfig, params: Params, pixels: jax.Array) -> jax.Array:
    """[B, H, W, 3] float pixels -> [B, n_patches, projection_dim]."""
    eps = cfg.layer_norm_eps
    H = cfg.num_attention_heads
    D = cfg.hidden_size
    Dh = D // H

    x = patchify(cfg, pixels).astype(jnp.bfloat16) @ params["patch_embed"]
    if cfg.use_class_token:
        cls = jnp.broadcast_to(
            params["class_embed"].astype(x.dtype)[None, None, :],
            (x.shape[0], 1, x.shape[-1]),
        )
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(x.dtype)
    x = _layernorm(x, params["ln_pre"], eps)

    def layer_fn(x, lp):
        B, T = x.shape[0], x.shape[1]
        h = _layernorm(x, lp["ln1"], eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(B, T, H, Dh)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B, T, H, Dh)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B, T, H, Dh)
        scores = jnp.einsum(
            "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(Dh)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, D)
        x = x + (attn @ lp["wo"] + lp["bo"]).astype(x.dtype)
        h = _layernorm(x, lp["ln2"], eps)
        mlp = jax.nn.gelu(h @ lp["mlp_up"] + lp["mlp_up_b"]) @ lp["mlp_down"]
        x = x + (mlp + lp["mlp_down_b"]).astype(x.dtype)
        return x, None

    layer_names = [
        "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
        "ln1", "ln2", "mlp_up", "mlp_up_b", "mlp_down", "mlp_down_b",
    ]
    x, _ = jax.lax.scan(layer_fn, x, {n: params[n] for n in layer_names})
    if cfg.apply_post_ln:
        x = _layernorm(x, params["ln_post"], eps)
    if cfg.use_class_token:
        x = x[:, 1:]  # feature-select: drop the class token's slot
    # LLaVA-style projector into the language model's embedding space
    x = jax.nn.gelu(x @ params["proj_1"] + params["proj_1_b"])
    x = x @ params["proj_2"] + params["proj_2_b"]
    return x
