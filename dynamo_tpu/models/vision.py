"""ViT vision tower + projector for vision-language serving.

The native analogue of the reference's multimodal pipeline (reference:
examples/multimodal — a dedicated encode worker runs a vision encoder
and ships image embeddings to the LLM worker, which injects them at
``<image>`` placeholder positions). Here the tower is a functional JAX
ViT in the same style as models/llama.py: layers stacked on a leading
axis, one ``lax.scan`` over the transformer body, bf16 matmuls with f32
layernorms/softmax. Patchify is a reshape + one matmul (not a conv):
that is the MXU-native formulation.

A two-layer GELU MLP projector maps vision hidden size to the language
model's hidden size (LLaVA-style), so ``encode_images`` output can be
spliced directly into the decoder's embedding stream.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclass
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    layer_norm_eps: float = 1e-5
    projection_dim: int = 4096  # language-model hidden size

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size

    @classmethod
    def from_dict(cls, raw: dict) -> "VisionConfig":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in raw.items() if k in known})


def vision_param_shapes(cfg: VisionConfig) -> dict[str, tuple[tuple[int, ...], Any]]:
    L, D, F = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
    P = cfg.projection_dim
    bf16 = jnp.bfloat16
    return {
        "patch_embed": ((cfg.patch_dim, D), bf16),
        "pos_embed": ((cfg.num_patches, D), jnp.float32),
        "ln_pre": ((2, D), jnp.float32),  # [scale, bias]
        "wq": ((L, D, D), bf16),
        "bq": ((L, D), bf16),
        "wk": ((L, D, D), bf16),
        "bk": ((L, D), bf16),
        "wv": ((L, D, D), bf16),
        "bv": ((L, D), bf16),
        "wo": ((L, D, D), bf16),
        "bo": ((L, D), bf16),
        "ln1": ((L, 2, D), jnp.float32),
        "ln2": ((L, 2, D), jnp.float32),
        "mlp_up": ((L, D, F), bf16),
        "mlp_up_b": ((L, F), bf16),
        "mlp_down": ((L, F, D), bf16),
        "mlp_down_b": ((L, D), bf16),
        "ln_post": ((2, D), jnp.float32),
        "proj_1": ((D, P), bf16),
        "proj_1_b": ((P,), bf16),
        "proj_2": ((P, P), bf16),
        "proj_2_b": ((P,), bf16),
    }


def init_vision_params(cfg: VisionConfig, seed: int = 0) -> Params:
    shapes = vision_param_shapes(cfg)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(shapes))
    params: Params = {}
    for (name, (shape, dtype)), k in zip(shapes.items(), keys):
        if name.startswith("ln"):
            # [scale=1, bias=0]
            arr = jnp.stack(
                [jnp.ones(shape[-1:], dtype), jnp.zeros(shape[-1:], dtype)]
            )
            arr = jnp.broadcast_to(arr, shape).astype(dtype)
        elif name.endswith("_b") or name.startswith("b"):
            arr = jnp.zeros(shape, dtype)
        else:
            fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
            scale = 1.0 / math.sqrt(max(1, fan_in))
            arr = (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)
        params[name] = arr
    return params


def load_vision_params(cfg: VisionConfig, path: str) -> Params:
    """Load tower weights from an .npz archive keyed by
    ``vision_param_shapes`` names (the projector-merged export format;
    HF CLIP checkpoints convert offline with a rename+stack script)."""
    import numpy as np

    shapes = vision_param_shapes(cfg)
    with np.load(path) as data:
        missing = set(shapes) - set(data.files)
        if missing:
            raise ValueError(f"{path} missing vision params: {sorted(missing)}")
        params: Params = {}
        for name, (shape, dtype) in shapes.items():
            arr = data[name]
            if arr.shape != shape:
                raise ValueError(
                    f"{name}: expected {shape}, got {arr.shape}"
                )
            params[name] = jnp.asarray(arr, dtype=dtype)
    return params


def _layernorm(x: jax.Array, ln: jax.Array, eps: float) -> jax.Array:
    """ln: [2, D] = [scale, bias]."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * ln[0] + ln[1]
    return out.astype(x.dtype)


def patchify(cfg: VisionConfig, pixels: jax.Array) -> jax.Array:
    """[B, H, W, 3] -> [B, n_patches, patch_dim] (reshape-only, no conv)."""
    B = pixels.shape[0]
    g = cfg.image_size // cfg.patch_size
    p = cfg.patch_size
    x = pixels.reshape(B, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, g, g, p, p, 3]
    return x.reshape(B, g * g, p * p * 3)


def encode_images(cfg: VisionConfig, params: Params, pixels: jax.Array) -> jax.Array:
    """[B, H, W, 3] float pixels -> [B, n_patches, projection_dim]."""
    eps = cfg.layer_norm_eps
    H = cfg.num_attention_heads
    D = cfg.hidden_size
    Dh = D // H

    x = patchify(cfg, pixels).astype(jnp.bfloat16) @ params["patch_embed"]
    x = x + params["pos_embed"].astype(x.dtype)
    x = _layernorm(x, params["ln_pre"], eps)

    def layer_fn(x, lp):
        B, T = x.shape[0], x.shape[1]
        h = _layernorm(x, lp["ln1"], eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(B, T, H, Dh)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B, T, H, Dh)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B, T, H, Dh)
        scores = jnp.einsum(
            "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(Dh)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, D)
        x = x + (attn @ lp["wo"] + lp["bo"]).astype(x.dtype)
        h = _layernorm(x, lp["ln2"], eps)
        mlp = jax.nn.gelu(h @ lp["mlp_up"] + lp["mlp_up_b"]) @ lp["mlp_down"]
        x = x + (mlp + lp["mlp_down_b"]).astype(x.dtype)
        return x, None

    layer_names = [
        "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
        "ln1", "ln2", "mlp_up", "mlp_up_b", "mlp_down", "mlp_down_b",
    ]
    x, _ = jax.lax.scan(layer_fn, x, {n: params[n] for n in layer_names})
    x = _layernorm(x, params["ln_post"], eps)
    # LLaVA-style projector into the language model's embedding space
    x = jax.nn.gelu(x @ params["proj_1"] + params["proj_1_b"])
    x = x @ params["proj_2"] + params["proj_2_b"]
    return x
