"""Multimodal (vision-language) serving: image processing, the vision
encode worker, and the multimodal preprocessor (reference:
examples/multimodal — encode worker + LLM worker pipeline)."""

from dynamo_tpu.multimodal.embeds import pack_segments, unpack_segments
from dynamo_tpu.multimodal.processor import ImageProcessor
from dynamo_tpu.multimodal.encoder import VisionEncoder, VisionEncoderEngine
from dynamo_tpu.multimodal.preprocessor import MultimodalPreprocessor

__all__ = [
    "ImageProcessor",
    "MultimodalPreprocessor",
    "VisionEncoder",
    "VisionEncoderEngine",
    "pack_segments",
    "unpack_segments",
]
