"""Wire format for embedding segments riding on PreprocessedRequest.

A segment is (offset, array[n, D]): rows to inject over the decoder's
token embeddings starting at token position ``offset``. Packed as
base64 so the request stays JSON-serializable across the runtime's
request plane (same constraint the reference's NATS request plane
imposes on its Python-side multimodal handoff)."""

from __future__ import annotations

import base64

import numpy as np

Segment = tuple[int, np.ndarray]

MAX_SEGMENT_BYTES = 256 << 20


def pack_segments(segments: list[Segment]) -> list[dict]:
    out = []
    for offset, arr in segments:
        arr = np.ascontiguousarray(arr)
        out.append(
            {
                "offset": int(offset),
                "shape": list(arr.shape),
                "dtype": arr.dtype.name,
                "data": base64.b64encode(arr.tobytes()).decode(),
            }
        )
    return out


def unpack_segments(packed: list[dict]) -> list[Segment]:
    out: list[Segment] = []
    for seg in packed:
        shape = tuple(int(d) for d in seg["shape"])
        if len(shape) != 2:
            raise ValueError(f"embedding segment must be 2-D, got {shape}")
        dtype = np.dtype(seg["dtype"])
        if dtype.kind != "f":
            raise ValueError(f"embedding segment dtype {dtype} not float")
        n_bytes = int(np.prod(shape)) * dtype.itemsize
        if n_bytes > MAX_SEGMENT_BYTES:
            raise ValueError("embedding segment too large")
        raw = base64.b64decode(seg["data"])
        if len(raw) != n_bytes:
            raise ValueError(
                f"embedding segment payload {len(raw)}B != expected {n_bytes}B"
            )
        out.append(
            (int(seg["offset"]), np.frombuffer(raw, dtype=dtype).reshape(shape))
        )
    return out
