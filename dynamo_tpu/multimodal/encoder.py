"""Vision encode worker: images in, projected embeddings out.

The native analogue of the reference's multimodal *encode worker*
(examples/multimodal/components/encode_worker.py): a separate service
that runs the vision tower so LLM workers never touch image bytes. The
engine form (``VisionEncoderEngine``) serves over the runtime's
endpoint plane — deploy it as its own component and point the
multimodal preprocessor's ``encode`` hook at its client."""

from __future__ import annotations

from typing import Any, AsyncIterator, Optional

import numpy as np

from dynamo_tpu.models.vision import (
    VisionConfig,
    encode_images,
    init_vision_params,
)
from dynamo_tpu.multimodal.embeds import pack_segments
from dynamo_tpu.multimodal.processor import ImageProcessor
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream


class VisionEncoder:
    """In-process vision tower: urls -> [n_images, n_patches, D] float32."""

    def __init__(self, cfg: VisionConfig, params: Optional[dict] = None,
                 seed: int = 0, image_root: Optional[str] = None):
        import jax

        self.cfg = cfg
        self.params = params if params is not None else init_vision_params(
            cfg, seed=seed
        )
        import os

        if image_root is None:
            image_root = os.environ.get("DYN_IMAGE_ROOT") or None
        self.processor = ImageProcessor(cfg.image_size, image_root=image_root)
        self._encode = jax.jit(lambda p, px: encode_images(cfg, p, px))

    @property
    def tokens_per_image(self) -> int:
        return self.cfg.num_patches

    def encode_urls(self, urls: list[str]) -> np.ndarray:
        pixels = self.processor.load_batch(urls)
        return np.asarray(self._encode(self.params, pixels), np.float32)


class VisionEncoderEngine(AsyncEngine):
    """Endpoint-servable encode worker. Request: {"image_urls": [...]};
    response: one message {"segments": packed, "tokens_per_image": n}
    where segment offsets are image-relative (0, n, 2n, ...) — the
    preprocessor rebases them onto prompt positions."""

    def __init__(self, encoder: VisionEncoder):
        self.encoder = encoder

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        urls = list(request["image_urls"])
        embeds = self.encoder.encode_urls(urls)  # [B, n, D]
        n = self.encoder.tokens_per_image
        segments = [(i * n, embeds[i]) for i in range(len(urls))]
        yield {
            "segments": pack_segments(segments),
            "tokens_per_image": n,
        }

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)
