"""Multimodal preprocessor: chat requests with images -> tokenized
request + embedding segments.

Mirrors the reference's multimodal pipeline shape (examples/multimodal:
processor extracts image URLs, an encode worker produces embeddings,
the LLM worker receives prompt + embeddings): each ``image_url``
content part becomes ``tokens_per_image`` repetitions of the image
placeholder token in the prompt, and the image's projected patch
embeddings ride the request as ``mm_embeds`` segments anchored at the
placeholder offsets. The decoder splices them over the token embeddings
(models/llama.py forward(extra_embeds=...))."""

from __future__ import annotations

import uuid
from typing import Awaitable, Callable, Optional

import numpy as np

from dynamo_tpu.multimodal.embeds import pack_segments
from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor
from dynamo_tpu.protocols.common import PreprocessedRequest
from dynamo_tpu.protocols.openai import ChatCompletionRequest, guided_options

# encode(urls) -> [n_images, tokens_per_image, D] float32
EncodeFn = Callable[[list[str]], "np.ndarray"]

IMAGE_PLACEHOLDER = "<image>"


def extract_image_urls(request: ChatCompletionRequest) -> list[str]:
    """Collect image_url parts across messages, in order."""
    urls: list[str] = []
    for m in request.messages:
        if isinstance(m.content, list):
            for part in m.content:
                if part.get("type") == "image_url":
                    img = part.get("image_url") or {}
                    url = img.get("url") if isinstance(img, dict) else img
                    if url:
                        urls.append(url)
    return urls


class MultimodalPreprocessor(OpenAIPreprocessor):
    """OpenAIPreprocessor + image handling.

    ``encode`` runs the vision tower (a local VisionEncoder.encode_urls,
    or a remote encode-worker call); ``image_token_id`` is the
    placeholder token the decoder overwrites with patch embeddings.
    """

    def __init__(
        self,
        tokenizer,
        formatter,
        encode: EncodeFn,
        image_token_id: int,
        tokens_per_image: int,
        model_name: str = "",
    ):
        super().__init__(tokenizer, formatter, model_name=model_name)
        self._encode = encode
        self.image_token_id = image_token_id
        self.tokens_per_image = tokens_per_image

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        urls = extract_image_urls(request)
        if not urls:
            return super().preprocess_chat(request)
        # render with a textual placeholder per image, then expand each
        # placeholder into tokens_per_image image tokens
        flat = self._render_with_placeholders(request)
        pieces = flat.split(IMAGE_PLACEHOLDER)
        if len(pieces) != len(urls) + 1:
            raise ValueError(
                f"prompt has {len(pieces) - 1} image placeholders for "
                f"{len(urls)} images"
            )
        embeds = self._encode(urls)  # [n_images, tokens_per_image, D]
        if embeds.shape[:2] != (len(urls), self.tokens_per_image):
            raise ValueError(
                f"encoder returned {embeds.shape}, expected "
                f"({len(urls)}, {self.tokens_per_image}, D)"
            )
        token_ids: list[int] = []
        segments = []
        for i, piece in enumerate(pieces):
            if piece:
                token_ids.extend(self.tokenizer.encode(piece))
            if i < len(urls):
                segments.append((len(token_ids), np.asarray(embeds[i], np.float32)))
                token_ids.extend([self.image_token_id] * self.tokens_per_image)
        return PreprocessedRequest(
            request_id=f"chatcmpl-{uuid.uuid4().hex}",
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=request.stop_conditions(),
            output=request.output_options(),
            model=request.model,
            annotations=list(request.extension().annotations),
            speculative=request.extension().speculative,
            migration=request.extension().migration,
            guided=guided_options(request),
            mm_embeds=pack_segments(segments),
        )

    def _render_with_placeholders(self, request: ChatCompletionRequest) -> str:
        """Chat-template render with image parts replaced by the textual
        placeholder (most VLM chat templates expect exactly this)."""
        messages = []
        for m in request.messages:
            d = m.model_dump(exclude_none=True)
            if isinstance(m.content, list):
                parts = []
                for part in m.content:
                    if part.get("type") == "image_url":
                        parts.append(IMAGE_PLACEHOLDER)
                    else:
                        parts.append(part.get("text", ""))
                d["content"] = "".join(parts)
            messages.append(d)
        if self.formatter is None:
            raise ValueError("chat requests need a PromptFormatter")
        return self.formatter.render(
            messages, add_generation_prompt=True, tools=request.tools
        )
