"""Image loading + preprocessing for the vision tower.

Accepts OpenAI ``image_url`` content: ``data:`` URLs (base64 inline),
and local ``file://`` / plain paths only when an ``image_root`` is
configured (requests may then only reference files under that
directory). Plain ``http(s)://`` fetching is deliberately not
implemented here — serving nodes should not pull arbitrary remote URLs;
a fronting proxy can inline them as data URLs (the reference's
multimodal example similarly feeds local/url-resolved images into its
encode worker, examples/multimodal/components/)."""

from __future__ import annotations

import base64
import io
import os

import numpy as np

# CLIP-style normalization constants
_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)

MAX_IMAGE_BYTES = 64 << 20


class ImageProcessor:
    """url/path -> normalized pixel array [image_size, image_size, 3].

    ``image_root``: directory local-file references are confined to.
    ``None`` (the default) rejects all local paths — request-facing
    deployments must not let API clients probe/read arbitrary
    worker-local files through image_url content."""

    def __init__(self, image_size: int = 224, image_root: str | None = None):
        self.image_size = image_size
        self.image_root = (
            os.path.realpath(image_root) if image_root is not None else None
        )

    def load(self, url: str) -> np.ndarray:
        if url.startswith("data:"):
            head, _, payload = url.partition(",")
            if not head.endswith(";base64"):
                raise ValueError("data: URL must be base64-encoded")
            raw = base64.b64decode(payload)
        elif url.startswith(("http://", "https://")):
            raise ValueError(
                "remote image URLs are not fetched by workers; inline the "
                "image as a data: URL"
            )
        else:
            raw = self._read_local(url)
        if len(raw) > MAX_IMAGE_BYTES:
            raise ValueError("image too large")
        return self._decode(raw)

    def _read_local(self, url: str) -> bytes:
        if self.image_root is None:
            raise ValueError(
                "local image paths are disabled (no image_root configured); "
                "inline the image as a data: URL"
            )
        path = url[len("file://"):] if url.startswith("file://") else url
        # resolve symlinks BEFORE the containment check so a link inside
        # the root can't escape it
        resolved = os.path.realpath(os.path.join(self.image_root, path))
        if os.path.commonpath([resolved, self.image_root]) != self.image_root:
            raise ValueError("image path escapes the configured image root")
        if os.path.getsize(resolved) > MAX_IMAGE_BYTES:
            raise ValueError("image file too large")
        with open(resolved, "rb") as f:
            return f.read()

    def _decode(self, raw: bytes) -> np.ndarray:
        from PIL import Image

        img = Image.open(io.BytesIO(raw)).convert("RGB")
        img = img.resize((self.image_size, self.image_size), Image.BICUBIC)
        arr = np.asarray(img, np.float32) / 255.0
        return (arr - _MEAN) / _STD

    def load_batch(self, urls: list[str]) -> np.ndarray:
        """-> [B, image_size, image_size, 3]."""
        return np.stack([self.load(u) for u in urls])
