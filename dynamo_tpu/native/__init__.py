"""ctypes bindings to the native C++ tier (`native/src/*.cc`).

The reference keeps its runtime hot loops in a native language (reference:
lib/tokens/src/lib.rs token hashing; lib/llm/src/kv_router/indexer.rs radix
index; lib/llm/src/block_manager/pool/inactive.rs block pool) with Python
bindings on top. dynamo-tpu does the same in C++: this module loads
``_dynamo_native.so`` (built by ``python native/build.py``) and exposes

- :func:`hash_sequence` — batch chained block/sequence hashing (xxh3,
  bit-identical to :mod:`dynamo_tpu.tokens`),
- :class:`NativeRadix` — the KV-router prefix index,
- :class:`NativeLru` — content-addressed LRU pool bookkeeping.

Every consumer falls back to its pure-Python implementation when the
library is absent or ``DYN_NATIVE=0`` is set, so the native tier is a
performance floor, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

# insert() result protocol, shared by NativeLru, the pure-Python fallback
# (kvbm.pool._PyLruIndex), and native/src/lru.cc (the C literals there are
# documented against these names).
LRU_PRESENT, LRU_INSERTED, LRU_EVICTED = 0, 1, 2


def _so_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "_dynamo_native.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DYN_NATIVE", "1") == "0":
        return None
    path = _so_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    u64, i64, sz = ctypes.c_uint64, ctypes.c_int64, ctypes.c_size_t
    p = ctypes.POINTER

    lib.dyn_xxh3_64.restype = u64
    lib.dyn_xxh3_64.argtypes = [ctypes.c_void_p, sz, u64]
    lib.dyn_hash_sequence.restype = sz
    lib.dyn_hash_sequence.argtypes = [p(ctypes.c_int32), sz, sz, u64, p(u64), p(u64)]
    lib.dyn_chain_hash.restype = u64
    lib.dyn_chain_hash.argtypes = [u64, ctypes.c_int, u64, u64]

    lib.dyn_radix_new.restype = ctypes.c_void_p
    lib.dyn_radix_free.argtypes = [ctypes.c_void_p]
    lib.dyn_radix_apply.argtypes = [ctypes.c_void_p, i64, ctypes.c_int, p(u64), sz]
    lib.dyn_radix_remove_worker.argtypes = [ctypes.c_void_p, i64]
    lib.dyn_radix_find.restype = sz
    lib.dyn_radix_find.argtypes = [ctypes.c_void_p, p(u64), sz, p(i64), p(ctypes.c_uint32), sz]
    lib.dyn_radix_find_multi.restype = sz
    lib.dyn_radix_find_multi.argtypes = [
        p(ctypes.c_void_p), sz, p(u64), sz, p(i64), p(ctypes.c_uint32), sz,
    ]
    lib.dyn_radix_num_blocks.restype = sz
    lib.dyn_radix_num_blocks.argtypes = [ctypes.c_void_p]
    lib.dyn_radix_applied.restype = u64
    lib.dyn_radix_applied.argtypes = [ctypes.c_void_p]
    lib.dyn_radix_num_workers.restype = sz
    lib.dyn_radix_num_workers.argtypes = [ctypes.c_void_p]

    lib.dyn_lru_new.restype = ctypes.c_void_p
    lib.dyn_lru_new.argtypes = [sz]
    lib.dyn_lru_free.argtypes = [ctypes.c_void_p]
    lib.dyn_lru_lookup.restype = i64
    lib.dyn_lru_lookup.argtypes = [ctypes.c_void_p, u64, ctypes.c_int]
    lib.dyn_lru_insert.restype = ctypes.c_int
    lib.dyn_lru_insert.argtypes = [ctypes.c_void_p, u64, p(i64), p(u64), p(i64)]
    lib.dyn_lru_evict.restype = i64
    lib.dyn_lru_evict.argtypes = [ctypes.c_void_p, u64]
    lib.dyn_lru_len.restype = sz
    lib.dyn_lru_len.argtypes = [ctypes.c_void_p]
    lib.dyn_lru_match_prefix.restype = sz
    lib.dyn_lru_match_prefix.argtypes = [ctypes.c_void_p, p(u64), sz]

    _LIB = lib
    return _LIB


def is_available() -> bool:
    return _load() is not None


def build(force: bool = False) -> bool:
    """Compile the native library in place (delegates to native/build.py)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "dyn_native_build", os.path.join(repo, "native", "build.py")
    )
    if spec is None or spec.loader is None:
        return False
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ok = mod.build(force=force)
    if ok:
        global _TRIED, _LIB
        _TRIED, _LIB = False, None  # reload on next use
    return ok


# ---------------------------------------------------------------------------
# hashing


def xxh3_64(data: bytes, seed: int = 0) -> int:
    lib = _load()
    assert lib is not None
    buf = (ctypes.c_char * len(data)).from_buffer_copy(data) if data else None
    return lib.dyn_xxh3_64(buf, len(data), ctypes.c_uint64(seed))


def hash_sequence(
    tokens: np.ndarray, block_size: int, salt: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """(block_hashes, seq_hashes) for all complete blocks, or None if the
    native tier is unavailable."""
    lib = _load()
    if lib is None:
        return None
    # Match the pure-Python path's dtype handling: token ids are treated as
    # u32 (compute_block_hash casts via uint32), so ids in [2^31, 2^32) must
    # not overflow an int32 conversion — go through uint32 and reinterpret
    # the bytes, which is what the hash sees anyway.
    arr = np.asarray(tokens)
    if arr.dtype == np.int32:
        arr = np.ascontiguousarray(arr)
    else:
        arr = np.ascontiguousarray(arr.astype(np.uint32, copy=False)).view(np.int32)
    n_blocks = len(arr) // block_size if block_size else 0
    block_out = np.empty(n_blocks, dtype=np.uint64)
    seq_out = np.empty(n_blocks, dtype=np.uint64)
    if n_blocks:
        wrote = lib.dyn_hash_sequence(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(arr),
            block_size,
            ctypes.c_uint64(salt),
            block_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            seq_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        assert wrote == n_blocks
    return block_out, seq_out


def chain_hash(parent: int | None, block_hash: int, salt: int) -> int | None:
    lib = _load()
    if lib is None:
        return None
    return lib.dyn_chain_hash(
        ctypes.c_uint64(parent or 0),
        0 if parent is None else 1,
        ctypes.c_uint64(block_hash),
        ctypes.c_uint64(salt),
    )


# ---------------------------------------------------------------------------
# radix index

_RADIX_OPS = {"stored": 0, "removed": 1, "cleared": 2}


class NativeRadix:
    """Handle to the C++ prefix index (same semantics as
    kv_router.indexer.RadixTree)."""

    def __init__(self) -> None:
        lib = _load()
        assert lib is not None, "native tier unavailable"
        self._lib = lib
        self._h = lib.dyn_radix_new()

    def __del__(self) -> None:  # pragma: no cover
        h = getattr(self, "_h", None)
        if h:
            self._lib.dyn_radix_free(h)
            self._h = None

    @staticmethod
    def _as_u64(hashes) -> np.ndarray:
        return np.ascontiguousarray(
            np.asarray(list(hashes), dtype=np.uint64) if not isinstance(hashes, np.ndarray) else hashes,
            dtype=np.uint64,
        )

    def apply(self, worker_id: int, op: str, block_hashes) -> None:
        arr = self._as_u64(block_hashes)
        self._lib.dyn_radix_apply(
            self._h,
            ctypes.c_int64(worker_id),
            _RADIX_OPS[op],
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(arr),
        )

    def remove_worker(self, worker_id: int) -> None:
        self._lib.dyn_radix_remove_worker(self._h, ctypes.c_int64(worker_id))

    def find_matches(self, seq_hashes) -> dict[int, int]:
        arr = self._as_u64(seq_hashes)
        cap = max(64, 2 * self._lib.dyn_radix_num_workers(self._h))
        workers = np.empty(cap, dtype=np.int64)
        scores = np.empty(cap, dtype=np.uint32)
        n = self._lib.dyn_radix_find(
            self._h,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(arr),
            workers.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            cap,
        )
        return {int(workers[i]): int(scores[i]) for i in range(n)}

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._h)

    @property
    def applied_events(self) -> int:
        return self._lib.dyn_radix_applied(self._h)


def radix_find_multi(trees, seq_hashes) -> dict[int, int]:
    """Batched find_matches over several NativeRadix trees with ONE FFI
    crossing (the sharded indexer's match path — per-call ctypes
    overhead otherwise floors its latency at n_shards x a single tree).
    Worker sets must be disjoint across trees (sharded-by-worker)."""
    import numpy as np

    assert trees
    lib = trees[0]._lib
    arr = NativeRadix._as_u64(seq_hashes)
    handles = (ctypes.c_void_p * len(trees))(
        *[t._h for t in trees]
    )
    cap = max(
        64,
        2 * sum(lib.dyn_radix_num_workers(t._h) for t in trees),
    )
    workers = np.empty(cap, dtype=np.int64)
    scores = np.empty(cap, dtype=np.uint32)
    n = lib.dyn_radix_find_multi(
        handles,
        len(trees),
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(arr),
        workers.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        scores.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        cap,
    )
    return {int(workers[i]): int(scores[i]) for i in range(n)}


# ---------------------------------------------------------------------------
# LRU pool index


class NativeLru:
    """Handle to the C++ content-addressed LRU index (TierPool bookkeeping)."""

    PRESENT, INSERTED, EVICTED = LRU_PRESENT, LRU_INSERTED, LRU_EVICTED

    def __init__(self, num_blocks: int) -> None:
        lib = _load()
        assert lib is not None, "native tier unavailable"
        self._lib = lib
        self._h = lib.dyn_lru_new(num_blocks)

    def __del__(self) -> None:  # pragma: no cover
        h = getattr(self, "_h", None)
        if h:
            self._lib.dyn_lru_free(h)
            self._h = None

    def lookup(self, seq_hash: int, touch: bool = True) -> int | None:
        bid = self._lib.dyn_lru_lookup(self._h, ctypes.c_uint64(seq_hash), int(touch))
        return None if bid < 0 else int(bid)

    def insert(self, seq_hash: int) -> tuple[int, int, tuple[int, int] | None]:
        """Returns (code, block_id, victim) with victim=(hash, block) when
        code==EVICTED. The caller must demote the victim's data before
        writing block_id (storage is reused)."""
        out_block = ctypes.c_int64()
        v_hash = ctypes.c_uint64()
        v_block = ctypes.c_int64()
        code = self._lib.dyn_lru_insert(
            self._h,
            ctypes.c_uint64(seq_hash),
            ctypes.byref(out_block),
            ctypes.byref(v_hash),
            ctypes.byref(v_block),
        )
        if code < 0:
            raise RuntimeError("zero-capacity pool")
        victim = (int(v_hash.value), int(v_block.value)) if code == self.EVICTED else None
        return code, int(out_block.value), victim

    def evict(self, seq_hash: int) -> int | None:
        bid = self._lib.dyn_lru_evict(self._h, ctypes.c_uint64(seq_hash))
        return None if bid < 0 else int(bid)

    def __len__(self) -> int:
        return self._lib.dyn_lru_len(self._h)

    def match_prefix(self, seq_hashes) -> int:
        arr = NativeRadix._as_u64(seq_hashes)
        return self._lib.dyn_lru_match_prefix(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(arr)
        )
