"""Device kernels and transfer ops (XLA + Pallas)."""
