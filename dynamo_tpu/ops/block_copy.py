"""KV block gather/scatter between device caches and packed host blocks.

TPU-native replacement for the reference's CUDA block-copy machinery
(lib/llm/src/kernels/block_copy.cu ``copy_blocks_kernel`` and the cudarc
async-memcpy paths in block_manager/block/transfer/cuda.rs): here the
gather/scatter is a jitted XLA program — ``take`` / ``.at[].set`` on the
block axis — which XLA lowers to efficient dynamic-slice copies on HBM,
and the host hop is a device↔host transfer of one contiguous packed
buffer. Cache buffers are donated on scatter so the update is in-place.

Block-id batches are padded to power-of-two buckets so each shape
compiles once. Block 0 is the engine's garbage block: padding gathers
read it (discarded) and padding scatters write it (harmless).

Two contracts here are mechanically enforced (docs/static_analysis.md
"The JAX-semantics layer"): the scatter paths donate their cache
inputs, so every caller must rebind from the return value — dynalint
DL201 (`use-after-donate`) follows the donation one wrapper level up
through :func:`scatter_blocks`'s parameters and flags any read of the
old references; and each id bucket is its own cache-sized jit program,
so the engine prewarms the reachable buckets (`_prewarm`'s kvbm loop —
DL203 `prewarm-coverage` checks the callables are referenced there,
and `DYN_COMPILE_FENCE=1` catches any bucket prewarm missed at
runtime).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.utils.bucketing import next_bucket

ID_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256]


def _bucket(n: int) -> int:
    return next_bucket(n, ID_BUCKETS)


@functools.partial(jax.jit, static_argnums=(3,))
def _gather(k, v, ids, block_size):
    """Plain-array caches (bf16/fp8); int8 (values, scales) caches are
    handled by _gather's tuple-aware dispatch in gather_blocks via
    _gather_quant — the PACKED host format is always a float array, so
    tier contents and the disagg wire stay dtype-stable regardless of
    the device cache's quantization."""
    L, S, H, D = k.shape
    N = S // block_size
    kr = k.reshape(L, N, block_size, H, D)
    vr = v.reshape(L, N, block_size, H, D)
    kb = jnp.take(kr, ids, axis=1)  # [L, n, bs, H, D]
    vb = jnp.take(vr, ids, axis=1)
    packed = jnp.stack([kb, vb], axis=0)  # [2, L, n, bs, H, D]
    return packed.transpose(2, 0, 1, 3, 4, 5)  # [n, 2, L, bs, H, D]


@functools.partial(jax.jit, static_argnums=(4,), donate_argnums=(0, 1))
def _scatter(k, v, ids, packed, block_size):
    L, S, H, D = k.shape
    N = S // block_size
    data = packed.transpose(1, 2, 0, 3, 4, 5)  # [2, L, n, bs, H, D]
    kr = k.reshape(L, N, block_size, H, D).at[:, ids].set(data[0])
    vr = v.reshape(L, N, block_size, H, D).at[:, ids].set(data[1])
    return kr.reshape(L, S, H, D), vr.reshape(L, S, H, D)


@functools.partial(jax.jit, static_argnums=(5,))
def _gather_quant(kv, ks, vv, vs, ids, block_size):
    """int8 cache -> packed bf16 blocks: dequantize at the tier
    boundary so host pools / the disagg wire keep one float layout
    (requantizing on restore is idempotent: dequantized values are
    exactly representable under their original scale)."""
    from dynamo_tpu.ops.kv_quant import dequantize_kv

    L, S, H, D = kv.shape
    N = S // block_size

    def deq(vals, scales):
        vb = jnp.take(vals.reshape(L, N, block_size, H, D), ids, axis=1)
        sb = jnp.take(scales, ids, axis=1)  # [L, n, H, bs]
        return dequantize_kv(vb, sb.transpose(0, 1, 3, 2), jnp.bfloat16)

    packed = jnp.stack([deq(kv, ks), deq(vv, vs)], axis=0)
    return packed.transpose(2, 0, 1, 3, 4, 5)  # [n, 2, L, bs, H, D]


@functools.partial(jax.jit, static_argnums=(6,), donate_argnums=(0, 1, 2, 3))
def _scatter_quant(kv, ks, vv, vs, ids, packed, block_size):
    """Packed float blocks -> int8 cache: requantize per (slot, head)
    and scatter values + scales (inverse of _gather_quant)."""
    from dynamo_tpu.ops.kv_quant import quantize_kv

    L, S, H, D = kv.shape
    N = S // block_size
    data = packed.transpose(1, 2, 0, 3, 4, 5)  # [2, L, n, bs, H, D]

    def enc(vals, scales, blocks):
        q8, sc = quantize_kv(blocks)  # [L, n, bs, H, D] -> + [L, n, bs, H]
        vals = vals.reshape(L, N, block_size, H, D).at[:, ids].set(q8)
        scales = scales.at[:, ids].set(sc.transpose(0, 1, 3, 2))
        return vals.reshape(L, S, H, D), scales

    kv, ks = enc(kv, ks, data[0])
    vv, vs = enc(vv, vs, data[1])
    return kv, ks, vv, vs


def pad_ids_to_bucket(block_ids) -> np.ndarray:
    """Pad a block-id batch to its compile bucket. Padding entries are
    the reserved garbage block 0 (padding gathers read it and are
    discarded; padding scatters write it, harmlessly). The ONE home of
    this convention — the multihost mirrored copies use it too."""
    n = len(block_ids)
    ids = np.zeros((_bucket(n),), np.int32)
    ids[:n] = block_ids
    return ids


def pad_rows_to(n_ids: int, data: np.ndarray) -> np.ndarray:
    """Zero-pad packed rows to match a bucketed id batch."""
    if n_ids == len(data):
        return data
    pad = np.zeros((n_ids - len(data), *data.shape[1:]), data.dtype)
    return np.concatenate([data, pad], axis=0)


def gather_blocks(k, v, block_ids: list[int], block_size: int) -> np.ndarray:
    """Device → host: returns packed [n, 2, L, bs, Hkv, Dh] ndarray.
    int8 (values, scales) caches dequantize to bf16 at this boundary."""
    n = len(block_ids)
    ids = pad_ids_to_bucket(block_ids)
    if isinstance(k, tuple):
        packed = _gather_quant(k[0], k[1], v[0], v[1], ids, block_size)
    else:
        packed = _gather(k, v, ids, block_size)
    return np.asarray(packed)[:n]


def scatter_blocks(k, v, block_ids: list[int], data: np.ndarray, block_size: int):
    """Host → device: writes packed blocks, returns new (k, v).

    Inputs k/v are DONATED — callers must replace their references.
    int8 (values, scales) caches requantize at this boundary.
    """
    ids = pad_ids_to_bucket(block_ids)
    data = pad_rows_to(len(ids), data)
    if isinstance(k, tuple):
        kv, ks, vv, vs = _scatter_quant(
            k[0], k[1], v[0], v[1], ids, jnp.asarray(data), block_size
        )
        return (kv, ks), (vv, vs)
    return _scatter(k, v, ids, jnp.asarray(data), block_size)
