"""KV block gather/scatter between device caches and packed host blocks.

TPU-native replacement for the reference's CUDA block-copy machinery
(lib/llm/src/kernels/block_copy.cu ``copy_blocks_kernel`` and the cudarc
async-memcpy paths in block_manager/block/transfer/cuda.rs): here the
gather/scatter is a jitted XLA program — ``take`` / ``.at[].set`` on the
block axis — which XLA lowers to efficient dynamic-slice copies on HBM,
and the host hop is a device↔host transfer of one contiguous packed
buffer. Cache buffers are donated on scatter so the update is in-place.

Block-id batches are padded to power-of-two buckets so each shape
compiles once. Block 0 is the engine's garbage block: padding gathers
read it (discarded) and padding scatters write it (harmless).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.utils.bucketing import next_bucket

ID_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256]


def _bucket(n: int) -> int:
    return next_bucket(n, ID_BUCKETS)


@functools.partial(jax.jit, static_argnums=(3,))
def _gather(k, v, ids, block_size):
    L, S, H, D = k.shape
    N = S // block_size
    kr = k.reshape(L, N, block_size, H, D)
    vr = v.reshape(L, N, block_size, H, D)
    kb = jnp.take(kr, ids, axis=1)  # [L, n, bs, H, D]
    vb = jnp.take(vr, ids, axis=1)
    packed = jnp.stack([kb, vb], axis=0)  # [2, L, n, bs, H, D]
    return packed.transpose(2, 0, 1, 3, 4, 5)  # [n, 2, L, bs, H, D]


@functools.partial(jax.jit, static_argnums=(4,), donate_argnums=(0, 1))
def _scatter(k, v, ids, packed, block_size):
    L, S, H, D = k.shape
    N = S // block_size
    data = packed.transpose(1, 2, 0, 3, 4, 5)  # [2, L, n, bs, H, D]
    kr = k.reshape(L, N, block_size, H, D).at[:, ids].set(data[0])
    vr = v.reshape(L, N, block_size, H, D).at[:, ids].set(data[1])
    return kr.reshape(L, S, H, D), vr.reshape(L, S, H, D)


def pad_ids_to_bucket(block_ids) -> np.ndarray:
    """Pad a block-id batch to its compile bucket. Padding entries are
    the reserved garbage block 0 (padding gathers read it and are
    discarded; padding scatters write it, harmlessly). The ONE home of
    this convention — the multihost mirrored copies use it too."""
    n = len(block_ids)
    ids = np.zeros((_bucket(n),), np.int32)
    ids[:n] = block_ids
    return ids


def pad_rows_to(n_ids: int, data: np.ndarray) -> np.ndarray:
    """Zero-pad packed rows to match a bucketed id batch."""
    if n_ids == len(data):
        return data
    pad = np.zeros((n_ids - len(data), *data.shape[1:]), data.dtype)
    return np.concatenate([data, pad], axis=0)


def gather_blocks(k, v, block_ids: list[int], block_size: int) -> np.ndarray:
    """Device → host: returns packed [n, 2, L, bs, Hkv, Dh] ndarray."""
    n = len(block_ids)
    packed = _gather(k, v, pad_ids_to_bucket(block_ids), block_size)
    return np.asarray(packed)[:n]


def scatter_blocks(k, v, block_ids: list[int], data: np.ndarray, block_size: int):
    """Host → device: writes packed blocks, returns new (k, v).

    Inputs k/v are DONATED — callers must replace their references.
    """
    ids = pad_ids_to_bucket(block_ids)
    data = pad_rows_to(len(ids), data)
    return _scatter(k, v, ids, jnp.asarray(data), block_size)
