"""int8 KV-cache quantization helpers (kv_cache_dtype="int8").

Per-(token, head) symmetric int8: amax over the head_dim axis sets one
f32 scale per written (slot, head); values round to [-127, 127]. This is
the accuracy-maximal granularity (finer than the per-page scales of
typical GPU int8 KV schemes) and it costs 4 bytes per 128-byte row —
3.1% overhead on the halved cache.

Scale STORAGE layout — the part dictated by the TPU: ``[L, N, Hk, bs]``
f32 (page-major, then head, then slot-in-page). Rationale:
- a natural ``[L, S, Hk]`` array lane-pads Hk (=8) to 128 on TPU — a
  16x memory blowup that would cost more than the int8 savings. With
  slots-in-page on lanes the minor dim is bs (=128 serving pages):
  zero padding at the default geometry;
- the kernels fetch one page's scales as a BlockSpec tile
  ``(1, 1, Hk, bs)`` whose trailing dims equal the array dims — the
  form Mosaic's "last two block dims x8/x128 or full" rule always
  accepts — and the tile arrives in-register as ``[Hk, bs]``, exactly
  the per-column score-scale orientation, NO in-kernel reshape.
  (Every reshape-based variant hits Mosaic's lane->sublane shape-cast
  rejection, "infer-vector-layout: unsupported shape cast" — probed.);
- TP shards the Hk axis: P(None, None, "tp", None).

Reference analogue: the vLLM quantized-KV option the reference's engine
args pass through (--kv-cache-dtype); the reference's own KV layouts
live in lib/llm/src/block_manager/layout.rs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kv_scale_shape(
    num_layers: int, num_blocks: int, block_size: int, num_kv_heads: int
) -> tuple[int, int, int, int]:
    return (num_layers, num_blocks, num_kv_heads, block_size)


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``x [..., Hk, Dh]`` float -> (int8 values, f32 scales [..., Hk])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    sc = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / sc[..., None]), -127, 127).astype(jnp.int8)
    return q, sc


def scale_scatter_indices(
    slot_mapping: jax.Array, block_size: int
) -> tuple[jax.Array, jax.Array]:
    """Flat slot ids [M] -> (pages [M], offsets [M]) addressing the
    [L, N, Hk, bs] scale array. Prefill (T > 1) writes via the
    indexed-slice scatter ``scales.at[layer, pages, :, offsets].set(
    sc[M, Hk])`` — all heads of one slot's scale column at once. Decode
    (T == 1) instead read-modify-writes whole [Hk, bs] page TILES
    selected by ``pages`` (gather page, jnp.where on the ``offsets``
    column, set back): only the canonical one-indexed-axis scatter form
    updates the carried cache in place, and the indexed-slice form at
    T == 1 made XLA materialize + copy the full scale plane per layer at
    the Pallas custom-call boundary (see models/llama.py write_kv)."""
    return slot_mapping // block_size, slot_mapping % block_size


def gather_slot_scales(
    scales_l: jax.Array,  # [N, Hk, bs] one layer's scales
    slot_ids: jax.Array,  # [...] flat slot ids
    block_size: int,
    num_kv_heads: int,
) -> jax.Array:
    """Per-slot scales [..., Hk] for the XLA gather-then-attend path."""
    n = slot_ids // block_size
    h = jnp.arange(num_kv_heads, dtype=slot_ids.dtype).reshape(
        (1,) * slot_ids.ndim + (num_kv_heads,)
    )
    off = (slot_ids % block_size)[..., None]
    return scales_l[n[..., None], h, off]


def dequantize_kv(vals: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """``vals [..., Hk, Dh]`` int8 + ``scales [..., Hk]`` -> float."""
    return (vals.astype(jnp.float32) * scales[..., None]).astype(dtype)
