"""KV layout rearrange between differing tensor-parallel degrees.

The reference ships Triton kernels that rearrange KV cache layout when a
prefill engine's TP degree differs from the decode engine's (vLLM patch
``kv_rearrange.py``, vllm_v0.8.4-dynamo-kv-disagg-patch.patch:914-1046,
used by the NIXL connector so a TP1 prefill worker can feed a TP4 decode
worker). On GPU this needs a custom kernel because each rank's cache is
a strided slab in its own VRAM.

On TPU the equivalent is a *logical* transform: packed blocks are
``[N, 2, L, block_size, Hkv, Dh]`` and a TP rank owns a contiguous head
range, so resharding between TP degrees is slicing/concatenation on the
head axis — XLA lowers the on-device variant to a relayout, and the
host-staged transfer plane applies the numpy variant. The functions here
are the single source of truth for how head ranges map to ranks.

Supported degrees: ``Hkv % tp == 0`` (each rank owns ``Hkv/tp`` heads)
or ``tp % Hkv == 0`` (heads replicated over ``tp/Hkv`` ranks; rank
``r`` serves head ``r // (tp//Hkv)`` and only the first replica of each
head is a *primary* shipper — mirrors the reference where replicated
ranks hold identical KV).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

HEAD_AXIS = -2  # [..., Hkv, Dh]


def head_range(num_kv_heads: int, tp: int, rank: int) -> tuple[int, int]:
    """(start, count) of KV heads owned by ``rank`` in a ``tp``-way shard."""
    if not 0 <= rank < tp:
        raise ValueError(f"rank {rank} out of range for tp={tp}")
    if num_kv_heads % tp == 0:
        per = num_kv_heads // tp
        return rank * per, per
    if tp % num_kv_heads == 0:
        # replicated: each head is held by tp/Hkv consecutive ranks
        return rank // (tp // num_kv_heads), 1
    raise ValueError(f"incompatible tp={tp} for {num_kv_heads} KV heads")


def is_primary_rank(num_kv_heads: int, tp: int, rank: int) -> bool:
    """Whether ``rank`` is the canonical shipper of its head range (always
    true when heads shard evenly; first replica only when replicated)."""
    head_range(num_kv_heads, tp, rank)  # same ValueError on bad combos
    if num_kv_heads % tp == 0:
        return True
    return rank % (tp // num_kv_heads) == 0


def extract_tp_shard(packed: np.ndarray, tp: int, rank: int) -> np.ndarray:
    """Slice a full-head packed block batch down to ``rank``'s heads."""
    num_kv_heads = packed.shape[HEAD_AXIS]
    start, count = head_range(num_kv_heads, tp, rank)
    return packed[..., start : start + count, :]


def merge_tp_shards(shards: Sequence[np.ndarray], tp: int,
                    num_kv_heads: int) -> np.ndarray:
    """Reassemble full-head packed blocks from one shard per primary rank.

    ``shards[i]`` must be the shard of the i-th *primary* rank, in rank
    order (for even sharding that is every rank; for replicated heads,
    one per distinct head).
    """
    primaries = [r for r in range(tp) if is_primary_rank(num_kv_heads, tp, r)]
    if len(shards) != len(primaries):
        raise ValueError(
            f"expected {len(primaries)} primary shards for tp={tp}, "
            f"got {len(shards)}"
        )
    full = np.concatenate(list(shards), axis=HEAD_AXIS)
    if full.shape[HEAD_AXIS] != num_kv_heads:
        raise ValueError(
            f"merged heads {full.shape[HEAD_AXIS]} != {num_kv_heads}"
        )
    return full


def rearrange_tp(shards: Sequence[np.ndarray], tp_src: int, tp_dst: int,
                 num_kv_heads: int) -> list[np.ndarray]:
    """Re-split source-TP shards into destination-TP shards.

    The host-side equivalent of the reference's Triton rearrange: takes
    one packed-block shard per source primary rank and returns one per
    destination rank (replicas duplicated so every dst rank gets its
    copy).
    """
    full = merge_tp_shards(shards, tp_src, num_kv_heads)
    return [extract_tp_shard(full, tp_dst, r) for r in range(tp_dst)]


def rearrange_tp_device(stacked, tp_src: int, tp_dst: int):
    """On-device (jit-friendly) variant for even sharding.

    ``stacked`` is ``[tp_src, ..., Hkv/tp_src, Dh]`` (source shards
    stacked on a leading axis); returns ``[tp_dst, ..., Hkv/tp_dst, Dh]``.
    Pure reshapes — XLA lowers this to a relayout/collective depending on
    sharding, which is exactly the Pallas-free TPU answer to the
    reference's custom kernel.
    """
    import jax.numpy as jnp

    per_src = stacked.shape[HEAD_AXIS]
    num_kv_heads = tp_src * per_src
    if num_kv_heads % tp_dst != 0:
        raise ValueError(f"tp_dst={tp_dst} incompatible with {num_kv_heads} heads")
    # [tp_src, ..., per_src, Dh] -> [..., Hkv, Dh]
    full = jnp.concatenate(jnp.split(stacked, stacked.shape[0], axis=0),
                           axis=HEAD_AXIS)[0]
    # [..., Hkv, Dh] -> [tp_dst, ..., Hkv/tp_dst, Dh]
    parts = jnp.split(full, tp_dst, axis=HEAD_AXIS)
    return jnp.stack(parts, axis=0)


def cast_packed(packed: np.ndarray, dst_dtype: np.dtype) -> np.ndarray:
    """Cast packed blocks between float dtypes (bf16/f16/f32) on the host
    path; identity if already right."""
    dst = np.dtype(dst_dtype)
    if packed.dtype == dst:
        return packed
    return packed.astype(dst)
