"""Pallas TPU paged-attention decode kernel.

The hot op of the decode loop (TPU replacement for the CUDA/Triton paged
attention the reference delegates to vLLM; ≈ the role of the patch's
Triton kernels, container/deps/vllm/...-patch kv_rearrange + vLLM's
paged_attention_v1). Semantics match
``models.llama.paged_attention_reference`` for T=1 queries, including
``sliding_window`` (Mistral-family).

Design (see /opt/skills/guides/pallas_guide.md):
- grid = (batch, page): pages iterate innermost, so the flash-attention
  running (max, sum, acc) state lives in VMEM scratch across page
  steps; Pallas double-buffers the per-page K/V fetches from HBM
  automatically.
- each step fetches one whole page ``[block_size, Hkv, Dh]`` — every
  blocked trailing dim equals the full array dim, which is what the
  Mosaic TPU lowering requires (trailing block dims must be ×8/×128 or
  full), and one fetch serves all ``H`` query heads (GQA groups are a
  reshape in-kernel, no ``jnp.repeat`` materialization).
- ``block_tables`` and ``context_lens`` ride as scalar-prefetch args:
  the page index_map dereferences the block table *before* the body
  runs, so only the pages a sequence actually references are pulled
  into VMEM — no [B, S, H, Dh] gather materialization.
- grid steps outside a sequence's live page range are CLAMPED onto the
  nearest live page in the index map: Pallas skips the copy when the
  block index repeats between steps, so table-width padding and
  out-of-window pages cost no HBM traffic (their compute is also
  skipped via ``pl.when``).

HBM traffic per decode step ≈ window × Hkv × Dh × 2 per sequence —
the roofline minimum — vs the reference path's group-expanded
materialization.

TP: attention is local per KV-head shard, so multi-device meshes wrap
this kernel in ``shard_map`` over the "tp" axis (models/llama.py
attend_mlp) — one kernel instance per shard, no collectives.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    tables_ref,  # scalar prefetch: [B, W] int32
    ctx_ref,  # scalar prefetch: [B] int32
    q_ref,  # [1, H, Dh]
    k_ref,  # [1, bs, Hk, Dh] — page j of the sequence
    v_ref,  # [1, bs, Hk, Dh]
    o_ref,  # [1, H, Dh]
    acc_ref,  # VMEM scratch [H, Dh] f32
    m_ref,  # VMEM scratch [H, 1] f32
    l_ref,  # VMEM scratch [H, 1] f32
    *,
    block_size: int,
    scale: float,
    window: Optional[int],
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    # first key position a decode query (at position ctx-1) may attend to
    lo = jnp.int32(0) if window is None else jnp.maximum(ctx - window, 0)
    page_live = (j * block_size < ctx) & ((j + 1) * block_size > lo)

    @pl.when(page_live)
    def _page():
        H, Dh = q_ref.shape[1], q_ref.shape[2]
        bs, Hk = k_ref.shape[1], k_ref.shape[2]
        G = H // Hk
        q = q_ref[0].astype(jnp.float32)  # [H, Dh]
        k = k_ref[0].astype(jnp.float32)  # [bs, Hk, Dh]
        v = v_ref[0].astype(jnp.float32)
        # GQA: group query heads over their shared KV head. Unrolled
        # per-KV-head matmuls — Mosaic has no batched dot_general with
        # differing batch positions, and Hk is small and static.
        qg = q.reshape(Hk, G, Dh)
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    qg[hk], k[:, hk, :], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        ) * scale  # [H, bs]
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        valid = (pos < ctx) & (pos >= lo)  # [1, bs]
        s = jnp.where(valid, s, -1e30)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pg = p.reshape(Hk, G, bs)
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pg[hk], v[:, hk, :], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        )  # [H, Dh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        # padded batch rows have ctx == 0 -> l == 0; clamp instead of NaN
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-9)).astype(
            o_ref.dtype
        )


@functools.partial(
    jax.jit, static_argnames=("block_size", "sliding_window", "interpret")
)
def paged_attention_decode(
    q: jax.Array,  # [B, H, Dh] (decode: one query token per sequence)
    k_cache_l: jax.Array,  # [n_slots, Hkv, Dh] (one layer)
    v_cache_l: jax.Array,
    block_tables: jax.Array,  # [B, W] int32
    context_lens: jax.Array,  # [B] int32
    block_size: int,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, H, Dh] attention outputs."""
    B, H, Dh = q.shape
    S, Hk, _ = k_cache_l.shape
    N = S // block_size
    W = block_tables.shape[1]
    scale = 1.0 / math.sqrt(Dh)

    kp = k_cache_l.reshape(N, block_size, Hk, Dh)
    vp = v_cache_l.reshape(N, block_size, Hk, Dh)

    def kv_index(b, j, t, c):
        # clamp dead grid steps (past the last live page, or before a
        # sliding window's first) onto the nearest live page: a repeated
        # block index skips the HBM copy entirely
        last = jnp.maximum((c[b] - 1) // block_size, 0)
        jj = jnp.minimum(j, last)
        if sliding_window is not None:
            first = jnp.clip((c[b] - sliding_window) // block_size, 0, last)
            jj = jnp.maximum(jj, first)
        return (t[b, jj], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, context_lens
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda b, j, t, c: (b, 0, 0)),
            pl.BlockSpec((1, block_size, Hk, Dh), kv_index),
            pl.BlockSpec((1, block_size, Hk, Dh), kv_index),
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, j, t, c: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, block_size=block_size, scale=scale,
            window=sliding_window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens, q, kp, vp)
    return out
