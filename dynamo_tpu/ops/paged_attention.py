"""Pallas TPU paged-attention decode kernel.

The hot op of the decode loop (TPU replacement for the CUDA/Triton paged
attention the reference delegates to vLLM; ≈ the role of the patch's
Triton kernels, container/deps/vllm/...-patch kv_rearrange + vLLM's
paged_attention_v1). Semantics match
``models.llama.paged_attention_reference`` for T=1 queries, including
``sliding_window`` (Mistral-family).

Design (see /opt/skills/guides/pallas_guide.md):
- grid = (batch, page): pages iterate innermost, so the flash-attention
  running (max, sum, acc) state lives in VMEM scratch across page
  steps; Pallas double-buffers the per-page K/V fetches from HBM
  automatically.
- each step fetches one whole page ``[block_size, Hkv, Dh]`` — every
  blocked trailing dim equals the full array dim, which is what the
  Mosaic TPU lowering requires (trailing block dims must be ×8/×128 or
  full), and one fetch serves all ``H`` query heads (GQA groups are a
  reshape in-kernel, no ``jnp.repeat`` materialization).
- ``block_tables`` and ``context_lens`` ride as scalar-prefetch args:
  the page index_map dereferences the block table *before* the body
  runs, so only the pages a sequence actually references are pulled
  into VMEM — no [B, S, H, Dh] gather materialization.
- grid steps outside a sequence's live page range are CLAMPED onto the
  nearest live page in the index map: Pallas skips the copy when the
  block index repeats between steps, so table-width padding and
  out-of-window pages cost no HBM traffic (their compute is also
  skipped via ``pl.when``).

HBM traffic per decode step ≈ window × Hkv × Dh × 2 per sequence —
the roofline minimum — vs the reference path's group-expanded
materialization.

TP: attention is local per KV-head shard, so multi-device meshes wrap
this kernel in ``shard_map`` over the "tp" axis (models/llama.py
attend_mlp) — one kernel instance per shard, no collectives.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _scale_rows(ks2: jax.Array, rows_per_hk: int) -> jax.Array:
    """Expand a per-page scale tile [Hk, bs] to score-row layout
    [Hk*rows_per_hk, bs] (rows are hk-major in both kernels). The tile
    is loaded in this orientation directly from the [L, N, Hk, bs]
    scale storage (ops/kv_quant.py explains why that layout is the one
    Mosaic accepts), so the expansion is a broadcast + leading-dim
    merge — the lane dim (bs) never moves."""
    Hk, bs = ks2.shape
    return jnp.broadcast_to(
        ks2[:, None, :], (Hk, rows_per_hk, bs)
    ).reshape(Hk * rows_per_hk, bs)


def _decode_kernel_stacked(
    layer_ref,  # scalar prefetch: [1] int32 — layer to read
    tables_ref,  # scalar prefetch: [B, W] int32
    ctx_ref,  # scalar prefetch: [B] int32
    *refs,  # q, k, v, [ks, vs,] o, acc, m, l — scales iff quantized
    block_size: int,
    scale: float,
    window: Optional[int],
    quantized: bool,
):
    """THE flash-decode kernel body, over a stacked cache
    [L, N, bs, Hk, Dh] with the layer as a scalar-prefetch index (the
    per-layer API wraps it with L=1). Rationale for layer indexing in
    the BlockSpec: slicing one layer out of the carried cache before a
    pallas_call materializes a full-layer copy at the custom-call
    boundary (XLA cannot fuse a producer slice into a custom call) —
    measured ~11 ms/step at a 4.7 GB cache, scaling linearly with cache
    size. Indexing here keeps per-step HBM traffic at just the
    referenced pages. GQA groups query heads over their shared KV head
    via unrolled per-KV-head matmuls (Mosaic has no batched dot_general
    with differing batch positions; Hk is small and static).

    ``quantized``: int8 cache values with per-(slot, head) f32 scales
    riding two extra page-tile refs [1, 1, Hk, bs]. K's scale applies to
    the f32 SCORES per column (exact: int8 -> bf16 is lossless, so the
    only rounding is the quantization itself); V's scale folds into the
    probabilities before the PV dot (p is f32 at that point). int8 page
    loads convert at essentially bf16-load speed on v5e (measured 8.7
    vs 8.0 ms/call at ISL-3000 geometry) — unlike fp8, whose emulated
    convert collapses the kernel to 29 GB/s effective (13.8 ms/call)."""
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    lo = jnp.int32(0) if window is None else jnp.maximum(ctx - window, 0)
    page_live = (j * block_size < ctx) & ((j + 1) * block_size > lo)

    @pl.when(page_live)
    def _page():
        H, Dh = q_ref.shape[1], q_ref.shape[2]
        bs, Hk = k_ref.shape[2], k_ref.shape[3]
        G = H // Hk
        # storage dtype straight into the MXU (bf16 operands, f32
        # accumulation) — f32 upcasts double VMEM for nothing. A
        # quantized fp8 cache (engine kv_cache_dtype=float8_e4m3fn)
        # upcasts to the query dtype here: every e4m3 value is exactly
        # representable in bf16, so the HBM read is byte-halved and the
        # convert is free VPU work (the dot itself stays bf16×bf16).
        q = q_ref[0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if k.dtype != q.dtype:
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        qg = q.reshape(Hk, G, Dh)
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    qg[hk], k[:, hk, :], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        ) * scale
        if quantized:
            # K dequant via per-column score scaling (f32, exact)
            s = s * _scale_rows(ks_ref[0, 0], G)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        valid = (pos < ctx) & (pos >= lo)
        s = jnp.where(valid, s, -1e30)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if quantized:
            # V dequant folded into the probabilities while still f32
            p = p * _scale_rows(vs_ref[0, 0], G)
        pg = p.astype(v.dtype).reshape(Hk, G, bs)
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pg[hk], v[:, hk, :], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-9)).astype(
            o_ref.dtype
        )


@functools.partial(
    jax.jit, static_argnames=("block_size", "sliding_window", "interpret")
)
def paged_attention_decode_stacked(
    q: jax.Array,  # [B, H, Dh]
    k_cache: jax.Array,  # [L, n_slots, Hkv, Dh] — the FULL stacked cache
    v_cache: jax.Array,
    layer_idx: jax.Array,  # scalar int32 — layer to attend over
    block_tables: jax.Array,  # [B, W] int32
    context_lens: jax.Array,  # [B] int32
    block_size: int,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [L, N, Hkv, bs] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode attention over layer ``layer_idx`` of the stacked cache.

    Equivalent to ``paged_attention_decode(q, k_cache[layer_idx], ...)``
    but WITHOUT materializing the layer slice (see
    _decode_kernel_stacked). This is the hot decode path the engine's
    layer scan uses: the cache stays a scan carry and only referenced
    pages move.

    ``k_scale``/``v_scale``: per-(slot, head) dequant scales for an
    int8 cache, stored [L, N, Hk, bs] (layout rationale:
    ops/kv_quant.py). The scale tile loads directly as [Hk, bs] — no
    in-kernel reshape, so any page geometry lowers."""
    B, H, Dh = q.shape
    L, S, Hk, _ = k_cache.shape
    N = S // block_size
    W = block_tables.shape[1]
    scale = 1.0 / math.sqrt(Dh)
    quantized = k_scale is not None

    # leading-dim split: layout-preserving (free) on TPU
    kp = k_cache.reshape(L, N, block_size, Hk, Dh)
    vp = v_cache.reshape(L, N, block_size, Hk, Dh)
    layer_arr = jnp.asarray(layer_idx, jnp.int32).reshape(1)

    def kv_index(b, j, lyr, t, c):
        last = jnp.maximum((c[b] - 1) // block_size, 0)
        jj = jnp.minimum(j, last)
        if sliding_window is not None:
            first = jnp.clip((c[b] - sliding_window) // block_size, 0, last)
            jj = jnp.maximum(jj, first)
        return (lyr[0], t[b, jj], 0, 0, 0)

    def scale_index(b, j, lyr, t, c):
        return kv_index(b, j, lyr, t, c)[:2] + (0, 0)

    in_specs = [
        pl.BlockSpec((1, H, Dh), lambda b, j, lyr, t, c: (b, 0, 0)),
        pl.BlockSpec((1, 1, block_size, Hk, Dh), kv_index),
        pl.BlockSpec((1, 1, block_size, Hk, Dh), kv_index),
    ]
    inputs = [q, kp, vp]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, Hk, block_size), scale_index),
            pl.BlockSpec((1, 1, Hk, block_size), scale_index),
        ]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # layer, block_tables, context_lens
        grid=(B, W),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, j, lyr, t, c: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel_stacked, block_size=block_size, scale=scale,
            window=sliding_window, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(layer_arr, block_tables, context_lens, *inputs)


def _prefill_kernel_stacked(
    layer_ref,   # scalar prefetch: [1] int32
    starts_ref,  # scalar prefetch: [B] int32 — first query position per row
    tables_ref,  # scalar prefetch: [B, W] int32
    ctx_ref,     # scalar prefetch: [B] int32 (context incl. this chunk)
    *refs,  # q, k, v, [ks, vs,] o, acc, m, l — scales iff quantized
    block_size: int,
    tq: int,
    scale: float,
    window: Optional[int],
    quantized: bool,
):
    """Flash prefill over the paged cache: one query TILE of ``tq``
    tokens vs one KV page per grid step, causal (+ sliding window)
    masked, online-softmax state in VMEM across the page axis. The
    chunk's own K/V are read back from the cache (the caller scatters
    them in before attending), so chunked long prompts attend their
    full prefix without any [T, S] score materialization — the XLA
    reference path's [B, Hk, G, T, S] scores tensor is ~400 MB at
    T=1024/S=3072 and its HBM traffic dominates long-prompt TTFT."""
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    start = starts_ref[b]
    # query positions covered by this tile
    q_lo = start + qi * tq
    q_hi_excl = jnp.minimum(start + (qi + 1) * tq, ctx)
    # keys this tile may attend: [lo_bound, q_hi_excl)
    lo_bound = (
        jnp.int32(0) if window is None
        else jnp.maximum(q_lo - (window - 1), 0)
    )
    page_live = (
        (j * block_size < q_hi_excl)
        & ((j + 1) * block_size > lo_bound)
        & (q_lo < ctx)
    )

    @pl.when(page_live)
    def _page():
        Tq, H, Dh = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
        bs, Hk = k_ref.shape[2], k_ref.shape[3]
        G = H // Hk
        # keep q/k/v in their storage dtype (bf16 in serving): the MXU
        # takes bf16 operands natively with f32 accumulation, and f32
        # upcasts would double the kernel's VMEM footprint (scoped-vmem
        # OOM at block_size=128 geometries)
        q = q_ref[0, 0]  # [Tq, H, Dh]
        k = k_ref[0, 0]  # [bs, Hk, Dh]
        v = v_ref[0, 0]
        if k.dtype != q.dtype:
            # quantized fp8 cache: upcast to the query/compute dtype
            # (exact — e4m3 ⊂ bf16); HBM traffic stays 1 byte/elem
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        # hk-major rows: [Hk, Tq*G, Dh] -> flat [Hk*Tq*G, Dh]
        qg = q.reshape(Tq, Hk, G, Dh).swapaxes(0, 1).reshape(Hk, Tq * G, Dh)
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    qg[hk], k[:, hk, :], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        ) * scale  # [Hk*Tq*G, bs] f32
        if quantized:
            # int8 cache: K's per-(slot, head) scale applied to the f32
            # scores per column (see _decode_kernel_stacked)
            s = s * _scale_rows(ks_ref[0, 0], Tq * G)
        key_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs), 1
        )  # [1, bs]
        # per-row query position: row r = (hk, t, g) -> q token t
        t_idx = (
            jax.lax.broadcasted_iota(jnp.int32, (Hk * Tq * G, 1), 0)
            // G % Tq
        )
        q_pos = q_lo + t_idx  # [rows, 1]
        valid = (key_pos <= q_pos) & (key_pos < ctx) & (q_pos < ctx)
        if window is not None:
            valid = valid & (key_pos > q_pos - window)
        s = jnp.where(valid, s, -1e30)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if quantized:
            # V dequant folded into the probabilities while still f32
            p = p * _scale_rows(vs_ref[0, 0], Tq * G)
        # p in the value dtype for the MXU (standard flash practice; the
        # softmax stats above stay f32)
        pg = p.astype(v.dtype).reshape(Hk, Tq * G, bs)
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pg[hk], v[:, hk, :], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for hk in range(Hk)
            ],
            axis=0,
        )  # [Hk*Tq*G, Dh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        Tq, H, Dh = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
        Hk = k_ref.shape[3]
        G = H // Hk
        # rows with no valid key (padded rows/tokens): clamp, not NaN
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-9)
        out = out.reshape(Hk, Tq, G, Dh).swapaxes(0, 1).reshape(Tq, H, Dh)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "sliding_window", "interpret"),
)
def paged_attention_prefill_stacked(
    q: jax.Array,  # [B, T, H, Dh] — a (possibly chunked) prefill rectangle
    k_cache: jax.Array,  # [L, n_slots, Hkv, Dh] stacked cache
    v_cache: jax.Array,
    layer_idx: jax.Array,  # scalar int32
    block_tables: jax.Array,  # [B, W] int32
    start_pos: jax.Array,  # [B] int32 — absolute position of q[:, 0]
    context_lens: jax.Array,  # [B] int32 — total context incl. this chunk
    block_size: int,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [L, N, Hkv, bs] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Flash prefill attention over the paged cache; returns
    [B, T, H, Dh]. Requires the chunk's K/V to already be scattered
    into the cache (models/llama.py writes before attending). Rows are
    contiguous token runs: q[b, t] sits at absolute position
    start_pos[b] + t (padded rows: start 0 / ctx 0 -> all-masked).
    ``k_scale``/``v_scale``: int8-cache dequant scales (layout and
    constraints documented on paged_attention_decode_stacked)."""
    B, T, H, Dh = q.shape
    L, S, Hk, _ = k_cache.shape
    N = S // block_size
    W = block_tables.shape[1]
    scale = 1.0 / math.sqrt(Dh)
    quantized = k_scale is not None
    # query tile: 128 keeps the kernel's VMEM state ~2 MB for the 8B
    # geometry at block_size=16; halve while the f32 working-set
    # ESTIMATE (acc + scores) exceeds 5 MB — measured actual usage runs
    # ~2.8x the estimate (17.5 MB at a 6.3 MB estimate: probs, masks,
    # relayout copies), and the scoped-VMEM budget is 16 MB, so 5 MB
    # estimated ≈ 14 MB actual with margin. Hit by big block_size
    # (128-token pages) and wide-H geometries (70B H=64).
    tq = 128 if T % 128 == 0 else T
    # only halve while divisibility survives (odd-factor T stops where
    # it is — the kernel then runs one bigger tile; correctness first)
    while tq > 16 and T % (tq // 2) == 0 and (
        tq * H * (Dh + 2 * block_size) * 4 > 5 * 2**20
    ):
        tq //= 2
    n_tiles = T // tq

    kp = k_cache.reshape(L, N, block_size, Hk, Dh)
    vp = v_cache.reshape(L, N, block_size, Hk, Dh)
    q5 = q.reshape(B, n_tiles, tq, H, Dh)
    layer_arr = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    starts = jnp.asarray(start_pos, jnp.int32)

    def kv_index(b, qi, j, lyr, st, t, c):
        # clamp dead steps onto the nearest live page: repeats skip the
        # HBM copy. Live range for tile qi: pages touching
        # [max(0, tile_start - window), min(tile_end, ctx))
        last_any = jnp.maximum((c[b] - 1) // block_size, 0)
        tile_hi = jnp.minimum(st[b] + (qi + 1) * tq, c[b])
        last = jnp.clip((tile_hi - 1) // block_size, 0, last_any)
        jj = jnp.minimum(j, last)
        if sliding_window is not None:
            first = jnp.clip(
                (st[b] + qi * tq - (sliding_window - 1)) // block_size,
                0, last,
            )
            jj = jnp.maximum(jj, first)
        return (lyr[0], t[b, jj], 0, 0, 0)

    in_specs = [
        pl.BlockSpec(
            (1, 1, tq, H, Dh),
            lambda b, qi, j, lyr, st, t, c: (b, qi, 0, 0, 0),
        ),
        pl.BlockSpec((1, 1, block_size, Hk, Dh), kv_index),
        pl.BlockSpec((1, 1, block_size, Hk, Dh), kv_index),
    ]
    inputs = [q5, kp, vp]
    if quantized:
        def scale_index(b, qi, j, lyr, st, t, c):
            return kv_index(b, qi, j, lyr, st, t, c)[:2] + (0, 0)

        in_specs += [
            pl.BlockSpec((1, 1, Hk, block_size), scale_index),
            pl.BlockSpec((1, 1, Hk, block_size), scale_index),
        ]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # layer, starts, block_tables, context_lens
        grid=(B, n_tiles, W),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, tq, H, Dh),
            lambda b, qi, j, lyr, st, t, c: (b, qi, 0, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((Hk * tq * (H // Hk), Dh), jnp.float32),
            pltpu.VMEM((Hk * tq * (H // Hk), 1), jnp.float32),
            pltpu.VMEM((Hk * tq * (H // Hk), 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _prefill_kernel_stacked, block_size=block_size, tq=tq,
            scale=scale, window=sliding_window, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_tiles, tq, H, Dh), q.dtype),
        interpret=interpret,
    )(layer_arr, starts, block_tables, context_lens, *inputs)
    return out.reshape(B, T, H, Dh)


@functools.partial(
    jax.jit, static_argnames=("block_size", "sliding_window", "interpret")
)
def paged_attention_decode(
    q: jax.Array,  # [B, H, Dh] (decode: one query token per sequence)
    k_cache_l: jax.Array,  # [n_slots, Hkv, Dh] (one layer)
    v_cache_l: jax.Array,
    block_tables: jax.Array,  # [B, W] int32
    context_lens: jax.Array,  # [B] int32
    block_size: int,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [N, Hkv, bs] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Returns [B, H, Dh] attention outputs.

    Thin wrapper over the stacked kernel with a single-layer stack
    (k_cache_l[None] is a free expand-dims) — ONE flash-decode kernel
    body serves both the per-layer API (tests, external callers) and
    the engine's stacked hot path."""
    return paged_attention_decode_stacked(
        q, k_cache_l[None], v_cache_l[None], jnp.int32(0), block_tables,
        context_lens, block_size=block_size, sliding_window=sliding_window,
        interpret=interpret,
        k_scale=None if k_scale is None else k_scale[None],
        v_scale=None if v_scale is None else v_scale[None],
    )
