"""Fused int8-weight × float-activation matmul Pallas kernels.

THE weight-bound decode hot path (ROADMAP item 2). The engine's int8
serving weights (models/quant.py) used to reach the MXU through a mixed
int8×bf16 ``jax.lax.dot_general`` — XLA materializes the upcast weight
tile in a way that never approaches int8-byte-bound (measured only
~1.3-2× over bf16 on v5e, far from the 2× byte ratio, and worse once
the scale multiply lands as a separate HBM-visiting op). These kernels
do what Marlin-style fused dequant GEMMs do on GPU: stream the int8
weight tiles from HBM, upcast **in register**, accumulate in f32, and
apply the per-output-channel f32 scale in the epilogue — the upcast
never exists in HBM, so the weight read is byte-bound at 1 B/elem.

Kernel family (one body, flag-specialized like ops/paged_attention.py):

- ``qmm``            — y = (x @ w_int8) * scale, optional fused
                        residual add in the epilogue (``wo`` / ``w_down``:
                        the decode residual never round-trips HBM between
                        the matmul and the add);
- ``qmm_gate_up``    — act(x @ Wg * sg) * (x @ Wu * su): both MLP weight
                        tensors stream through ONE kernel pass and the
                        SiLU·mul epilogue runs on the f32 accumulators'
                        tiles in VMEM (the [M, F] gate/up intermediates
                        never hit HBM);
- ``qmm_lm_head``    — the vocab-tiled variant: at V=128256 the LM head
                        is the single largest weight read of a decode
                        step, so N-tiling + a dedicated tune key matter.

Numerics contract (tests/test_qmatmul.py): int8→bf16 upcast is exact,
products accumulate in f32, the dequant scale applies in f32, and the
output rounds to the activation dtype exactly like the reference
``models.llama.mm`` epilogue — residual adds and the SiLU·mul run in
the output dtype so both impls round at the same points. Remaining
differences vs the reference are K-tile accumulation ORDER only.

Grid = (M-tiles, N-tiles, K-tiles), K innermost: the f32 accumulator
lives in VMEM scratch across K steps and every weight byte is read
exactly once per M-tile. Tile sizes come from a small autotune table
keyed on (M-bucket, K, N, kind) with an on-disk JSON cache in the style
of analysis/cache.py (atomic writes, every failure degrades to the
heuristic default); ``DYN_QMATMUL_TUNE=1`` measures candidates on real
hardware at engine prewarm and persists the winners.

Dispatch lives in ``models.llama.matmul_impl`` (DYN_MATMUL_IMPL =
auto|reference|pallas, mirroring DYN_ATTN_IMPL); off-TPU the kernels
run interpreted so tier-1 exercises them on CPU. Multi-device meshes
keep the reference path: the contraction axis of ``wo``/``w_down`` is
tp-sharded, and a shard_mapped qmatmul would need its own psum story —
single-chip decode (the headline bench) is where the weight-bound win
lives.
"""

from __future__ import annotations

import functools
import json
import os
import tempfile
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# M (token-rows) buckets the tune table is keyed on; the wrapper pads
# every call up to its bucket (padded rows compute zeros and are sliced
# off), so one compiled kernel serves each bucket like the engine's
# batch buckets do.
M_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


def m_bucket(m: int) -> int:
    for b in M_BUCKETS:
        if m <= b:
            return b
    # beyond the ladder: round UP to a multiple of the largest bucket
    # (rounding down would make the pad width negative and crash; every
    # bm candidate <= 512 divides any multiple of 8192)
    top = M_BUCKETS[-1]
    return -(-m // top) * top


# ---------------------------------------------------------------------------
# Tile selection: heuristic defaults + on-disk autotune table
# ---------------------------------------------------------------------------


def _largest_divisor(n: int, candidates: tuple[int, ...]) -> int:
    """Largest candidate dividing n, else n itself (a full dim is always
    a legal Mosaic block dim regardless of alignment)."""
    for c in candidates:
        if c <= n and n % c == 0:
            return c
    return n


def default_tiles(mb: int, K: int, N: int, kind: str) -> tuple[int, int, int]:
    """Heuristic (bm, bn, bk). Rationale: bm covers the whole decode
    batch in one tile (M is tiny next to K/N); bk ~512 keeps the x tile
    and accumulator small while amortizing the K-loop; bn ~512-1024
    makes the int8 weight tile the dominant VMEM tenant (that's the
    stream we must keep wide). All non-full tiles are multiples of 128
    so both the int8 sublane rule (32) and the lane rule (128) hold."""
    bm = min(mb, 256)
    bk = _largest_divisor(K, (512, 256, 128))
    if kind == "lm_head":
        # vocab is huge and M tiny: widen N so the weight stream (the
        # only traffic that matters at [D, 128256]) runs long tiles.
        # 768 divides 128256 (= 167 * 768); 512 does not.
        bn = _largest_divisor(N, (1024, 768, 512, 384, 256, 128))
    else:
        bn = _largest_divisor(N, (512, 384, 256, 128))
    if kind == "gate_up":
        # two weight tiles + two accumulators live at once: halve K
        # depth to keep the working set near the single-weight variants'
        bk = _largest_divisor(K, (256, 128))
    return bm, bn, bk


def _valid_tiles(tiles, mb: int, K: int, N: int) -> bool:
    """A tune-table entry is only trusted if it still describes a legal
    blocking — corrupt or stale entries degrade to the default."""
    if not (
        isinstance(tiles, (list, tuple))
        and len(tiles) == 3
        and all(isinstance(t, int) and t > 0 for t in tiles)
    ):
        return False
    bm, bn, bk = tiles
    if mb % bm or N % bn or K % bk:
        return False
    # non-full tiles must satisfy the lane rule
    if bn != N and bn % 128:
        return False
    if bk != K and bk % 128:
        return False
    if bm != mb and bm % 8:
        return False
    return True


def _tune_path() -> Optional[Path]:
    env = os.environ.get("DYN_QMATMUL_TUNE_DIR")
    if env:
        return Path(env) / "tune.json"
    try:
        from dynamo_tpu.analysis.config import find_pyproject

        pyproject = find_pyproject(Path(__file__).resolve())
        if pyproject is not None:
            return pyproject.parent / ".dynamo_qmatmul" / "tune.json"
    except Exception:
        pass
    return None


_table: Optional[dict] = None


def _load_table() -> dict:
    """Entries: {"kind:mb:K:N": [bm, bn, bk]}. Any failure — missing
    file, bad JSON, wrong schema — degrades to an empty table; the
    kernel must never be wrong or crash because of the cache."""
    global _table
    if _table is None:
        _table = {}
        path = _tune_path()
        if path is not None:
            try:
                data = json.loads(path.read_text())
                if isinstance(data, dict) and data.get("version") == 1:
                    entries = data.get("entries")
                    if isinstance(entries, dict):
                        _table = entries
            except (OSError, ValueError):
                _table = {}
    return _table


def _save_table() -> None:
    path = _tune_path()
    if path is None or _table is None:
        return
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent), suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps({"version": 1, "entries": _table}))
        os.replace(tmp, path)
    except OSError:
        pass  # a table that can't persist is just an unwarmed table


def _reset_table_for_tests() -> None:
    global _table
    _table = None


def tune_key(m: int, K: int, N: int, kind: str) -> str:
    return f"{kind}:{m_bucket(m)}:{K}:{N}"


def tile_config(m: int, K: int, N: int, kind: str) -> tuple[int, int, int]:
    """(bm, bn, bk) for this shape: the tuned entry when one exists and
    still validates, the heuristic default otherwise."""
    mb = m_bucket(m)
    entry = _load_table().get(tune_key(m, K, N, kind))
    if entry is not None and _valid_tiles(entry, mb, K, N):
        return tuple(entry)
    return default_tiles(mb, K, N, kind)


def record_tiles(
    m: int, K: int, N: int, kind: str, tiles: tuple[int, int, int]
) -> None:
    table = _load_table()
    table[tune_key(m, K, N, kind)] = list(tiles)
    _save_table()


def _candidate_tiles(mb: int, K: int, N: int, kind: str):
    """Small candidate grid around the default (autotune is a table fill,
    not a search problem — a handful of compiles per shape)."""
    seen = set()
    bms = {min(mb, 128), min(mb, 256), min(mb, 512)}
    bns = {
        _largest_divisor(N, (c,)) for c in (256, 384, 512, 768, 1024)
    } | {default_tiles(mb, K, N, kind)[1]}
    bks = {_largest_divisor(K, (c,)) for c in (128, 256, 512, 1024)}
    for bm in sorted(bms):
        for bn in sorted(bns):
            for bk in sorted(bks):
                t = (bm, bn, bk)
                if t not in seen and _valid_tiles(list(t), mb, K, N):
                    seen.add(t)
                    yield t


def autotune(
    m: int, K: int, N: int, kind: str, dtype=jnp.bfloat16, repeats: int = 3
) -> tuple[int, int, int]:
    """Measure candidate tilings on the real device and persist the
    winner. TPU only — interpret-mode timings would tune for the
    emulator; off-TPU this returns the default untouched."""
    import time

    if jax.default_backend() != "tpu":
        return tile_config(m, K, N, kind)
    mb = m_bucket(m)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (mb, K), jnp.float32).astype(dtype)
    w = jax.random.randint(key, (K, N), -127, 128, jnp.int8)
    s = jnp.full((N,), 0.01, jnp.float32)
    best, best_t = None, float("inf")
    res = jnp.zeros((mb, N), dtype)
    for tiles in _candidate_tiles(mb, K, N, kind):
        try:
            # measure the EXACT kernel variant the serving path
            # dispatches for this kind — the residual epilogue streams
            # an extra [bm, bn] input per tile, a different traffic
            # profile than the plain kernel
            if kind == "gate_up":
                fn = jax.jit(lambda a: qmm_gate_up(a, w, s, w, s, tiles=tiles))
            elif kind == "residual":
                fn = jax.jit(
                    lambda a: qmm(a, w, s, residual=res, tiles=tiles)
                )
            elif kind == "lm_head":
                fn = jax.jit(lambda a: qmm_lm_head(a, w, s, tiles=tiles))
            else:
                fn = jax.jit(lambda a: qmm(a, w, s, tiles=tiles))
            jax.block_until_ready(fn(x))  # compile
            t0 = time.monotonic()
            for _ in range(repeats):
                out = fn(x)
            jax.block_until_ready(out)
            dt = (time.monotonic() - t0) / repeats
        except Exception:
            continue  # a candidate Mosaic rejects is just not a candidate
        if dt < best_t:
            best, best_t = tiles, dt
    if best is not None:
        record_tiles(m, K, N, kind, best)
        return best
    return tile_config(m, K, N, kind)


# ---------------------------------------------------------------------------
# The kernel body (flag-specialized: residual / gate-up epilogues)
# ---------------------------------------------------------------------------


def _act(name: str, g: jax.Array) -> jax.Array:
    """Gate activation, mirroring models.llama.mlp_act (same failure
    contract: silently substituting silu would serve corrupt logits)."""
    if name == "gelu":
        return jax.nn.gelu(g, approximate=True)
    if name == "silu":
        return jax.nn.silu(g)
    raise ValueError(f"unsupported activation {name!r}")


def _qmm_kernel(
    *refs,
    n_k: int,
    fused: str,  # "" | "residual" | "gate_up"
    act: str,
):
    """One (bm, bn) output tile accumulated over the K grid axis.

    refs layout by variant:
      plain:    x, w, s, o, acc
      residual: x, w, s, r, o, acc
      gate_up:  x, wg, sg, wu, su, o, accg, accu

    The int8 weight tile upcasts to the activation dtype IN REGISTER
    (exact: |w| <= 127 is representable in bf16) and feeds the MXU as a
    native bf16×bf16 dot with f32 accumulation — the dequant scale
    multiplies the f32 accumulator once, in the epilogue."""
    if fused == "gate_up":
        x_ref, wg_ref, sg_ref, wu_ref, su_ref, o_ref, accg_ref, accu_ref = refs
    elif fused == "residual":
        x_ref, w_ref, s_ref, r_ref, o_ref, acc_ref = refs
    else:
        x_ref, w_ref, s_ref, o_ref, acc_ref = refs
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        if fused == "gate_up":
            accg_ref[:] = jnp.zeros_like(accg_ref)
            accu_ref[:] = jnp.zeros_like(accu_ref)
        else:
            acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    dims = (((1,), (0,)), ((), ()))
    if fused == "gate_up":
        accg_ref[:] += jax.lax.dot_general(
            x, wg_ref[:].astype(x.dtype), dims,
            preferred_element_type=jnp.float32,
        )
        accu_ref[:] += jax.lax.dot_general(
            x, wu_ref[:].astype(x.dtype), dims,
            preferred_element_type=jnp.float32,
        )
    else:
        acc_ref[:] += jax.lax.dot_general(
            x, w_ref[:].astype(x.dtype), dims,
            preferred_element_type=jnp.float32,
        )

    @pl.when(k == n_k - 1)
    def _epilogue():
        if fused == "gate_up":
            # round each dequantized matmul to the output dtype BEFORE
            # the activation — the same rounding points as the reference
            # mlp_act(mm(gate)) * mm(up) composition
            g = (accg_ref[:] * sg_ref[:]).astype(o_ref.dtype)
            u = (accu_ref[:] * su_ref[:]).astype(o_ref.dtype)
            o_ref[:] = _act(act, g) * u
        elif fused == "residual":
            # residual add in the output dtype (reference: x + mm(...)
            # .astype(x.dtype) — the cast happens before the add)
            o_ref[:] = r_ref[:] + (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)
        else:
            o_ref[:] = (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)


def _qmm_call(
    x2: jax.Array,  # [M, K] float activations (bf16/f32)
    weights: list[jax.Array],  # one [K, N] int8, or two for gate_up
    scales: list[jax.Array],  # matching [N] f32 per-channel scales
    residual2: Optional[jax.Array],  # [M, N] or None
    kind: str,
    fused: str,
    act: str,
    interpret: bool,
    tiles: Optional[tuple[int, int, int]],
) -> jax.Array:
    M, K = x2.shape
    N = weights[0].shape[1]
    for w in weights:
        assert w.dtype == jnp.int8 and w.shape == (K, N)
    bm, bn, bk = tiles if tiles is not None else tile_config(M, K, N, kind)
    mp = m_bucket(M)
    bm = min(bm, mp)
    # explicit `tiles` bypasses _valid_tiles — a non-dividing blocking
    # would silently leave output columns unwritten (grid floor-division
    # drops the remainder), so fail loudly instead
    if mp % bm or N % bn or K % bk:
        raise ValueError(
            f"tiles (bm={bm}, bn={bn}, bk={bk}) must divide the padded "
            f"problem (M={mp}, N={N}, K={K})"
        )
    if M != mp:
        x2 = jnp.pad(x2, ((0, mp - M), (0, 0)))
        if residual2 is not None:
            residual2 = jnp.pad(residual2, ((0, mp - M), (0, 0)))
    grid = (mp // bm, N // bn, K // bk)

    in_specs = [pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))]
    inputs: list[jax.Array] = [x2]
    for w, s in zip(weights, scales):
        in_specs.append(pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)))
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, k: (0, j)))
        inputs.append(w)
        inputs.append(s.reshape(1, N).astype(jnp.float32))
    if residual2 is not None:
        in_specs.append(pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)))
        inputs.append(residual2)

    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    if fused == "gate_up":
        scratch.append(pltpu.VMEM((bm, bn), jnp.float32))

    out = pl.pallas_call(
        functools.partial(
            _qmm_kernel, n_k=grid[2], fused=fused, act=act
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, N), x2.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*inputs)
    return out[:M] if M != mp else out


def _flatten(x: jax.Array) -> tuple[jax.Array, tuple[int, ...]]:
    return x.reshape(-1, x.shape[-1]), x.shape[:-1]


def qmm(
    x: jax.Array,  # [..., K] float activations
    w: jax.Array,  # [K, N] int8
    scale: jax.Array,  # [N] f32 per-output-channel dequant scale
    residual: Optional[jax.Array] = None,  # [..., N] fused epilogue add
    kind: str = "mm",
    interpret: bool = False,
    tiles: Optional[tuple[int, int, int]] = None,
) -> jax.Array:
    """y = (x @ w) * scale (+ residual), rounded to x.dtype — the
    in-kernel-dequant replacement for the reference ``mm`` epilogue."""
    x2, lead = _flatten(x)
    r2 = None
    if residual is not None:
        r2, _ = _flatten(residual)
        kind = "residual" if kind == "mm" else kind
    y = _qmm_call(
        x2, [w], [scale], r2, kind,
        "residual" if residual is not None else "", "silu", interpret, tiles,
    )
    return y.reshape(*lead, w.shape[1])


def qmm_gate_up(
    x: jax.Array,  # [..., D]
    w_gate: jax.Array,  # [D, F] int8
    gate_scale: jax.Array,  # [F] f32
    w_up: jax.Array,  # [D, F] int8
    up_scale: jax.Array,  # [F] f32
    act: str = "silu",
    interpret: bool = False,
    tiles: Optional[tuple[int, int, int]] = None,
) -> jax.Array:
    """act(x @ Wg * sg) * (x @ Wu * su) — both MLP weights stream in one
    kernel pass; the [..., F] gate/up intermediates never touch HBM."""
    x2, lead = _flatten(x)
    y = _qmm_call(
        x2, [w_gate, w_up], [gate_scale, up_scale], None, "gate_up",
        "gate_up", act, interpret, tiles,
    )
    return y.reshape(*lead, w_gate.shape[1])


def qmm_lm_head(
    x: jax.Array,  # [..., D] final hidden states
    w: jax.Array,  # [D, V] int8
    scale: jax.Array,  # [V] f32
    interpret: bool = False,
    tiles: Optional[tuple[int, int, int]] = None,
) -> jax.Array:
    """The vocab-tiled LM-head qmm (its own tune key: at V=128256 this
    is the single largest weight read per decode step). Output rounds
    to x.dtype exactly like ``mm`` — the caller upcasts to f32 for
    sampling, same as the reference path."""
    x2, lead = _flatten(x)
    y = _qmm_call(
        x2, [w], [scale], None, "lm_head", "", "silu", interpret, tiles
    )
    return y.reshape(*lead, w.shape[1])


def ensure_tuned(
    shapes: list[tuple[int, int, int, str]], tune: Optional[bool] = None
) -> None:
    """Engine-prewarm hook: make sure every reachable (M, K, N, kind)
    has a tile config ready before the step functions trace. With
    DYN_QMATMUL_TUNE=1 on TPU this measures and persists winners (a few
    compiles per missing shape — one-time, cached on disk); otherwise
    the heuristic defaults serve, and any previously-tuned entries load
    from the cache."""
    if tune is None:
        tune = os.environ.get("DYN_QMATMUL_TUNE") == "1"
    table = _load_table()
    for m, K, N, kind in shapes:
        if tune and tune_key(m, K, N, kind) not in table:
            autotune(m, K, N, kind)
        else:
            tile_config(m, K, N, kind)  # validates/loads the entry
