"""Device mesh + sharding utilities (the TPU-native parallelism layer).

Where the reference delegates tensor/expert parallelism to its engines'
NCCL groups (reference: SURVEY.md §2.6), dynamo-tpu owns them natively:
a `jax.sharding.Mesh` with named axes

  dp — data parallel (batch)           sp — sequence/context parallel
  tp — tensor parallel (heads/hidden)  ep — expert parallel (MoE)
  pp — pipeline parallel (layer stages, parallel/pipeline.py)

and `NamedSharding` rules applied to params, KV cache, and activations.
XLA inserts the collectives (psum/all-gather/reduce-scatter) over ICI.
"""

from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh, shard

__all__ = ["MeshConfig", "build_mesh", "shard", "forward_pp"]


def __getattr__(name):
    if name == "forward_pp":  # lazy: pipeline pulls in the model module
        from dynamo_tpu.parallel.pipeline import forward_pp

        return forward_pp
    raise AttributeError(name)
