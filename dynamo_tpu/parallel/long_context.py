"""Sequence-parallel long-context prefill.

The reference has no long-context scaling (SURVEY.md §5: no ring/
Ulysses/context-parallel anywhere); it routes long prompts to dedicated
prefill engines and offloads KV. The TPU build makes long context
first-class: a prefill worker can shard the PROMPT over an ``sp`` mesh
axis and run exact causal attention with ring (ICI-neighbor ppermute)
or Ulysses (all-to-all) communication — parallel/ring_attention.py —
then hand the resulting KV blocks to the normal disagg transfer plane.
Decode workers stay tensor-parallel; the prefill-sp ↔ decode-tp handoff
rides the same content-hash-addressed block shipment as every other
remote prefill (disagg/worker.py), so sequence parallelism composes
with disaggregation instead of complicating the decode engine.

Design notes (TPU-first):
- prompts pad to a multiple of the sp degree; causal masking keeps pad
  positions from influencing real ones, and padded KV is dropped before
  packing (only full token blocks ship);
- the transformer body is the same stacked-layer ``lax.scan`` as
  models/llama.py, with per-layer K/V (post-RoPE) stacked as scan
  outputs — exactly the paged cache's content, just dense;
- sp-mesh prefill runs tp=1: head sharding belongs to decode. The
  transfer plane's head-slice path covers multi-host TP prefill
  (ops/kv_rearrange.py) if both are ever combined.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    Params,
    _moe_mlp,
    embed_lookup,
    layer_param_names,
    mlp_act,
    mm,
    rmsnorm,
    rope,
    scale_embed,
)
from dynamo_tpu.parallel.ring_attention import ring_attention, ulysses_attention
from dynamo_tpu.tokens import TokenBlockSequence


def long_prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [1, T] int32, T divisible by mesh sp degree
    mesh: Mesh,
    attn: str = "ring",
    last_idx: Optional[jax.Array] = None,  # index of the last REAL token
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Full-prompt forward with sequence-sharded attention.

    Returns (last_logits [1, V], k [L, T, Hkv, Dh], v [L, T, Hkv, Dh]).
    ``last_idx`` points at the last real token when the prompt was
    padded (logits are taken there, not at a pad position).
    """
    if cfg.sliding_window is not None:
        # ring/ulysses attention here is full-causal; serving a
        # sliding-window model through it would silently export KV the
        # decode engine disagrees with
        raise ValueError(
            "sequence-parallel prefill does not support sliding-window "
            "models yet"
        )
    H, Hk, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    B, T = tokens.shape
    attend = ring_attention if attn == "ring" else ulysses_attention
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]

    x = scale_embed(cfg, embed_lookup(params, tokens))  # [1, T, D]

    def layer_fn(x, lp):
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
        q = mm(lp, "wq", h)
        k = mm(lp, "wk", h)
        v = mm(lp, "wv", h)
        if cfg.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(B, T, H, Dh)
        k = k.reshape(B, T, Hk, Dh)
        v = v.reshape(B, T, Hk, Dh)
        q, k = rope(q, k, positions, cfg.rope_theta)
        a = attend(q, k, v, mesh)
        x = x + mm(lp, "wo", a.reshape(B, T, H * Dh)).astype(x.dtype)
        h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
        if cfg.is_moe:
            x = x + _moe_mlp(cfg, lp, h).astype(x.dtype)
        else:
            mlp = mm(
                lp, "w_down", mlp_act(cfg, mm(lp, "w_gate", h)) * mm(lp, "w_up", h)
            )
            x = x + mlp.astype(x.dtype)
        return x, (k, v)

    layer_params = {n: params[n] for n in layer_param_names(params)}
    x, (ks, vs) = jax.lax.scan(layer_fn, x, layer_params)
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
    if last_idx is None:
        last_idx = jnp.asarray(T - 1, jnp.int32)
    x_last = jax.lax.dynamic_index_in_dim(x, last_idx, axis=1, keepdims=False)
    logits = mm(params, "lm_head", x_last).astype(jnp.float32)
    # [L, 1, T, Hk, Dh] -> [L, T, Hk, Dh]
    return logits, ks[:, 0], vs[:, 0]


def kv_to_packed_blocks(
    k: np.ndarray, v: np.ndarray, block_size: int, n_tokens: int
) -> np.ndarray:
    """Dense per-token KV [L, T, Hkv, Dh] -> packed transfer blocks
    [n_full_blocks, 2, L, block_size, Hkv, Dh] (the kvbm/layout.py wire
    shape); the partial tail block is dropped (decode recomputes it)."""
    n_blocks = n_tokens // block_size
    L, _, Hk, Dh = k.shape
    out = np.empty((n_blocks, 2, L, block_size, Hk, Dh), k.dtype)
    for b in range(n_blocks):
        sl = slice(b * block_size, (b + 1) * block_size)
        out[b, 0] = k[:, sl]
        out[b, 1] = v[:, sl]
    return out


class LongContextPrefiller:
    """Duck-types what the disagg prefill loop needs (config.block_size +
    prefill_export) while running sequence-parallel instead of through an
    engine scheduler."""

    def __init__(
        self,
        model_config: ModelConfig,
        params: Params,
        mesh: Mesh,
        block_size: int,
        attn: str = "ring",
        kv_dtype: str = "bfloat16",
    ):
        if "sp" not in mesh.axis_names:
            raise ValueError("LongContextPrefiller needs an 'sp' mesh axis")
        if model_config.sliding_window is not None:
            raise ValueError(
                "sequence-parallel prefill does not support sliding-window "
                "models yet"
            )
        self.model_config = model_config
        self.params = params
        self.mesh = mesh
        self.sp = mesh.shape["sp"]
        self.attn = attn
        self.kv_dtype = kv_dtype

        from dataclasses import dataclass

        @dataclass
        class _Cfg:
            block_size: int

        self.config = _Cfg(block_size=block_size)
        # mesh is closed over (not a traceable argument)
        self._fn = jax.jit(
            functools.partial(long_prefill, model_config, mesh=mesh, attn=attn)
        )

    def _pad(self, token_ids: list[int]) -> tuple[np.ndarray, int]:
        T = len(token_ids)
        # pad to a multiple of sp so the sequence shards evenly; causal
        # masking keeps pad positions from influencing real ones
        Tp = -(-T // self.sp) * self.sp
        arr = np.zeros((1, Tp), np.int32)
        arr[0, :T] = token_ids
        return arr, T

    def prefill(self, token_ids: list[int]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (last_logits [V], k [L, T, Hkv, Dh], v) for the REAL tokens."""
        arr, T = self._pad(token_ids)
        sharding = NamedSharding(self.mesh, P(None, "sp"))
        arr = jax.device_put(arr, sharding)
        with self.mesh:
            logits, k, v = self._fn(
                self.params, arr, last_idx=jnp.asarray(T - 1, jnp.int32)
            )
        last = np.asarray(logits)[0]
        return last, np.asarray(k[:, :T]), np.asarray(v[:, :T])

    async def prefill_export(
        self, token_ids: list[int]
    ) -> tuple[list[int], np.ndarray]:
        """Disagg hook: -> (block sequence hashes, packed blocks)."""
        bs = self.config.block_size
        loop = asyncio.get_running_loop()

        def run():
            _, k, v = self.prefill(token_ids)
            packed = kv_to_packed_blocks(
                k.astype(_np_dtype(self.kv_dtype)),
                v.astype(_np_dtype(self.kv_dtype)),
                bs,
                len(token_ids),
            )
            return packed

        packed = await loop.run_in_executor(None, run)
        tokens = TokenBlockSequence(list(token_ids), block_size=bs)
        hashes = tokens.sequence_hashes()[: len(token_ids) // bs]
        return hashes[: packed.shape[0]], packed


def _np_dtype(name: str):
    from dynamo_tpu.kvbm.layout import resolve_dtype

    return resolve_dtype(name)
