"""Mesh construction + sharding helpers."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# mesh dimension order (single source of truth for build_mesh's reshape):
# pp outermost after dp (stage hops cross the slower interconnect), tp
# innermost so TP collectives ride the fastest ICI dimension.
AXES = ("dp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Product must equal the device count in use.

    For inference engines the common shapes are (dp=1, tp=N) for dense
    models, (dp=1, tp=k, ep=m) for MoE decode, and (pp=s, tp=k) for
    pipeline-staged very deep models (parallel/pipeline.py).
    """

    dp: int = 1
    pp: int = 1
    tp: int = 1
    ep: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp * self.ep * self.sp

    def axis_sizes(self) -> dict[str, int]:
        return {
            "dp": self.dp, "pp": self.pp, "tp": self.tp,
            "ep": self.ep, "sp": self.sp,
        }


def build_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a named-axis mesh over the given (or all) devices.

    Axis order is (dp, tp, ep, sp) with tp innermost-but-one so TP
    collectives ride the fastest ICI dimension on real slices.
    """
    devices = list(devices if devices is not None else jax.devices())
    if config.size != len(devices):
        raise ValueError(
            f"mesh {config} needs {config.size} devices, have {len(devices)}"
        )
    sizes = config.axis_sizes()
    arr = np.asarray(devices).reshape(*(sizes[a] for a in AXES))
    return Mesh(arr, axis_names=AXES)


def shard(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding shorthand: shard(mesh, 'tp', None) etc."""
    return NamedSharding(mesh, P(*spec))


def host_to_device(mesh: Mesh, array, *spec):
    """device_put with a named sharding."""
    return jax.device_put(array, shard(mesh, *spec))
