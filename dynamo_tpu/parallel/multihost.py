"""Multi-host serving: leader→follower step broadcast.

The reference brings up multi-node engines with a leader that owns
scheduling and followers that execute the same device program
(reference: lib/llm/src/engines.rs:41-58 MultiNodeConfig — SGLang-style
leader_addr/node_rank bring-up). The JAX equivalent: every process
holds its shard of the globally-sharded params/KV cache, and every
process must enter the SAME jitted step with the SAME host inputs for
the collectives to line up.

Node rank 0 (the leader) runs the scheduler, batching, detokenization
and serving planes exactly as single-host. Before each device dispatch
it broadcasts (a) a fixed-size control vector describing the step kind
and array geometry, then (b) the host input arrays themselves — both
via ``multihost_utils.broadcast_one_to_all``, which rides the same
ICI/DCN fabric as the model collectives (no extra sockets, no second
cluster plane). Followers loop: receive control, allocate
matching-shape buffers, receive arrays, enter the identical jit. A STOP
control exits the loop at shutdown.

Why not broadcast through the coordinator/store? Step inputs are on the
critical path (every decode window); the store is a control plane. The
reference makes the same split: NATS for control, direct links for data.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# control vector layout (int32[8]):
# [kind, B, T, table_width, flags, reserved, reserved, reserved]
CTRL_LEN = 8
KIND_STOP = 0
KIND_STEP = 1  # single fused step (prefill or 1-token decode)
KIND_MULTI_STEP = 2  # fused K-step decode window


class StepBroadcaster:
    """Leader side: announce each device step to the followers."""

    def __init__(self) -> None:
        from jax.experimental import multihost_utils

        self._bcast = multihost_utils.broadcast_one_to_all

    def _ctrl(self, kind: int, b: int = 0, t: int = 0, w: int = 0) -> None:
        ctrl = np.zeros((CTRL_LEN,), np.int32)
        ctrl[:4] = (kind, b, t, w)
        self._bcast(ctrl)

    def announce_step(self, arrays: dict, sampling) -> None:
        b, t = arrays["tokens"].shape
        w = arrays["block_tables"].shape[1]
        self._ctrl(KIND_STEP, b, t, w)
        self._bcast(_step_tuple(arrays, sampling))

    def announce_multi_step(self, arrays: dict, sampling) -> None:
        b = arrays["tokens"].shape[0]
        w = arrays["block_tables"].shape[1]
        self._ctrl(KIND_MULTI_STEP, b, 1, w)
        self._bcast(_multi_step_tuple(arrays, sampling))

    def announce_stop(self) -> None:
        self._ctrl(KIND_STOP)


def _step_tuple(arrays: dict, sampling) -> tuple:
    return (
        np.asarray(arrays["tokens"], np.int32),
        np.asarray(arrays["positions"], np.int32),
        np.asarray(arrays["slot_mapping"], np.int32),
        np.asarray(arrays["block_tables"], np.int32),
        np.asarray(arrays["context_lens"], np.int32),
        np.asarray(arrays["last_token_idx"], np.int32),
        np.asarray(sampling.temperature, np.float32),
        np.asarray(sampling.top_k, np.int32),
        np.asarray(sampling.top_p, np.float32),
        np.asarray(sampling.seeds, np.uint32),
    )


def _multi_step_tuple(arrays: dict, sampling) -> tuple:
    return (
        np.asarray(arrays["tokens"], np.int32),
        np.asarray(arrays["positions"], np.int32),
        np.asarray(arrays["block_tables"], np.int32),
        np.asarray(arrays["context_lens"], np.int32),
        np.asarray(arrays["valid_steps"], np.int32),
        np.asarray(sampling.temperature, np.float32),
        np.asarray(sampling.top_k, np.int32),
        np.asarray(sampling.top_p, np.float32),
        np.asarray(sampling.seeds, np.uint32),
    )


def _zeros_step(b: int, t: int, w: int) -> tuple:
    return (
        np.zeros((b, t), np.int32),
        np.zeros((b, t), np.int32),
        np.zeros((b * t,), np.int32),
        np.zeros((b, w), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.float32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.float32),
        np.zeros((b,), np.uint32),
    )


def _zeros_multi_step(b: int, w: int) -> tuple:
    return (
        np.zeros((b, 1), np.int32),
        np.zeros((b, 1), np.int32),
        np.zeros((b, w), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.float32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.float32),
        np.zeros((b,), np.uint32),
    )


class StepFollower:
    """Follower side: mirror the leader's device dispatches until STOP.

    ``step_fn``/``multi_step_fn`` are the engine's jitted functions;
    ``get_state``/``set_state`` read and write the (params, k_cache,
    v_cache) triple so donated caches stay threaded between steps.
    """

    def __init__(self, engine) -> None:
        from jax.experimental import multihost_utils

        self._bcast = multihost_utils.broadcast_one_to_all
        self.engine = engine

    def run(self) -> None:
        e = self.engine
        while True:
            ctrl = np.asarray(self._bcast(np.zeros((CTRL_LEN,), np.int32)))
            kind, b, t, w = (int(x) for x in ctrl[:4])
            if kind == KIND_STOP:
                return
            if kind == KIND_STEP:
                args = self._bcast(_zeros_step(b, t, w))
                (tokens, positions, slots, tables, ctx, last,
                 temp, tk, tp, seeds) = args
                _, _, e.k_cache, e.v_cache = e._step_fn(
                    e.params, e.k_cache, e.v_cache, tokens, positions,
                    slots, tables, ctx, last, temp, tk, tp, seeds,
                )
            elif kind == KIND_MULTI_STEP:
                args = self._bcast(_zeros_multi_step(b, w))
                (tokens, positions, tables, ctx, valid,
                 temp, tk, tp, seeds) = args
                _, _, e.k_cache, e.v_cache = e._multi_step_fn(
                    e.params, e.k_cache, e.v_cache, tokens, positions,
                    tables, ctx, valid, temp, tk, tp, seeds,
                )
            else:
                raise RuntimeError(f"unknown multihost step kind {kind}")


def host_value(arr) -> np.ndarray:
    """Device array -> host numpy, robust to multi-host replication:
    np.asarray refuses non-fully-addressable arrays, but every process
    holds a complete copy of replicated outputs in its local shard."""
    try:
        return np.asarray(arr)
    except Exception:
        return np.asarray(arr.addressable_data(0))
