"""Multi-host serving: leader→follower step broadcast.

The reference brings up multi-node engines with a leader that owns
scheduling and followers that execute the same device program
(reference: lib/llm/src/engines.rs:41-58 MultiNodeConfig — SGLang-style
leader_addr/node_rank bring-up). The JAX equivalent: every process
holds its shard of the globally-sharded params/KV cache, and every
process must enter the SAME jitted step with the SAME host inputs for
the collectives to line up.

Node rank 0 (the leader) runs the scheduler, batching, detokenization
and serving planes exactly as single-host. Before each device dispatch
it broadcasts (a) a fixed-size control vector describing the step kind
and array geometry, then (b) the host input arrays themselves — both
via ``multihost_utils.broadcast_one_to_all``, which rides the same
ICI/DCN fabric as the model collectives (no extra sockets, no second
cluster plane). Followers loop: receive control, allocate
matching-shape buffers, receive arrays, enter the identical jit. A STOP
control exits the loop at shutdown.

Why not broadcast through the coordinator/store? Step inputs are on the
critical path (every decode window); the store is a control plane. The
reference makes the same split: NATS for control, direct links for data.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

# control vector layout (int32[16]):
# [kind, B, T, table_width, sampling_flags, bias_width, gen_width,
#  prompt_width, P, T_rect, p_flags, p_bias_width, p_gen_width,
#  p_prompt_width, 0, 0] — slots 4-7 describe the (decode) sampling
# dict structure, slots 8-13 the mixed step's prefill rectangle and its
# sampling dict, so followers can allocate matching broadcast buffers
CTRL_LEN = 16
FLAG_PENALTIES = 1  # sampling dict carries the penalty tables
FLAG_TOPLP = 2  # sampling dict carries the top-logprobs marker
FLAG_BIAS = 4  # sampling dict carries the logit-bias tables

# fixed key order for broadcasting SamplingBatch.arrays as a tuple
SAMPLING_BASE_KEYS = (
    ("temperature", np.float32), ("top_k", np.int32), ("top_p", np.float32),
    ("min_p", np.float32), ("seeds", np.uint32),
)
SAMPLING_BIAS_KEYS = (
    ("bias_ids", np.int32), ("bias_vals", np.float32),
)
SAMPLING_PEN_KEYS = (
    ("freq_pen", np.float32), ("pres_pen", np.float32),
    ("rep_pen", np.float32),
    ("gen_ids", np.int32), ("gen_counts", np.float32),
    ("prompt_ids", np.int32), ("prompt_counts", np.float32),
)
KIND_STOP = 0
KIND_STEP = 1  # single fused step (prefill or 1-token decode)
KIND_MULTI_STEP = 2  # fused K-step decode window
KIND_KV_GATHER = 3  # mirrored KV offload gather (shard-local store)
KIND_KV_SCATTER = 4  # mirrored KV onboard scatter (shard-local load)
KIND_KV_DISABLE = 5  # leader-side offload failure: drop shard pools
KIND_MIXED = 6  # mixed prefill-rectangle + K-step decode window
KIND_KV_EXPORT = 7  # mirrored replicated gather (disagg KV export)
KIND_KV_IMPORT = 8  # broadcast full blocks; each process pools its shard
KIND_STEP_MM = 9  # single step + multimodal embed rectangle (VLM)
KIND_CHAIN = 10  # next window's token column = device-chained outputs


class FatalMultihostError(RuntimeError):
    """A failure INSIDE a mirrored collective (after the announce, while
    followers are already blocked in the same jitted op). The lockstep
    recovery protocol (KIND_KV_DISABLE) only works BETWEEN complete
    mirrored ops — a disable broadcast issued now would mismatch the
    followers' in-flight collective and hang or desync the job, so the
    only safe response is to take the multihost job down."""


class StepBroadcaster:
    """Leader side: announce each device step to the followers."""

    def __init__(self) -> None:
        from jax.experimental import multihost_utils

        self._bcast = multihost_utils.broadcast_one_to_all

    def _ctrl(
        self, kind: int, b: int = 0, t: int = 0, w: int = 0,
        sampling: Optional[dict] = None,
    ) -> None:
        ctrl = np.zeros((CTRL_LEN,), np.int32)
        ctrl[:4] = (kind, b, t, w)
        if sampling is not None:
            _fill_sampling_desc(ctrl, 4, sampling)
        self._bcast(ctrl)

    def announce_step(self, arrays: dict, sampling) -> None:
        b, t = arrays["tokens"].shape
        w = arrays["block_tables"].shape[1]
        self._ctrl(KIND_STEP, b, t, w, sampling.arrays)
        self._bcast(_step_tuple(arrays, sampling))

    def announce_step_mm(self, arrays: dict, sampling) -> None:
        """Multimodal prefill step: the embed rectangle [B, T, D] f32 +
        its bool mask ride the broadcast after the step arrays (D is
        model hidden_size — both sides derive it, so the control word
        stays unchanged). Reference analogue: the multimodal examples'
        encode-worker -> LLM embedding handoff running multinode
        (examples/multimodal/)."""
        b, t = arrays["tokens"].shape
        w = arrays["block_tables"].shape[1]
        self._ctrl(KIND_STEP_MM, b, t, w, sampling.arrays)
        self._bcast(
            _step_tuple(arrays, sampling)
            + (
                np.asarray(arrays["extra_embeds"], np.float32),  # dynalint: disable=transitive-host-sync-in-step-loop — host-built embed rectangle (np.ndarray from the encode worker); dtype coercion touches host memory only
                # bool over the wire as uint8: broadcast dtype safety
                np.asarray(arrays["embeds_mask"], np.uint8),  # dynalint: disable=transitive-host-sync-in-step-loop — host-built bool mask; uint8 coercion for the wire, no device handle here
            )
        )

    def announce_chain(self, src_idx: np.ndarray, prev_mixed: bool) -> None:
        """Pipelined window: the NEXT multi-step/mixed announce's token
        column must come from each process's OWN retained device
        outputs (chain_tokens over the previous window's last-token
        column / prefill graduations) — the host token values in that
        announce are placeholders. This is what lifts the decode
        pipeline's single-host limit: followers never need the leader's
        host token values, they compute the identical chain from the
        identical device state."""
        self._ctrl(KIND_CHAIN, len(src_idx), int(prev_mixed))
        self._bcast((np.asarray(src_idx, np.int32),))  # dynalint: disable=transitive-host-sync-in-step-loop — src_idx is the scheduler's host-built row-source column, never a device array

    def announce_multi_step(self, arrays: dict, sampling) -> None:
        b = arrays["tokens"].shape[0]
        w = arrays["block_tables"].shape[1]
        self._ctrl(KIND_MULTI_STEP, b, 1, w, sampling.arrays)
        self._bcast(_multi_step_tuple(arrays, sampling))

    def announce_mixed(
        self, p_arrays: dict, p_sampling, d_arrays: dict, d_sampling
    ) -> None:
        ctrl = np.zeros((CTRL_LEN,), np.int32)
        ctrl[0] = KIND_MIXED
        ctrl[1] = d_arrays["tokens"].shape[0]
        ctrl[2] = 1
        ctrl[3] = d_arrays["block_tables"].shape[1]  # == p width (padded)
        _fill_sampling_desc(ctrl, 4, d_sampling.arrays)
        ctrl[8], ctrl[9] = p_arrays["tokens"].shape
        _fill_sampling_desc(ctrl, 10, p_sampling.arrays)
        self._bcast(ctrl)
        self._bcast(
            _step_tuple(p_arrays, p_sampling)
            + _multi_step_tuple(d_arrays, d_sampling)
        )

    def announce_kv(self, kind: int, block_ids: list[int],
                    seq_hashes: list[int]) -> None:
        """Mirrored KV gather/scatter: every process must enter the same
        jitted copy with the same ids; hashes key each process's
        shard-local pool. Hashes travel as two uint32 halves — JAX
        canonicalizes uint64 to uint32 (x64 disabled), which would
        silently truncate the xxh3 keys in flight."""
        self._ctrl(kind, len(block_ids))
        self._bcast((
            np.asarray(block_ids, np.int32),
            _split_hashes(seq_hashes),
        ))

    def announce_kv_export(self, block_ids: list[int]) -> None:
        """Disagg export: all processes must enter the same replicated
        gather (mirror_gather_full)."""
        self._ctrl(KIND_KV_EXPORT, len(block_ids))
        self._bcast((np.asarray(block_ids, np.int32),))

    def announce_kv_import(
        self, seq_hashes: list[int], packed_full: np.ndarray
    ) -> None:
        """Disagg import: ship the full blocks to every process; each
        inserts ITS head slice into its shard pool (lockstep kept)."""
        self._ctrl(KIND_KV_IMPORT, len(seq_hashes))
        self._bcast((
            _split_hashes(seq_hashes),
            np.ascontiguousarray(packed_full),
        ))

    def announce_stop(self) -> None:
        self._ctrl(KIND_STOP)


def _fill_sampling_desc(ctrl: np.ndarray, off: int, s: dict) -> None:
    """Write a sampling dict's structure descriptor (flags + sparse
    table widths) into ctrl[off:off+4]."""
    ctrl[off] = (
        (FLAG_PENALTIES if "rep_pen" in s else 0)
        | (FLAG_TOPLP if "top_lp_n" in s else 0)
        | (FLAG_BIAS if "bias_ids" in s else 0)
    )
    ctrl[off + 1] = s["bias_ids"].shape[1] if "bias_ids" in s else 0
    if "rep_pen" in s:
        ctrl[off + 2] = s["gen_ids"].shape[1]
        ctrl[off + 3] = s["prompt_ids"].shape[1]


def _sampling_keys(flags: int) -> tuple:
    # optional key groups select jit VARIANTS; omitting one on followers
    # would trace a DIFFERENT program than the leader's (divergent
    # collectives across hosts)
    return (
        SAMPLING_BASE_KEYS
        + (SAMPLING_BIAS_KEYS if flags & FLAG_BIAS else ())
        + (SAMPLING_PEN_KEYS if flags & FLAG_PENALTIES else ())
        + ((("top_lp_n", np.int32),) if flags & FLAG_TOPLP else ())
    )


def _sampling_flags(s: dict) -> int:
    return (
        (FLAG_PENALTIES if "rep_pen" in s else 0)
        | (FLAG_TOPLP if "top_lp_n" in s else 0)
        | (FLAG_BIAS if "bias_ids" in s else 0)
    )


def _sampling_tuple(sampling) -> tuple:
    s = sampling.arrays
    return tuple(
        np.asarray(s[k], dt) for k, dt in _sampling_keys(_sampling_flags(s))  # dynalint: disable=transitive-host-sync-in-step-loop — SamplingBatch.arrays is a host-numpy pytree by contract (engine/sampling.py); wire-dtype coercion only
    )


def _zeros_sampling(b: int, flags: int, nb: int, ng: int, nr: int) -> tuple:
    widths = {"bias_ids": nb, "bias_vals": nb, "gen_ids": ng,
              "gen_counts": ng, "prompt_ids": nr, "prompt_counts": nr}
    return tuple(
        np.zeros((b, widths[k]) if k in widths else (b,), dt)
        for k, dt in _sampling_keys(flags)
    )


def _sampling_dict(args: tuple, flags: int) -> dict:
    return {
        k: np.asarray(v)
        for (k, _), v in zip(_sampling_keys(flags), args)
    }


_STEP_TUPLE_KEYS = (
    "tokens", "positions", "slot_mapping", "block_tables",
    "context_lens", "last_token_idx",
)
_MULTI_STEP_TUPLE_KEYS = (
    "tokens", "positions", "block_tables", "context_lens", "valid_steps",
)


def _step_tuple(arrays: dict, sampling) -> tuple:
    return tuple(
        np.asarray(arrays[k], np.int32) for k in _STEP_TUPLE_KEYS  # dynalint: disable=transitive-host-sync-in-step-loop — the planner builds these rectangles on host (scheduler plan()); staging to device happens AFTER the announce, so no device handle reaches this tuple
    ) + _sampling_tuple(sampling)


def _multi_step_tuple(arrays: dict, sampling) -> tuple:
    return tuple(
        np.asarray(arrays[k], np.int32) for k in _MULTI_STEP_TUPLE_KEYS  # dynalint: disable=transitive-host-sync-in-step-loop — host-built window plan arrays (see _step_tuple); int32 wire coercion only
    ) + _sampling_tuple(sampling)


def _zeros_step(b: int, t: int, w: int, flags: int, nb: int, ng: int,
                nr: int) -> tuple:
    return (
        np.zeros((b, t), np.int32),
        np.zeros((b, t), np.int32),
        np.zeros((b * t,), np.int32),
        np.zeros((b, w), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.int32),
    ) + _zeros_sampling(b, flags, nb, ng, nr)


def _zeros_multi_step(b: int, w: int, flags: int, nb: int, ng: int,
                      nr: int) -> tuple:
    return (
        np.zeros((b, 1), np.int32),
        np.zeros((b, 1), np.int32),
        np.zeros((b, w), np.int32),
        np.zeros((b,), np.int32),
        np.zeros((b,), np.int32),
    ) + _zeros_sampling(b, flags, nb, ng, nr)


# ---------------------------------------------------------------------------
# Sharded KV offload (docs/multihost.md "Sharded KV offload"): every
# process runs the SAME jitted gather/scatter over the tp-sharded cache,
# then stores/loads only its ADDRESSABLE slice of the packed blocks in a
# process-local host pool. No cross-host traffic: G2 capacity scales
# with the host count, and blocks reassemble implicitly because every
# process scatters its own shard back.
# ---------------------------------------------------------------------------


def _split_hashes(seq_hashes: list[int]) -> np.ndarray:
    """uint64 hashes -> uint32 [2, n] (hi, lo) — survives JAX's
    x64-disabled canonicalization on the broadcast path."""
    arr = np.asarray(seq_hashes, np.uint64)
    return np.stack([
        (arr >> np.uint64(32)).astype(np.uint32),
        (arr & np.uint64(0xFFFFFFFF)).astype(np.uint32),
    ])


def _join_hashes(halves: np.ndarray) -> list[int]:
    halves = np.asarray(halves)
    return [
        (int(hi) << 32) | int(lo) for hi, lo in zip(halves[0], halves[1])
    ]


def _packed_spec():
    from jax.sharding import PartitionSpec as P

    # packed blocks [n, 2, L, bs, H, D]: the KV-head axis carries the
    # cache's tp sharding, everything else replicated
    return P(None, None, None, None, "tp", None)


def mirror_gather(k_cache, v_cache, block_ids: np.ndarray, block_size: int,
                  mesh) -> np.ndarray:
    """All processes: jitted gather constrained to the packed spec, then
    extract this process's H-slice (dp replicas deduped)."""
    import jax
    from jax.sharding import NamedSharding

    from dynamo_tpu.ops.block_copy import (
        _gather,
        _gather_quant,
        pad_ids_to_bucket,
    )

    n = len(block_ids)
    ids = jnp_i32(pad_ids_to_bucket(block_ids))
    with mesh:
        if isinstance(k_cache, tuple):  # int8: dequant to the bf16 wire
            packed = _gather_quant(
                k_cache[0], k_cache[1], v_cache[0], v_cache[1], ids,
                block_size,
            )
        else:
            packed = _gather(k_cache, v_cache, ids, block_size)
        packed = jax.device_put(
            packed, NamedSharding(mesh, _packed_spec())
        )
        jax.block_until_ready(packed)  # dynalint: disable=transitive-host-sync-in-step-loop — mirrored-collective completion barrier: every process must finish the gather before reading shard rows; this IS the offload plane's audited sync point
    return local_packed_rows(packed)[:n]


def mirror_scatter(k_cache, v_cache, block_ids: np.ndarray,
                   local_rows: np.ndarray, block_size: int, mesh):
    """All processes: assemble the global packed array from per-process
    shard rows, then the jitted scatter. Returns new (k, v)."""
    import jax
    from jax.sharding import NamedSharding

    from dynamo_tpu.ops.block_copy import (
        _scatter,
        _scatter_quant,
        pad_ids_to_bucket,
        pad_rows_to,
    )

    ids = pad_ids_to_bucket(block_ids)
    local_rows = pad_rows_to(len(ids), local_rows)
    quant = isinstance(k_cache, tuple)
    kv = k_cache[0] if quant else k_cache
    global_shape = (
        len(ids), 2, kv.shape[0], block_size, kv.shape[2], kv.shape[3],
    )
    sharding = NamedSharding(mesh, _packed_spec())
    data = jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape
    )
    with mesh:
        if quant:  # requantize the bf16 wire rows into values + scales
            kvv, ks, vv, vs = _scatter_quant(
                k_cache[0], k_cache[1], v_cache[0], v_cache[1],
                jnp_i32(ids), data, block_size,
            )
            return (kvv, ks), (vv, vs)
        return _scatter(k_cache, v_cache, jnp_i32(ids), data, block_size)


import functools


@functools.lru_cache(maxsize=8)
def _gather_full_fn(mesh, block_size: int, quant: bool = False):
    """Cached jitted replicated gather — a per-call jit closure would
    retrace + recompile on EVERY export, on every host, stalling the
    lockstep step loop for seconds each time. ``quant``: int8
    (values, scales) caches dequantize to the bf16 wire in-graph."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.ops.block_copy import _gather, _gather_quant

    def gather_rep(k, v, ids):
        if quant:
            packed = _gather_quant(k[0], k[1], v[0], v[1], ids, block_size)
        else:
            packed = _gather(k, v, ids, block_size)
        return jax.lax.with_sharding_constraint(
            packed, NamedSharding(mesh, P())
        )

    return jax.jit(gather_rep)


def mirror_gather_full(k_cache, v_cache, block_ids: np.ndarray,
                       block_size: int, mesh) -> np.ndarray:
    """All processes: jitted gather with a fully-REPLICATED output
    sharding (XLA all-gathers the KV-head shards over the mesh), so
    every process — in particular the leader running the disagg
    transfer plane — holds WHOLE packed blocks. The ICI/DCN all-gather
    is the cost of assembling a cross-process-sharded cache; the blocks
    are about to travel over DCN anyway."""
    import jax

    from dynamo_tpu.ops.block_copy import pad_ids_to_bucket

    n = len(block_ids)
    with mesh:
        packed = _gather_full_fn(
            mesh, block_size, quant=isinstance(k_cache, tuple)
        )(k_cache, v_cache, jnp_i32(pad_ids_to_bucket(block_ids)))
        jax.block_until_ready(packed)
    return np.asarray(packed.addressable_data(0))[:n]


def local_head_rows(packed_full: np.ndarray, cache) -> np.ndarray:
    """This process's KV-head slice of full packed blocks
    [n, 2, L, bs, H, D] — the import-side inverse of
    ``local_packed_rows``: unique H-extents of the process's
    addressable cache shards, concatenated in H order, so shard pools
    filled from imports line up with pools filled by mirror_gather."""
    if isinstance(cache, tuple):  # int8 cache: shard geometry from values
        cache = cache[0]
    starts = sorted({s.index[2].start or 0 for s in cache.addressable_shards})
    h_loc = cache.addressable_shards[0].data.shape[2]
    return np.concatenate(
        [packed_full[..., h0 : h0 + h_loc, :] for h0 in starts], axis=4
    )


def jnp_i32(arr: np.ndarray):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(arr, np.int32))  # dynalint: disable=transitive-host-sync-in-step-loop — arr is a host id list/array being UPLOADED (h2d), not a device value syncing down


def local_packed_rows(arr) -> np.ndarray:
    """This process's slice of packed blocks [n, 2, L, bs, H, D]: unique
    H-extents of its addressable shards, concatenated in H order (dp
    replicas collapse to one copy)."""
    seen: dict[int, np.ndarray] = {}
    for shard in arr.addressable_shards:
        h0 = shard.index[4].start or 0
        if h0 not in seen:
            seen[h0] = np.asarray(shard.data)  # dynalint: disable=transitive-host-sync-in-step-loop — the offload plane's designated device->host read: gathered KV rows land on host here, once per shard, behind mirror_gather's barrier
    return np.concatenate([seen[h] for h in sorted(seen)], axis=4)


class ShardKvPool:
    """Process-local content-addressed pool of packed-block SHARDS.
    Mutations are driven exclusively by the broadcast gather/scatter
    sequence, so every process's pool holds the same hash set (contents
    differ: each holds its own shard) and LRU decisions stay in
    lockstep."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._data: "dict[int, np.ndarray]" = {}

    def insert_many(self, seq_hashes: list[int], rows: np.ndarray) -> None:
        for i, h in enumerate(seq_hashes):
            h = int(h)
            if h in self._data:
                self._data.pop(h)  # re-insert refreshes recency
            # copy: rows[i] is a view into the whole gather batch — a
            # stored view would pin the batch until EVERY row evicts,
            # overrunning the pool budget by the batch factor
            self._data[h] = np.ascontiguousarray(rows[i])
            if len(self._data) > self.num_blocks:
                self._data.pop(next(iter(self._data)))  # LRU-ish FIFO

    def contains(self, seq_hash: int) -> bool:
        return int(seq_hash) in self._data

    def rows(self, seq_hashes: list[int], row_shape, dtype) -> np.ndarray:
        out = np.zeros((len(seq_hashes), *row_shape), dtype)
        for i, h in enumerate(seq_hashes):
            row = self._data.get(int(h))
            if row is not None:
                out[i] = row
        return out

    @property
    def num_cached(self) -> int:
        return len(self._data)


class ShardedKvOffload:
    """Leader-side G2 offload manager for multi-host engines — the
    KvBlockManager surface the engine drives (on_block_committed / pump /
    onboard / pending_offloads / close), actuated through the mirrored
    gather/scatter broadcasts so every process moves its own shard.

    Tiers are G2-only here (host DRAM per process); disk/remote demotion
    and disagg export stay single-host features for now."""

    def __init__(self, engine, broadcaster: StepBroadcaster,
                 host_num_blocks: int, offload_batch: int = 16):
        self.engine = engine
        self.broadcaster = broadcaster
        self.pool = ShardKvPool(host_num_blocks)
        self.host = self.pool  # duck-typed contains/num_blocks for probes
        self.disk = None
        self.remote = None
        self._offload_batch = max(1, min(offload_batch, host_num_blocks))
        from collections import OrderedDict

        self._pending: "OrderedDict[int, int]" = OrderedDict()

    # engine surface ------------------------------------------------------
    def on_disable(self) -> None:
        """Called by engine._disable_kvbm BEFORE close while followers
        still listen: a leader-side failure mid-mirrored-op must not
        leave followers with diverged pools silently serving shards the
        leader never stored — both sides drop the tier together."""
        try:
            self.broadcaster._ctrl(KIND_KV_DISABLE)
        except Exception:
            pass

    def on_block_committed(self, seq_hash: int, device_block: int) -> None:
        if not self.pool.contains(seq_hash):
            self._pending[seq_hash] = device_block

    @property
    def pending_offloads(self) -> int:
        return len(self._pending)

    def pump(self, max_blocks: Optional[int] = None) -> int:
        e = self.engine
        if max_blocks == 0:
            return 0
        cap = self._offload_batch if max_blocks is None else min(
            max_blocks, self._offload_batch
        )
        batch: list[tuple[int, int]] = []
        while self._pending and len(batch) < cap:
            h, bid = self._pending.popitem(last=False)
            if e.allocator.lookup_block(h) == bid and not self.pool.contains(h):
                batch.append((h, bid))
        if not batch:
            return 0
        hashes = [h for h, _ in batch]
        ids = [b for _, b in batch]
        self.broadcaster.announce_kv(KIND_KV_GATHER, ids, hashes)
        try:
            rows = mirror_gather(
                e.k_cache, e.v_cache, np.asarray(ids, np.int32),  # dynalint: disable=transitive-host-sync-in-step-loop — ids is a host python list; list->numpy, nothing device-resident
                e.config.block_size, e.mesh,
            )
        except Exception as exc:  # followers are inside the collective
            raise FatalMultihostError(
                "leader failed inside a mirrored KV gather"
            ) from exc
        self.pool.insert_many(hashes, rows)
        return len(batch)

    def match_offloaded(self, seq_hashes: list[int]) -> int:
        n = 0
        for h in seq_hashes:
            if self.pool.contains(h):
                n += 1
            else:
                break
        return n

    def onboard(self, seq_hashes: list[int], device_blocks: list[int]) -> int:
        e = self.engine
        limit = min(len(seq_hashes), len(device_blocks))
        n = 0
        for i in range(limit):
            if self.pool.contains(seq_hashes[i]):
                n += 1
            else:
                break
        if n == 0:
            return 0
        hashes = list(seq_hashes[:n])
        ids = list(device_blocks[:n])
        sample = next(iter(self.pool._data.values()))
        rows = self.pool.rows(hashes, sample.shape, sample.dtype)
        self.broadcaster.announce_kv(KIND_KV_SCATTER, ids, hashes)
        try:
            e.k_cache, e.v_cache = mirror_scatter(
                e.k_cache, e.v_cache, np.asarray(ids, np.int32), rows,
                e.config.block_size, e.mesh,
            )
        except Exception as exc:  # followers are inside the collective
            raise FatalMultihostError(
                "leader failed inside a mirrored KV scatter"
            ) from exc
        return n

    def close(self) -> None:
        self._pending.clear()


class StepFollower:
    """Follower side: mirror the leader's device dispatches until STOP.

    ``step_fn``/``multi_step_fn`` are the engine's jitted functions;
    ``get_state``/``set_state`` read and write the (params, k_cache,
    v_cache) triple so donated caches stay threaded between steps.
    """

    def __init__(self, engine) -> None:
        from jax.experimental import multihost_utils

        self._bcast = multihost_utils.broadcast_one_to_all
        self.engine = engine

    def run(self) -> None:
        e = self.engine
        pool: Optional[ShardKvPool] = None
        if e.config.host_kv_blocks > 0:
            pool = ShardKvPool(e.config.host_kv_blocks)
        # device-resident outputs of the previous window, retained for
        # pipelined chaining (KIND_CHAIN)
        prev_last = None
        prev_pnext = None
        chained = None
        while True:
            ctrl = np.asarray(self._bcast(np.zeros((CTRL_LEN,), np.int32)))
            kind, b, t, w, flags, nb, ng, nr = (int(x) for x in ctrl[:8])
            if kind == KIND_STOP:
                return
            if kind == KIND_KV_DISABLE:
                # leader failed mid-offload and degraded to G1-only:
                # drop the shard pool in lockstep (no more KV kinds come)
                pool = None
                continue
            if kind == KIND_KV_EXPORT:
                (ids,) = self._bcast((np.zeros((b,), np.int32),))
                mirror_gather_full(
                    e.k_cache, e.v_cache, np.asarray(ids),
                    e.config.block_size, e.mesh,
                )  # leader keeps the result; followers just participate
                continue
            if kind == KIND_KV_IMPORT:
                from dynamo_tpu.kvbm import BlockLayout

                layout = BlockLayout.for_model(
                    e.model_config, e.config.block_size,
                    e.config.wire_kv_dtype(),
                )
                halves, packed = self._bcast((
                    np.zeros((2, b), np.uint32),
                    np.zeros((b, *layout.packed_shape), layout.np_dtype),
                ))
                hashes = _join_hashes(np.asarray(halves))
                assert pool is not None, "leader imports but follower has no pool"
                pool.insert_many(
                    hashes, local_head_rows(np.asarray(packed), e.k_cache)
                )
                continue
            if kind in (KIND_KV_GATHER, KIND_KV_SCATTER):
                ids, halves = self._bcast((
                    np.zeros((b,), np.int32), np.zeros((2, b), np.uint32),
                ))
                ids = np.asarray(ids)
                hashes = _join_hashes(halves)
                assert pool is not None, "leader offloads but follower has no pool"
                if kind == KIND_KV_GATHER:
                    rows = mirror_gather(
                        e.k_cache, e.v_cache, ids,
                        e.config.block_size, e.mesh,
                    )
                    pool.insert_many(hashes, rows)
                else:
                    sample = next(iter(pool._data.values()))
                    rows = pool.rows(hashes, sample.shape, sample.dtype)
                    e.k_cache, e.v_cache = mirror_scatter(
                        e.k_cache, e.v_cache, ids, rows,
                        e.config.block_size, e.mesh,
                    )
                continue
            if kind in (KIND_STEP, KIND_STEP_MM):
                zeros = _zeros_step(b, t, w, flags, nb, ng, nr)
                if kind == KIND_STEP_MM:
                    D = e.model_config.hidden_size
                    zeros = zeros + (
                        np.zeros((b, t, D), np.float32),
                        np.zeros((b, t), np.uint8),
                    )
                args = self._bcast(zeros)
                mm_args = ()
                if kind == KIND_STEP_MM:
                    embeds, mask = args[-2], args[-1]
                    args = args[:-2]
                    mm_args = (
                        np.asarray(embeds),
                        np.asarray(mask).astype(bool),
                    )
                tokens, positions, slots, tables, ctx, last = args[:6]
                s = _sampling_dict(args[6:], flags)
                out = e._step_fn(
                    e.params, e.k_cache, e.v_cache, tokens, positions,
                    slots, tables, ctx, last, s, *mm_args,
                )
                e.k_cache, e.v_cache = out[-2], out[-1]
            elif kind == KIND_CHAIN:
                (src,) = self._bcast((np.zeros((b,), np.int32),))
                prev_mixed = bool(t)
                assert prev_last is not None, "chain without a prior window"
                if prev_mixed:
                    assert prev_pnext is not None
                    chained = e._chain_fn(
                        prev_last, prev_pnext, np.asarray(src)
                    )
                else:
                    chained = e._chain_pure_fn(prev_last, np.asarray(src))
            elif kind == KIND_MULTI_STEP:
                args = self._bcast(
                    _zeros_multi_step(b, w, flags, nb, ng, nr)
                )
                tokens, positions, tables, ctx, valid = args[:5]
                if chained is not None:
                    tokens, chained = chained, None
                s = _sampling_dict(args[5:], flags)
                _, prev_last, e.k_cache, e.v_cache = e._multi_step_fn(
                    e.params, e.k_cache, e.v_cache, tokens, positions,
                    tables, ctx, valid, s,
                )
                prev_pnext = None
            elif kind == KIND_MIXED:
                p, t_rect, p_flags, p_nb, p_ng, p_nr = (
                    int(x) for x in ctrl[8:14]
                )
                p_zeros = _zeros_step(p, t_rect, w, p_flags, p_nb, p_ng, p_nr)
                d_zeros = _zeros_multi_step(b, w, flags, nb, ng, nr)
                args = self._bcast(p_zeros + d_zeros)
                np_ = len(p_zeros)
                p_args, d_args = args[:np_], args[np_:]
                p_s = _sampling_dict(p_args[6:], p_flags)
                d_s = _sampling_dict(d_args[5:], flags)
                d_list = list(d_args[:5])
                if chained is not None:
                    d_list[0], chained = chained, None
                _, prev_last, prev_pnext, e.k_cache, e.v_cache = (
                    e._mixed_step_fn(
                        e.params, e.k_cache, e.v_cache,
                        *p_args[:6], p_s, *d_list, d_s,
                    )
                )
            else:
                raise RuntimeError(f"unknown multihost step kind {kind}")


def host_value(arr) -> np.ndarray:
    """Device array -> host numpy, robust to multi-host replication:
    jax.device_get refuses non-fully-addressable arrays, but every
    process holds a complete copy of replicated outputs in its local
    shard.  ``device_get`` rather than ``np.asarray``: this is the
    engine's designated harvest point, and the explicit spelling keeps
    it sanctioned under the armed transfer fence
    (utils/transfer_fence.py) — an implicit ``__array__`` sync here
    would be indistinguishable from the strays the fence hunts."""
    try:
        return np.asarray(jax.device_get(arr))
    except Exception:
        return np.asarray(jax.device_get(arr.addressable_data(0)))
