"""Pipeline parallelism: GPipe-style SPMD stage rotation over a "pp" axis.

The reference only passes a pipeline-parallel knob down to its engines
(reference: SURVEY.md §2.6 — "config knob passed to engines only"); here
PP is native. TPU-idiomatic formulation:

- layer-stacked params (leading L axis, models/llama.py) are sharded over
  the "pp" mesh axis: each stage holds L/pp contiguous layers — no
  parameter broadcast, stage weights live on the stage's devices only.
- the batch is split into M microbatches; a `shard_map` over "pp" runs the
  classic GPipe rotation as a `lax.scan` over M+pp-1 ticks: every tick,
  each stage runs its local layers on its current microbatch and
  `ppermute`s the activation to the next stage. Bubble fraction is
  (pp-1)/(M+pp-1), amortised by choosing M >= pp.
- "pp" is a *manual* shard_map axis; "tp"/"dp" remain auto axes, so
  tensor-parallel matmul shardings propagate inside each stage untouched
  (partial-auto shard_map) and XLA still inserts the tp psums over ICI.
- the per-stage paged KV cache slice ([L/pp, slots, Hkv, Dh]) is updated
  in place by each tick; invalid (bubble) ticks write to the pad slot 0,
  which the allocator reserves as scratch.

This mirrors how the transformer scan treats layers as data: the pipeline
is just the same scan distributed over devices with a rotating carry.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.utils.jaxtools import pcast, shard_map
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    Params,
    embed_lookup,
    layer_param_names,
    mm,
    make_layer_fn,
    param_specs,
    rmsnorm,
    scale_embed,
)


def pp_param_specs(cfg: ModelConfig) -> dict[str, P]:
    """PartitionSpecs with layer-stacked params sharded over "pp" (axis 0).

    tp/ep placements from the base specs are preserved; non-layer params
    (embed/final_norm/lm_head) stay replicated across pp.
    """
    base = param_specs(cfg)
    out: dict[str, P] = {}
    for name, spec in base.items():
        if name in ("embed", "final_norm", "lm_head"):
            out[name] = spec
        else:
            out[name] = P("pp", *spec[1:])
    return out


PP_CACHE_SPEC = P("pp", None, "tp", None)

# shard_map specs may only mention the manual axis ("pp"); tp/ep shardings
# on the same arrays ride along as auto (GSPMD-managed) axes.
_PP_ONLY_CACHE_SPEC = P("pp", None, None, None)


def _pp_only(spec: P) -> P:
    return P(*(ax if ax == "pp" else None for ax in spec))


def forward_pp(
    cfg: ModelConfig,
    params: Params,
    k_cache: jax.Array,  # [L, n_slots, Hkv, Dh], L sharded over pp
    v_cache: jax.Array,
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T]
    slot_mapping: jax.Array,  # [B*T]
    block_tables: jax.Array,  # [B, max_blocks]
    context_lens: jax.Array,  # [B]
    last_token_idx: jax.Array,  # [B]
    block_size: int,
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Pipeline-parallel model step. Same contract as models.llama.forward.

    B must be divisible by num_microbatches (default: pp size).
    """
    pp = mesh.shape["pp"]
    B, T = tokens.shape
    if num_microbatches is None:
        # largest divisor of B that is <= pp: amortises the bubble without
        # ever rejecting a batch the plain forward would accept
        M = next(m for m in range(min(pp, B), 0, -1) if B % m == 0)
    else:
        M = num_microbatches
    if M < 1 or B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    Bm = B // M

    x = scale_embed(cfg, embed_lookup(params, tokens))  # [B, T, D]
    D = x.shape[-1]

    # microbatch views
    x_mb = x.reshape(M, Bm, T, D)
    pos_mb = positions.reshape(M, Bm, T)
    slots_mb = slot_mapping.reshape(M, Bm * T)
    tables_mb = block_tables.reshape(M, Bm, -1)
    ctx_mb = context_lens.reshape(M, Bm)
    last_mb = last_token_idx.reshape(M, Bm)

    lp = {k: params[k] for k in layer_param_names(params)}
    base_pp = pp_param_specs(cfg)

    def _lp_spec(k: str) -> P:
        if k.endswith("_scale"):
            # int8 scales: the weight's pp spec with the contraction
            # axis (-2) dropped (models/quant.py scale_spec)
            from dynamo_tpu.models.quant import scale_spec

            return _pp_only(scale_spec(base_pp[k[: -len("_scale")]], -2))
        return _pp_only(base_pp[k])

    lp_specs = {k: _lp_spec(k) for k in lp}

    def stage(lp_local, kc, vc, x_mb, pos_mb, slots_mb, tables_mb, ctx_mb,
              last_mb):
        r = jax.lax.axis_index("pp")
        n_ticks = M + pp - 1
        perm = [(j, (j + 1) % pp) for j in range(pp)]

        def tick(carry, t):
            x_prev, kc, vc, outs = carry
            mb = t - r  # microbatch index this stage works on this tick
            valid = (mb >= 0) & (mb < M)
            i = jnp.clip(mb, 0, M - 1)
            pos = pos_mb[i]
            # bubble ticks write garbage K/V to pad slot 0 (reserved)
            slots = jnp.where(valid, slots_mb[i], 0)
            tables = tables_mb[i]
            ctx = ctx_mb[i]
            # x_mb[i] is varying (indexed by the rank-derived i); stage 0
            # ingests a fresh microbatch, others take the permuted carry
            x_in = jnp.where(r == 0, x_mb[i], x_prev)
            layer_fn = make_layer_fn(cfg, pos, slots, tables, ctx, block_size)
            y, (kc, vc) = jax.lax.scan(layer_fn, x_in, (lp_local, kc, vc))
            # only each sequence's last-token hidden feeds the logits:
            # accumulate [Bm, D] per microbatch, not the full [Bm, T, D]
            y_last = jnp.take_along_axis(
                y, last_mb[i][:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
            # select (not multiply-mask: bubble-tick garbage may be inf/nan)
            # and accumulate in f32 — bf16 psum under partial-auto shard_map
            # trips an XLA crash ("invalid binary opcode copy")
            is_out = valid & (r == pp - 1)
            outs = outs.at[i].set(
                jnp.where(is_out, y_last.astype(jnp.float32), outs[i])
            )
            x_next = jax.lax.ppermute(y, "pp", perm)
            return (x_next, kc, vc, outs), None

        varying = lambda a: pcast(a, ("pp",), to="varying")
        init = (
            varying(jnp.zeros_like(x_mb[0])),
            kc,
            vc,
            varying(jnp.zeros((M, Bm, D), jnp.float32)),
        )
        (x_last, kc, vc, outs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        # outs is zero except on the last stage; psum replicates across pp
        outs = jax.lax.psum(outs, "pp").astype(x_mb.dtype)
        return outs, kc, vc

    outs, new_k, new_v = shard_map(
        stage,
        mesh=mesh,
        in_specs=(
            lp_specs,
            _PP_ONLY_CACHE_SPEC,
            _PP_ONLY_CACHE_SPEC,
            P(),
            P(),
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs=(P(), _PP_ONLY_CACHE_SPEC, _PP_ONLY_CACHE_SPEC),
        axis_names={"pp"},
    )(lp, k_cache, v_cache, x_mb, pos_mb, slots_mb, tables_mb, ctx_mb,
      last_mb)

    x_last = outs.reshape(B, D)
    x_last = rmsnorm(x_last, params["final_norm"], cfg.rms_norm_eps, cfg.norm_bias_one)
    logits = mm(params, "lm_head", x_last).astype(jnp.float32)
    return logits, new_k, new_v
