"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context scaling the reference does NOT have (SURVEY.md §2.6: no
ring/context/sequence parallelism anywhere in the reference — it scales
context only by KV offload + prefill routing). Here it is first-class:
prefill of a sequence too long for one chip's HBM is sharded over the
"sp" mesh axis, with K/V shards rotating around the ring via
``lax.ppermute`` while every device accumulates flash-attention partial
sums (blockwise softmax with running max/denominator, so the result is
exact, not approximate).

Communication rides ICI neighbor links (a ring maps perfectly onto a TPU
torus axis) and overlaps with each step's local attention compute, which
is the standard TPU recipe (jax-ml.github.io/scaling-book). SPMD via
``shard_map``: everything inside is per-shard code with explicit
collectives, so XLA cannot re-layout the ring.

GQA is supported by folding query heads into groups of the KV heads.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dynamo_tpu.utils.jaxtools import shard_map


def _merge(m, l, acc, m_new, l_new, acc_new):
    """Merge two flash-attention partial states (log-sum-exp algebra)."""
    m_out = jnp.maximum(m, m_new)
    a = jnp.exp(m - m_out)
    b = jnp.exp(m_new - m_out)
    return m_out, l * a + l_new * b, acc * a[..., None] + acc_new * b[..., None]


def _block_attend(q, k, v, q_pos, k_pos, scale):
    """Masked local attention block.

    q: [B, Tq, Hk, G, Dh], k/v: [B, Tk, Hk, Dh]. Returns the block's
    flash partials (m, l, acc) with shapes [B, Hk, G, Tq], [...], and
    [B, Hk, G, Tq, Dh].
    """
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = q_pos[:, None] >= k_pos[None, :]  # causal [Tq, Tk]
    s = jnp.where(mask[None, None, None], s, -1e30)
    m = jnp.max(s, axis=-1)  # [B, Hk, G, Tq]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v.dtype), v).astype(
        jnp.float32
    )
    return m, l, acc


def ring_attention(
    q: jax.Array,  # [B, T, H, Dh], T sharded over axis_name
    k: jax.Array,  # [B, T, Hk, Dh]
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact causal attention with sequence sharding. Returns [B, T, H, Dh]
    sharded like q."""
    B, T, H, Dh = q.shape
    Hk = k.shape[2]
    G = H // Hk
    scale = scale if scale is not None else Dh ** -0.5
    n_shards = mesh.shape[axis_name]

    def local(q_l, k_l, v_l):
        # q_l: [B, T_loc, H, Dh] — this device's sequence shard
        T_loc = q_l.shape[1]
        my = jax.lax.axis_index(axis_name)
        qg = q_l.reshape(B, T_loc, Hk, G, Dh)
        q_pos = my * T_loc + jnp.arange(T_loc, dtype=jnp.int32)

        m0 = jnp.full((B, Hk, G, T_loc), -1e30, jnp.float32)
        l0 = jnp.zeros((B, Hk, G, T_loc), jnp.float32)
        a0 = jnp.zeros((B, Hk, G, T_loc, Dh), jnp.float32)

        def attend(i, k_cur, v_cur, m, l, acc):
            src = (my - i) % n_shards  # whose K/V shard we hold this step
            k_pos = src * T_loc + jnp.arange(T_loc, dtype=jnp.int32)
            m_n, l_n, a_n = _block_attend(qg, k_cur, v_cur, q_pos, k_pos, scale)
            return _merge(m, l, acc, m_n, l_n, a_n)

        def step(i, carry):
            k_cur, v_cur, m, l, acc = carry
            m, l, acc = attend(i, k_cur, v_cur, m, l, acc)
            # rotate K/V around the ring (neighbor ICI hop)
            perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return k_nxt, v_nxt, m, l, acc

        # n_shards-1 rotations suffice: the last-held shard is attended
        # outside the loop, skipping a useless final ICI hop
        k_f, v_f, m, l, acc = jax.lax.fori_loop(
            0, n_shards - 1, step, (k_l, v_l, m0, l0, a0)
        )
        m, l, acc = attend(n_shards - 1, k_f, v_f, m, l, acc)
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, Hk, G, Tq, Dh]
        return out.transpose(0, 3, 1, 2, 4).reshape(B, T_loc, H, Dh).astype(
            q_l.dtype
        )

    spec = P(None, axis_name, None, None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,  # [B, T, H, Dh], T sharded over axis_name
    k: jax.Array,  # [B, T, Hk, Dh]
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): reshard
    sequence-sharded Q/K/V to head-sharded full-sequence via one
    ``all_to_all``, attend locally over the whole sequence, then reshard
    back. One collective round-trip instead of ``n_shards`` ring hops —
    wins when heads are plentiful and the axis spans fast ICI; requires
    num (kv) heads divisible by the axis size."""
    B, T, H, Dh = q.shape
    Hk = k.shape[2]
    n = mesh.shape[axis_name]
    if H % n or Hk % n:
        raise ValueError(
            f"ulysses needs H ({H}) and Hkv ({Hk}) divisible by |{axis_name}|={n}"
        )
    scale = scale if scale is not None else Dh ** -0.5

    def to_heads(x_l):  # [B, T_loc, Hx, Dh] -> [B, T, Hx/n, Dh]
        B_, T_loc, Hx, Dh_ = x_l.shape
        x_l = x_l.reshape(B_, T_loc, n, Hx // n, Dh_)
        x_l = jax.lax.all_to_all(
            x_l, axis_name, split_axis=2, concat_axis=1, tiled=False
        )  # [B, T_loc, 1, ...] concat over axis 1 -> [B, T, 1, Hx//n, Dh]
        return x_l.reshape(B_, T_loc * n, Hx // n, Dh_)

    spec_seq = P(None, axis_name, None, None)

    def local(q_l, k_l, v_l):
        T_loc = q_l.shape[1]
        qh, kh, vh = to_heads(q_l), to_heads(k_l), to_heads(v_l)
        out = reference_causal_attention(qh, kh, vh, scale)  # [B, T, H/n, Dh]
        # back: sequence-sharded, all heads. split seq; the received
        # device axis must land chunk-major BEFORE the local-head axis so
        # the reshape restores original head order
        out = out.reshape(B, n, T_loc, H // n, Dh)
        out = jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2)
        return out.reshape(B, T_loc, H, Dh)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_seq, spec_seq, spec_seq),
        out_specs=spec_seq,
        check_vma=False,
    )(q, k, v)


def reference_causal_attention(q, k, v, scale=None):
    """Single-device exact causal attention (test oracle)."""
    B, T, H, Dh = q.shape
    Hk = k.shape[2]
    G = H // Hk
    scale = scale if scale is not None else Dh ** -0.5
    qg = q.reshape(B, T, Hk, G, Dh)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale
    pos = jnp.arange(T, dtype=jnp.int32)
    mask = pos[:, None] >= pos[None, :]
    s = jnp.where(mask[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v.dtype), v)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, Dh).astype(q.dtype)
