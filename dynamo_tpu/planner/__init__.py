"""Dynamic autoscaling planner.

Reference: examples/llm/components/planner.py:51-365 (scaling loop) +
components/planner/src/dynamo/planner/{local_connector.py,
kubernetes_connector.py}.
"""

from dynamo_tpu.planner.planner import DegradationHooks, Planner, PlannerConfig
from dynamo_tpu.planner.connector import LocalConnector

__all__ = ["Planner", "PlannerConfig", "DegradationHooks", "LocalConnector"]
