"""Planner → supervisor/operator connectors.

Reference: components/planner/src/dynamo/planner/local_connector.py:34-304
(circus RPC + statefile) and kubernetes_connector.py:20-69 (patch the
graph CR). The local connector speaks the supervisor's store control
subject; add/remove round-trips are acknowledged over an ephemeral
reply subject.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from typing import Any, Optional

from dynamo_tpu.sdk.serving import CONTROL_SUBJECT, state_key
from dynamo_tpu.store.base import Store

log = logging.getLogger("dynamo_tpu.planner.connector")


class LocalConnector:
    def __init__(self, store: Store, namespace: str, timeout_s: float = 30.0):
        self.store = store
        self.namespace = namespace
        self.timeout_s = timeout_s

    async def _command(self, op: str, component: str) -> dict[str, Any]:
        reply_to = f"{self.namespace}.planner.reply.{uuid.uuid4().hex[:8]}"
        sub = await self.store.subscribe(reply_to)
        try:
            payload = json.dumps(
                {"op": op, "component": component, "reply_to": reply_to}
            ).encode()
            await self.store.publish(
                f"{self.namespace}.{CONTROL_SUBJECT}", payload
            )

            async def first() -> dict[str, Any]:
                async for _subj, data in sub:
                    return json.loads(data.decode())
                return {"ok": False, "error": "reply stream closed"}

            return await asyncio.wait_for(first(), timeout=self.timeout_s)
        finally:
            await sub.close()

    async def add_component(self, component: str) -> bool:
        r = await self._command("add", component)
        if not r.get("ok"):
            log.warning("add %s failed: %s", component, r.get("error"))
        return bool(r.get("ok"))

    async def remove_component(self, component: str) -> bool:
        r = await self._command("remove", component)
        if not r.get("ok"):
            log.warning("remove %s failed: %s", component, r.get("error"))
        return bool(r.get("ok"))

    async def drain_component(self, component: str) -> bool:
        """Scale down via the drain protocol: the supervisor SIGTERMs
        the newest replica with the grace widened past the drain
        deadline, so the worker hands its in-flight streams to peers
        before exiting (docs/robustness.md "Graceful drain")."""
        r = await self._command("drain", component)
        if not r.get("ok"):
            log.warning("drain %s failed: %s", component, r.get("error"))
        return bool(r.get("ok"))

    async def replicas(self, component: str) -> Optional[int]:
        entry = await self.store.kv_get(state_key(self.namespace))
        if entry is None:
            return None
        state = json.loads(entry.value.decode())
        comp = state.get("components", {}).get(component)
        return comp["replicas"] if comp else None


class KubernetesConnector:
    """Scale by patching the graph deployment CR's replica counts
    (reference: kubernetes_connector.py:25-60, kube.py:115). Shells out
    to kubectl; inert when kubectl/cluster are absent."""

    def __init__(self, namespace: str, deployment: str, k8s_namespace: str = "default"):
        self.namespace = namespace
        self.deployment = deployment
        self.k8s_namespace = k8s_namespace

    async def _patch_replicas(self, component: str, delta: int) -> bool:
        current = await self.replicas(component)
        if current is None:
            return False
        patch = json.dumps(
            {"spec": {"services": {component: {"replicas": current + delta}}}}
        )
        proc = await asyncio.create_subprocess_exec(
            "kubectl", "-n", self.k8s_namespace, "patch",
            "dynamographdeployment", self.deployment,
            "--type", "merge", "-p", patch,
            stdout=asyncio.subprocess.DEVNULL, stderr=asyncio.subprocess.PIPE,
        )
        _, err = await proc.communicate()
        if proc.returncode != 0:
            log.warning("kubectl patch failed: %s", err.decode()[:500])
        return proc.returncode == 0

    async def add_component(self, component: str) -> bool:
        return await self._patch_replicas(component, +1)

    async def remove_component(self, component: str) -> bool:
        return await self._patch_replicas(component, -1)

    async def drain_component(self, component: str) -> bool:
        """Kubernetes already drains on scale-down: the pod gets
        SIGTERM + terminationGracePeriodSeconds, which is exactly the
        worker's drain path. Delegates to the replica patch."""
        return await self._patch_replicas(component, -1)

    async def replicas(self, component: str) -> Optional[int]:
        proc = await asyncio.create_subprocess_exec(
            "kubectl", "-n", self.k8s_namespace, "get",
            "dynamographdeployment", self.deployment, "-o", "json",
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.DEVNULL,
        )
        out, _ = await proc.communicate()
        if proc.returncode != 0:
            return None
        try:
            obj = json.loads(out.decode())
            return int(obj["spec"]["services"][component]["replicas"])
        except Exception:
            return None
