"""Graceful-degradation ladder: one policy, two planes.

The planner decides the rung (`planner.py` escalates when the fleet is
saturated at ``max_decode`` and still breaching); this module is how a
rung becomes behavior:

- :class:`LadderPolicy` — the shared math (how much each rung tightens
  admission, when spec decode turns off). The fleet simulator and live
  serving both apply it, so what the sim proves is what production does.
- :class:`ServingDegradation` — applies a rung inside a serving
  process: scales the :class:`~dynamo_tpu.http.admission.AdmissionController`
  caps down and suspends speculative decoding on the engine.
- :class:`StoreDegradation` — the planner side in a distributed fleet:
  publishes the rung to the store under :func:`degradation_key`, where
  every worker's :func:`watch_degradation` task picks it up (capped
  backoff + snapshot resync, same contract as the model watcher — the
  ladder must never silently freeze).

In the simulator none of the store plumbing exists: ``FleetSim``
implements ``DegradationHooks`` directly and applies the same
:class:`LadderPolicy` synchronously at virtual time.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from typing import Any, Optional

from dynamo_tpu.telemetry.instruments import PLANNER_DEGRADATION_LEVEL
from dynamo_tpu.utils import affinity, tasks
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.planner.degradation")

LEVEL_NAMES = (
    "normal", "tighten admission", "spec decode off", "shed aggressively"
)


def degradation_key(namespace: str) -> str:
    return f"{namespace}/planner/degradation"


@dataclass(frozen=True)
class LadderPolicy:
    """What each rung does, as numbers (docs/autoscaling.md):
    level 1+ scales the admission caps down, level 2+ disables spec
    decode, level 3 clamps the queue to a shallow shed line."""

    queue_factor: float = 0.5
    kv_factor: float = 0.95
    shed_queue_depth: int = 32
    # the "demote cold KV" rung (fleet KV fabric, kvbm/fabric.py): each
    # rung scales the G2 host-pool watermarks down by this factor, so
    # cold KV demotes to disk / the shared bucket earlier the deeper
    # the fleet degrades — host RAM is given back before admission or
    # spec decode have to give anything up
    fabric_scale_factor: float = 0.75

    def admission_caps(
        self, base_queue: int, base_kv: float, level: int
    ) -> tuple[int, float]:
        """A base cap of 0 means "check disabled" and stays 0 when
        tightened — except the rung-3 shed line, which imposes itself
        on the queue whenever load signals exist to enforce it."""
        if level <= 0:
            return base_queue, base_kv
        queue = (
            max(1, int(base_queue * self.queue_factor))
            if base_queue > 0 else 0
        )
        if level >= 3:
            queue = (
                min(queue, self.shed_queue_depth)
                if queue > 0 else self.shed_queue_depth
            )
        return queue, base_kv * self.kv_factor

    def spec_enabled(self, base: bool, level: int) -> bool:
        return base and level < 2

    def fabric_pressure_scale(self, level: int) -> float:
        """Watermark multiplier for the fleet fabric's G2 pressure
        lifecycle: 1.0 at rung 0, tightening geometrically per rung
        (floored — the host tier must keep SOME working set or every
        admission pays a fetch)."""
        if level <= 0:
            return 1.0
        return max(0.25, self.fabric_scale_factor ** min(level, 3))

    def force_shed(self, level: int) -> bool:
        """Rung 3 on a frontend WITHOUT load signals: shed to the probe
        trickle rather than failing open (where load signals exist, the
        clamped admission caps govern instead)."""
        return level >= 3


class ServingDegradation:
    """DegradationHooks applied to a live serving process. Both targets
    are optional so each process wires what it owns: a frontend passes
    its admission controller, a worker passes its engine (spec decode
    suspends via the ``spec_suspended`` flag the step loop reads)."""

    def __init__(
        self,
        admission: Optional[Any] = None,
        engine: Optional[Any] = None,
        policy: Optional[LadderPolicy] = None,
        fabric: Optional[Any] = None,
    ):
        self.admission = admission
        self.engine = engine
        self.policy = policy or LadderPolicy()
        # fleet KV fabric (kvbm/fabric.py FleetKvFabric): the "demote
        # cold KV" rung scales its G2 watermarks via set_pressure_scale
        self.fabric = fabric
        self.level = 0
        if admission is not None:
            self._base_queue = admission.config.max_queue_depth
            self._base_kv = admission.config.max_kv_usage

    def set_level(self, level: int) -> None:
        level = max(0, level)
        if level == self.level:
            return
        log.warning(
            "degradation level %d -> %d (%s)",
            self.level, level, LEVEL_NAMES[min(level, 3)],
        )
        self.level = level
        PLANNER_DEGRADATION_LEVEL.set(level)
        if self.admission is not None:
            queue, kv = self.policy.admission_caps(
                self._base_queue, self._base_kv, level
            )
            self.admission.config.max_queue_depth = queue
            self.admission.config.max_kv_usage = kv
            self.admission.force_shed = self.policy.force_shed(level)
        if self.engine is not None:
            # deliberate cross-domain flip: this runs on the event loop
            # (watch_degradation task), the engine thread reads the bool
            # each step. A plain store is race-free for a bool; declared
            # so both enforcement planes (DL103 + DYN_AFFINITY_CHECK)
            # know it is sanctioned.
            with affinity.handoff("degradation rung -> engine.spec_suspended"):
                self.engine.spec_suspended = not self.policy.spec_enabled(  # dynalint: handoff=degradation-rung — loop->engine bool flip, read each step
                    True, level
                )
        if self.fabric is not None:
            # same cross-domain shape as spec_suspended: a plain float
            # store the engine-thread pump reads at its next pressure
            # pass (the "demote cold KV" rung)
            with affinity.handoff("degradation rung -> fabric watermarks"):
                self.fabric.set_pressure_scale(  # dynalint: handoff=degradation-rung — loop->engine float flip, read each pump
                    self.policy.fabric_pressure_scale(level)
                )


class StoreDegradation:
    """DegradationHooks for the distributed planner: publish the rung
    (fire-and-forget — the planner's control loop must not block on a
    flapping store; the watcher side resyncs from snapshots anyway).
    Payloads carry a wall-clock ``seq`` stamp so a put delayed behind a
    store reconnect cannot overwrite a newer rung on the watcher side
    (and a restarted planner's stamps keep increasing)."""

    def __init__(self, store: Any, namespace: str):
        self.store = store
        self.key = degradation_key(namespace)

    def set_level(self, level: int) -> None:
        payload = json.dumps(
            {"level": int(level), "seq": time.time_ns()}
        ).encode()

        async def _put() -> None:
            try:
                await self.store.kv_put(self.key, payload)
            except Exception:
                log.warning(
                    "failed to publish degradation level %d", level,
                    exc_info=True,
                )

        tasks.spawn(_put(), name="degradation-publish")


async def watch_degradation(
    store: Any, namespace: str, hooks: ServingDegradation
) -> None:
    """Follow the planner's published rung forever (run under
    ``utils.tasks.spawn``). Watch death resubscribes on capped backoff
    with a snapshot resync; a deleted key means level 0; entries whose
    ``seq`` is older than the last applied one are stale out-of-order
    writes and are ignored."""
    key = degradation_key(namespace)
    backoff = Backoff(base_s=0.5, cap_s=30.0)
    watch = None
    last_seq = -1

    def apply(value: bytes) -> None:
        nonlocal last_seq
        try:
            obj = json.loads(value)
            level = int(obj.get("level", 0))
            seq = int(obj.get("seq", last_seq + 1))
        except (ValueError, TypeError, json.JSONDecodeError):
            log.warning("malformed degradation entry: %r", value[:80])
            return
        if seq < last_seq:
            log.warning(
                "ignoring stale degradation write (seq %d < %d)",
                seq, last_seq,
            )
            return
        last_seq = seq
        hooks.set_level(level)

    while True:
        try:
            if watch is None:
                watch = await store.watch_prefix(key)
                backoff.reset()
                snapshot = watch.snapshot()
                if snapshot:
                    apply(snapshot[-1].value)
                else:
                    last_seq = -1
                    hooks.set_level(0)
            async for ev in watch:
                if ev.type == "put":
                    apply(ev.entry.value)
                else:
                    last_seq = -1  # key deleted: planner reset/retired
                    hooks.set_level(0)
            # stream ended cleanly (store dropped it): resubscribe
        except asyncio.CancelledError:
            raise
        except Exception:
            log.warning("degradation watch died; resubscribing",
                        exc_info=True)
        watch = None
        await backoff.sleep()
