"""Planner metrics logging: JSONL always, TensorBoard when available.

The reference planner writes its load/scaling signals to TensorBoard
(reference: examples/llm/components/planner.py tensorboard writer,
docs/planner.md:73-78). Here the durable format is JSONL (greppable,
no reader dependency) with TensorBoard event files written alongside
when torch is importable — plug an instance into ``Planner.on_metrics``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Optional

log = logging.getLogger("dynamo_tpu.planner.metrics")


class MetricsLogger:
    def __init__(self, log_dir: str, tensorboard: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, "planner_metrics.jsonl")
        self._f = open(self.path, "a", buffering=1)
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=log_dir)
            except Exception:
                log.info("tensorboard unavailable; JSONL only")

    def __call__(self, snap: dict[str, Any]) -> None:
        self._f.write(json.dumps(snap) + "\n")
        if self._tb is not None:
            # step from wall time: restarts with the same log dir stay
            # monotone instead of superimposing a second run at step 0
            step = int(snap.get("ts") or time.time())
            walltime = float(snap.get("ts") or time.time())
            for key, value in snap.items():
                if key != "ts" and isinstance(value, (int, float)):
                    self._tb.add_scalar(
                        f"planner/{key}", value, step, walltime=walltime
                    )

    def close(self) -> None:
        self._f.close()
        if self._tb is not None:
            self._tb.close()
