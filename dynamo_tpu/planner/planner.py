"""The autoscaling loop.

Reference: examples/llm/components/planner.py:51-365 — every
metric-pulling interval collect decode KV-load + prefill queue depth;
every adjustment interval compare against high/low watermarks with
grace periods and add/remove workers through a connector. Thresholds
default to the reference's (decode KV 0.9/0.5; prefill queue per-worker
0.5/0.2 — planner.py:42-50).

Metrics arrive over the workers' ``load_metrics`` component subject (the
same feed the KV router's scheduler consumes), so the planner is just
another subscriber — no extra worker-side machinery.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Protocol

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.store.base import Store
from dynamo_tpu.telemetry.slo import aggregate_slo

log = logging.getLogger("dynamo_tpu.planner")


class Connector(Protocol):
    async def add_component(self, component: str) -> bool: ...
    async def remove_component(self, component: str) -> bool: ...


@dataclass
class PlannerConfig:
    decode_component: str = "backend"
    prefill_component: str = "prefill"
    metric_interval_s: float = 5.0
    adjustment_interval_s: float = 30.0
    # decode watermarks on mean KV-cache usage (reference planner.py:42-50)
    decode_kv_scale_up: float = 0.9
    decode_kv_scale_down: float = 0.5
    # prefill watermarks on queue depth per prefill worker
    prefill_queue_scale_up: float = 0.5
    prefill_queue_scale_down: float = 0.2
    min_decode: int = 1
    max_decode: int = 8
    min_prefill: int = 0
    max_prefill: int = 8
    # consecutive breaches required before acting (grace periods)
    grace_cycles: int = 2


@dataclass
class _Signal:
    up_streak: int = 0
    down_streak: int = 0

    def observe(self, up: bool, down: bool) -> None:
        self.up_streak = self.up_streak + 1 if up else 0
        self.down_streak = self.down_streak + 1 if down else 0


class Planner:
    def __init__(
        self,
        store: Optional[Store],
        component: Optional[Component],  # decode component (load_metrics)
        connector: Connector,
        config: Optional[PlannerConfig] = None,
        prefill_workers: int = 0,
        decode_workers: int = 1,
    ):
        """``store``/``component`` may be None for a DRIVEN planner:
        the caller feeds snapshots straight into make_adjustments()
        (the planner-simulation example and what-if analyses) instead
        of collect() polling live metrics."""
        self.store = store
        self.component = component
        self.connector = connector
        self.config = config or PlannerConfig()
        self.aggregator = KvMetricsAggregator()
        self.queue = (
            PrefillQueue(store, component.namespace.name)
            if store is not None and component is not None
            else None
        )
        self.decode_workers = decode_workers
        self.prefill_workers = prefill_workers
        self._decode_sig = _Signal()
        self._prefill_sig = _Signal()
        self._task: Optional[asyncio.Task] = None
        self.history: list[dict[str, Any]] = []  # observability ring
        self.on_metrics: Optional[Any] = None  # hook for tracing/tensorboard

    async def start(self) -> None:
        assert self.component is not None and self.queue is not None, (
            "a driven planner (store=None) has no live metrics to poll — "
            "feed make_adjustments() directly"
        )
        sub = await self.component.subscribe("load_metrics")
        self.aggregator.start_consuming(sub)
        self._task = asyncio.create_task(self._run())

    async def collect(self) -> dict[str, float]:
        assert self.queue is not None
        fresh = self.aggregator.fresh_metrics()
        usages = [m.gpu_cache_usage_perc for m in fresh.values()]
        kv_load = sum(usages) / len(usages) if usages else 0.0
        depth = await self.queue.depth()
        per_worker = depth / max(1, self.prefill_workers)
        # SLO/goodput signals riding the same load_metrics feed
        # (telemetry/slo.py aggregate_slo — one rollup shared with the
        # metrics service so the two can't diverge): attainment is the
        # health signal raw KV load can't see — a fleet can sit under
        # the KV watermark while every request misses its ITL target.
        # Logged to metrics_log (numeric keys flow to JSONL/TensorBoard
        # automatically) and available to watermark logic.
        attainment, goodput = aggregate_slo(fresh.values())
        snap = {
            "kv_load_mean": kv_load,
            "decode_workers_reporting": float(len(fresh)),
            "prefill_queue_depth": float(depth),
            "prefill_queue_per_worker": per_worker,
            "slo_attainment_mean": attainment,
            "goodput_tokens_total": goodput,
            "ts": time.time(),
        }
        self.history.append(snap)
        del self.history[:-600]
        if self.on_metrics is not None:
            try:
                self.on_metrics(snap)
            except Exception:
                pass
        return snap

    async def make_adjustments(self, snap: dict[str, float]) -> None:
        c = self.config
        self._decode_sig.observe(
            up=snap["kv_load_mean"] > c.decode_kv_scale_up,
            down=snap["kv_load_mean"] < c.decode_kv_scale_down,
        )
        self._prefill_sig.observe(
            up=snap["prefill_queue_per_worker"] > c.prefill_queue_scale_up,
            down=snap["prefill_queue_per_worker"] < c.prefill_queue_scale_down,
        )
        if (
            self._decode_sig.up_streak >= c.grace_cycles
            and self.decode_workers < c.max_decode
        ):
            if await self.connector.add_component(c.decode_component):
                self.decode_workers += 1
                self._decode_sig = _Signal()
                log.info("scaled decode up to %d", self.decode_workers)
        elif (
            self._decode_sig.down_streak >= c.grace_cycles
            and self.decode_workers > c.min_decode
        ):
            if await self.connector.remove_component(c.decode_component):
                self.decode_workers -= 1
                self._decode_sig = _Signal()
                log.info("scaled decode down to %d", self.decode_workers)
        if (
            self._prefill_sig.up_streak >= c.grace_cycles
            and self.prefill_workers < c.max_prefill
        ):
            if await self.connector.add_component(c.prefill_component):
                self.prefill_workers += 1
                self._prefill_sig = _Signal()
                log.info("scaled prefill up to %d", self.prefill_workers)
        elif (
            self._prefill_sig.down_streak >= c.grace_cycles
            and self.prefill_workers > c.min_prefill
        ):
            if await self.connector.remove_component(c.prefill_component):
                self.prefill_workers -= 1
                self._prefill_sig = _Signal()
                log.info("scaled prefill down to %d", self.prefill_workers)

    async def _run(self) -> None:
        c = self.config
        last_adjust = time.monotonic()
        while True:
            snap = await self.collect()
            now = time.monotonic()
            if now - last_adjust >= c.adjustment_interval_s:
                await self.make_adjustments(snap)
                last_adjust = now
            await asyncio.sleep(c.metric_interval_s)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        await self.aggregator.close()
