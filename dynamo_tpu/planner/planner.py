"""The autoscaling loop.

Reference: examples/llm/components/planner.py:51-365 — every
metric-pulling interval collect decode KV-load + prefill queue depth;
every adjustment interval compare against high/low watermarks with
grace periods and add/remove workers through a connector. Thresholds
default to the reference's (decode KV 0.9/0.5; prefill queue per-worker
0.5/0.2 — planner.py:42-50).

Beyond the reference's watermarks, this planner closes three more loops
(docs/autoscaling.md):

- **SLO-aware scaling** — with ``slo_target`` set, sustained
  ``slo_attainment_mean`` below target scales decode up even when KV
  load sits under the watermark (a fleet can be latency-sick while
  memory-healthy), and scale-down additionally requires SLO headroom
  (``slo_target + slo_headroom``) so the planner never trades a met SLO
  for a saved chip.
- **Graceful degradation** — when the fleet is already at
  ``max_decode`` and the scale-up condition persists, the planner walks
  a degradation ladder instead of thrashing: level 1 tightens
  admission, level 2 disables speculative decoding, level 3 sheds
  aggressively. Steps are applied through an injectable
  :class:`DegradationHooks` and unwound one level at a time once
  headroom returns.
- **Self-healing reconciliation** — ``collect()`` reports
  ``decode_workers_reporting`` (workers whose metrics actually arrive);
  when that stays below the planner's *intent* for
  ``reconcile_cycles`` adjustment rounds (a chaos ``kill``, an OOM'd
  pod), the planner replaces the missing workers without touching its
  intent, emitting ``dynamo_planner_replacements_total``.

Metrics arrive over the workers' ``load_metrics`` component subject (the
same feed the KV router's scheduler consumes), so the planner is just
another subscriber — no extra worker-side machinery. All time flows
through an injectable :class:`~dynamo_tpu.utils.clock.Clock`, which is
what lets the discrete-event fleet simulator (``dynamo_tpu/sim``) drive
this exact code against a million-request virtual day.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional, Protocol

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.store.base import Store
from dynamo_tpu.telemetry.instruments import (
    PLANNER_CONNECTOR_FAILURES,
    PLANNER_DEGRADATION_LEVEL,
    PLANNER_REPLACEMENTS,
    PLANNER_SCALE_EVENTS,
)
from dynamo_tpu.planner.degradation import LEVEL_NAMES
from dynamo_tpu.telemetry.slo import aggregate_slo
from dynamo_tpu.utils.clock import SYSTEM, Clock

log = logging.getLogger("dynamo_tpu.planner")


class Connector(Protocol):
    async def add_component(self, component: str) -> bool: ...
    async def remove_component(self, component: str) -> bool: ...
    # optional: graceful scale-down (docs/robustness.md "Graceful
    # drain"); connectors without it fall back to remove_component


def _drain_or_remove(connector: Any, component: str):
    """Scale-downs prefer the drain protocol — the departing worker
    hands its streams off instead of dropping them — and fall back to
    the hard remove for connectors that predate it."""
    drain = getattr(connector, "drain_component", None)
    if drain is not None:
        return drain(component)
    return connector.remove_component(component)


class DegradationHooks(Protocol):
    """What the serving plane exposes to the degradation ladder. The
    sim's fleet implements this directly; live serving wires it to the
    admission controller + engine spec toggle."""

    def set_level(self, level: int) -> None: ...


@dataclass
class PlannerConfig:
    decode_component: str = "backend"
    prefill_component: str = "prefill"
    metric_interval_s: float = 5.0
    adjustment_interval_s: float = 30.0
    # decode watermarks on mean KV-cache usage (reference planner.py:42-50)
    decode_kv_scale_up: float = 0.9
    decode_kv_scale_down: float = 0.5
    # prefill watermarks on queue depth per prefill worker
    prefill_queue_scale_up: float = 0.5
    prefill_queue_scale_down: float = 0.2
    min_decode: int = 1
    max_decode: int = 8
    min_prefill: int = 0
    max_prefill: int = 8
    # consecutive breaches required before acting (grace periods)
    grace_cycles: int = 2
    # SLO-driven scaling: 0.0 disables (pure watermark planner, the
    # pre-ISSUE-6 behavior). With a target, sustained attainment below
    # it scales decode up even under the KV watermark, and scale-down
    # requires attainment >= target + headroom.
    slo_target: float = 0.0
    slo_headroom: float = 0.03
    # adjustment cycles a worker may go missing (reporting < intent)
    # before reconciliation replaces it; 0 disables self-healing
    reconcile_cycles: int = 3
    # adjustment cycles an ordered worker (scale-up or replacement) may
    # take to start reporting before reconciliation presumes the spawn
    # dead and replaces it too — real provisioning (pod schedule + model
    # load + first publish) routinely outlasts reconcile_cycles, and
    # without this credit every slow spawn triggers a duplicate
    spawn_grace_cycles: int = 10
    # degradation ladder ceiling (0 disables the ladder entirely)
    degrade_max_level: int = 3
    # rate limit for connector-refusal warnings (satellite: don't spam
    # the log every adjustment cycle at max/min capacity)
    connector_warn_interval_s: float = 60.0


@dataclass
class _Signal:
    up_streak: int = 0
    down_streak: int = 0

    def observe(self, up: bool, down: bool) -> None:
        self.up_streak = self.up_streak + 1 if up else 0
        self.down_streak = self.down_streak + 1 if down else 0


class Planner:
    def __init__(
        self,
        store: Optional[Store],
        component: Optional[Component],  # decode component (load_metrics)
        connector: Connector,
        config: Optional[PlannerConfig] = None,
        prefill_workers: int = 0,
        decode_workers: int = 1,
        clock: Optional[Clock] = None,
        degradation: Optional[DegradationHooks] = None,
    ):
        """``store``/``component`` may be None for a DRIVEN planner:
        the caller feeds snapshots straight into make_adjustments()
        (the fleet simulator, the planner-simulation example, what-if
        analyses) instead of collect() polling live metrics. ``clock``
        defaults to the real system clock; the simulator passes its
        virtual clock so ``_run`` and snapshot timestamps never touch
        wall time."""
        self.store = store
        self.component = component
        self.connector = connector
        self.config = config or PlannerConfig()
        self.clock = clock or SYSTEM
        self.degradation = degradation
        self.aggregator = KvMetricsAggregator()
        self.queue = (
            PrefillQueue(store, component.namespace.name)
            if store is not None and component is not None
            else None
        )
        self.decode_workers = decode_workers
        self.prefill_workers = prefill_workers
        self._decode_sig = _Signal()
        self._prefill_sig = _Signal()
        self._missing_streak = 0
        self._surplus_streak = 0
        self._relax_streak = 0
        self._adjust_cycle = 0
        # decode workers ordered but not yet reporting, one expiry cycle
        # per order (FIFO): reconciliation subtracts these from
        # "missing" until the fleet catches up or each order's own
        # spawn_grace_cycles expire — a shared deadline would let every
        # new order refresh a dead spawn's credit forever
        self._provisioning: deque[int] = deque()
        self._last_connector_warn: dict[str, float] = {}
        self.degradation_level = 0
        self.replacements_total = 0
        self._task: Optional[asyncio.Task] = None
        self.history: list[dict[str, Any]] = []  # observability ring
        self.on_metrics: Optional[Any] = None  # hook for tracing/tensorboard

    async def start(self) -> None:
        assert self.component is not None and self.queue is not None, (
            "a driven planner (store=None) has no live metrics to poll — "
            "feed make_adjustments() directly"
        )
        sub = await self.component.subscribe("load_metrics")
        self.aggregator.start_consuming(sub)
        self._task = asyncio.create_task(self._run())

    async def collect(self) -> dict[str, float]:
        assert self.queue is not None
        fresh = self.aggregator.fresh_metrics()
        usages = [m.gpu_cache_usage_perc for m in fresh.values()]
        kv_load = sum(usages) / len(usages) if usages else 0.0
        depth = await self.queue.depth()
        per_worker = depth / max(1, self.prefill_workers)
        # SLO/goodput signals riding the same load_metrics feed
        # (telemetry/slo.py aggregate_slo — one rollup shared with the
        # metrics service so the two can't diverge): attainment is the
        # health signal raw KV load can't see — a fleet can sit under
        # the KV watermark while every request misses its ITL target.
        # Logged to metrics_log (numeric keys flow to JSONL/TensorBoard
        # automatically) and available to watermark logic.
        attainment, goodput = aggregate_slo(fresh.values())
        snap = {
            "kv_load_mean": kv_load,
            "decode_workers_reporting": float(len(fresh)),
            "prefill_queue_depth": float(depth),
            "prefill_queue_per_worker": per_worker,
            "slo_attainment_mean": attainment,
            "goodput_tokens_total": goodput,
            "degradation_level": float(self.degradation_level),
            "ts": self.clock.time(),
        }
        self.history.append(snap)
        del self.history[:-600]
        if self.on_metrics is not None:
            try:
                self.on_metrics(snap)
            except Exception:
                pass
        return snap

    # -- connector plumbing (streak reset + rate-limited refusal warning) --

    def _warn_connector(self, op: str, component: str, note: str) -> None:
        PLANNER_CONNECTOR_FAILURES.labels(op).inc()
        key = f"{op}:{component}"
        now = self.clock.monotonic()
        last = self._last_connector_warn.get(key)
        if (
            last is not None
            and now - last < self.config.connector_warn_interval_s
        ):
            return
        self._last_connector_warn[key] = now
        log.warning(
            "connector refused %s %s (%s); streak reset — will re-arm "
            "after %d fresh breach cycle(s)",
            op, component, note, self.config.grace_cycles,
        )

    async def _scale(self, op: str, component: str, signal: _Signal) -> bool:
        """One add/remove through the connector. On refusal the breach
        streak RESETS (instead of silently re-issuing the same failed
        command every adjustment cycle) and a rate-limited warning
        records why nothing is happening."""
        ok = (
            await self.connector.add_component(component)
            if op == "add"
            else await _drain_or_remove(self.connector, component)
        )
        if not ok:
            signal.up_streak = 0
            signal.down_streak = 0
            self._warn_connector(op, component, "command not acknowledged")
        return ok

    # -- reconciliation (self-healing) -------------------------------------

    def _note_provisioning(self, n: int = 1) -> None:
        """Credit ``n`` decode workers as ordered-but-provisioning so
        reconciliation doesn't mistake spawn latency for a loss."""
        expire = self._adjust_cycle + self.config.spawn_grace_cycles
        self._provisioning.extend([expire] * n)

    async def _reconcile(self, snap: dict[str, float]) -> None:
        """Converge the fleet onto the planner's intent in both
        directions: replace workers the fleet lost without the planner
        asking (chaos kill, OOM, preempted node) and drain surplus
        workers the fleet gained without it asking (a slow spawn landing
        after a scale-down already passed it). Intent stays put; the
        connector moves the reported count to match it. Workers the
        planner itself just ordered get ``spawn_grace_cycles`` to start
        reporting before they count as missing."""
        c = self.config
        reporting = snap.get("decode_workers_reporting")
        if c.reconcile_cycles <= 0 or reporting is None:
            return
        # each order expires on its own deadline (oldest first): a fresh
        # order must not extend a dead spawn's credit, and one dead
        # spawn expiring must not strip credit from healthy later orders
        expired = 0
        while self._provisioning and self._adjust_cycle >= self._provisioning[0]:
            self._provisioning.popleft()
            expired += 1
        if expired:
            log.warning(
                "%d ordered decode worker(s) never reported within "
                "%d cycles; presuming the spawn(s) dead",
                expired, c.spawn_grace_cycles,
            )
        missing = self.decode_workers - int(reporting)
        if missing < 0:
            # surplus: a spawn landed after a scale-down raced past it,
            # or capacity was added out of band. Intent stays
            # authoritative — without this path the extra worker runs
            # (and bills) forever, because the policy down-branch is
            # clamped by intent, not by the reported count. Drain one
            # worker per sustained reconcile window.
            self._missing_streak = 0
            self._provisioning.clear()  # everything ordered has landed
            self._surplus_streak += 1
            if self._surplus_streak < c.reconcile_cycles:
                return
            self._surplus_streak = 0
            if await _drain_or_remove(self.connector, c.decode_component):
                PLANNER_SCALE_EVENTS.labels(
                    c.decode_component, "drain"
                ).inc()
                log.warning(
                    "reconciliation: draining surplus %s worker "
                    "(reporting %d > intent %d)",
                    c.decode_component, int(reporting), self.decode_workers,
                )
            else:
                self._warn_connector(
                    "remove", c.decode_component, "surplus drain refused"
                )
            return
        self._surplus_streak = 0
        if missing == 0:
            self._missing_streak = 0
            self._provisioning.clear()  # fleet caught up with intent
            return
        # credits beyond the observed gap correspond to spawns that
        # already landed — retire the oldest (first ordered, first up)
        while len(self._provisioning) > missing:
            self._provisioning.popleft()
        if missing <= len(self._provisioning):
            return  # fully explained by in-flight spawns: wait them out
        self._missing_streak += 1
        if self._missing_streak < c.reconcile_cycles:
            return
        self._missing_streak = 0
        for _ in range(missing - len(self._provisioning)):
            if await self.connector.add_component(c.decode_component):
                self.replacements_total += 1
                self._note_provisioning()
                PLANNER_REPLACEMENTS.labels(c.decode_component).inc()
                log.warning(
                    "reconciliation: replacing lost %s worker "
                    "(reporting %d < intent %d)",
                    c.decode_component, int(reporting), self.decode_workers,
                )
            else:
                self._warn_connector(
                    "add", c.decode_component, "replacement refused"
                )
                break

    # -- degradation ladder -------------------------------------------------

    def _set_degradation(self, level: int) -> None:
        c = self.config
        level = max(0, min(c.degrade_max_level, level))
        if level == self.degradation_level:
            return
        log.warning(
            "degradation ladder: level %d -> %d (%s)",
            self.degradation_level, level,
            LEVEL_NAMES[min(level, len(LEVEL_NAMES) - 1)],
        )
        self.degradation_level = level
        PLANNER_DEGRADATION_LEVEL.set(level)
        if self.degradation is not None:
            try:
                self.degradation.set_level(level)
            except Exception:
                log.exception("degradation hook failed at level %d", level)

    async def make_adjustments(self, snap: dict[str, float]) -> None:
        c = self.config
        self._adjust_cycle += 1
        await self._reconcile(snap)
        kv = snap.get("kv_load_mean", 0.0)
        slo = snap.get("slo_attainment_mean", 1.0)
        reporting = snap.get("decode_workers_reporting")
        # ZERO workers reporting is an outage, not an idle fleet: the
        # kv/slo defaults (0.0 / 1.0) are vacuous, and acting on them
        # would build scale-DOWN pressure that decays intent toward
        # min_decode while reconciliation is trying to restore the
        # fleet. Freeze decode scaling and the ladder until metrics
        # return; prefill scaling stays live (queue depth is
        # store-backed, not worker-reported).
        blind = reporting is not None and int(reporting) <= 0
        slo_on = c.slo_target > 0.0
        # latency-sick even if memory-healthy -> scale-up pressure
        slo_breach = slo_on and slo < c.slo_target
        # scale-down needs BOTH kv headroom and slo headroom
        slo_headroom = (not slo_on) or slo >= c.slo_target + c.slo_headroom
        self._decode_sig.observe(
            up=(kv > c.decode_kv_scale_up or slo_breach) and not blind,
            down=kv < c.decode_kv_scale_down and slo_headroom and not blind,
        )
        self._prefill_sig.observe(
            up=snap.get("prefill_queue_per_worker", 0.0)
            > c.prefill_queue_scale_up,
            down=snap.get("prefill_queue_per_worker", 0.0)
            < c.prefill_queue_scale_down,
        )
        if self._decode_sig.up_streak >= c.grace_cycles:
            if self.decode_workers < c.max_decode:
                if await self._scale("add", c.decode_component,
                                     self._decode_sig):
                    self.decode_workers += 1
                    self._note_provisioning()
                    self._decode_sig = _Signal()
                    PLANNER_SCALE_EVENTS.labels(
                        c.decode_component, "up"
                    ).inc()
                    log.info("scaled decode up to %d", self.decode_workers)
                elif c.degrade_max_level > 0:
                    # the connector refused the add: real capacity is
                    # smaller than --max-decode says, so the fleet is
                    # saturated in practice — degrade rather than let
                    # every request miss while a rate-limited warning
                    # is the only response (streaks already reset in
                    # _scale, pacing escalation per breach window)
                    self._set_degradation(self.degradation_level + 1)
            elif c.degrade_max_level > 0:
                # saturated at max fleet and still breaching: degrade
                # one rung per persistent-breach window instead of
                # letting every request miss its target
                self._set_degradation(self.degradation_level + 1)
                self._decode_sig = _Signal()
        elif (
            self._decode_sig.down_streak >= c.grace_cycles
            and self.decode_workers > c.min_decode
        ):
            if await self._scale("remove", c.decode_component,
                                 self._decode_sig):
                self.decode_workers -= 1
                self._decode_sig = _Signal()
                PLANNER_SCALE_EVENTS.labels(c.decode_component, "down").inc()
                log.info("scaled decode down to %d", self.decode_workers)
        # unwind the ladder one rung at a time once the fleet has real
        # headroom (under the scale-UP watermark, SLO met with margin)
        if self.degradation_level > 0:
            relaxed = kv < c.decode_kv_scale_up and slo_headroom and not blind
            self._relax_streak = self._relax_streak + 1 if relaxed else 0
            if self._relax_streak >= c.grace_cycles:
                self._set_degradation(self.degradation_level - 1)
                self._relax_streak = 0
        if self._prefill_sig.up_streak >= c.grace_cycles:
            if self.prefill_workers < c.max_prefill:
                if await self._scale("add", c.prefill_component,
                                     self._prefill_sig):
                    self.prefill_workers += 1
                    self._prefill_sig = _Signal()
                    PLANNER_SCALE_EVENTS.labels(
                        c.prefill_component, "up"
                    ).inc()
                    log.info("scaled prefill up to %d", self.prefill_workers)
        elif (
            self._prefill_sig.down_streak >= c.grace_cycles
            and self.prefill_workers > c.min_prefill
        ):
            if await self._scale("remove", c.prefill_component,
                                 self._prefill_sig):
                self.prefill_workers -= 1
                self._prefill_sig = _Signal()
                PLANNER_SCALE_EVENTS.labels(c.prefill_component, "down").inc()
                log.info("scaled prefill down to %d", self.prefill_workers)

    async def _run(self) -> None:
        c = self.config
        last_adjust = self.clock.monotonic()
        while True:
            snap = await self.collect()
            now = self.clock.monotonic()
            if now - last_adjust >= c.adjustment_interval_s:
                await self.make_adjustments(snap)
                last_adjust = now
            await self.clock.sleep(c.metric_interval_s)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        await self.aggregator.close()


async def rolling_restart(
    connector: Any,
    component: str,
    max_unavailable: int = 1,
    health_timeout_s: float = 120.0,
    poll_interval_s: float = 1.0,
    clock: Clock = SYSTEM,
) -> int:
    """Cycle every replica of ``component`` through a graceful drain,
    at most ``max_unavailable`` down at a time (docs/robustness.md
    "Graceful drain & rolling restarts").

    Each round drains the oldest replica(s) — the worker hands its
    in-flight streams to peers and exits 0 — spawns replacements, and
    gates on the reported replica count recovering to the baseline
    before touching the next one, so a replacement that never comes up
    healthy stops the rollout instead of cascading into an outage.
    Returns the number of replicas cycled (== the starting count on a
    complete rollout).
    """

    async def _wait_count(target: int) -> bool:
        deadline = clock.monotonic() + health_timeout_s
        while clock.monotonic() < deadline:
            if await connector.replicas(component) == target:
                return True
            await clock.sleep(poll_interval_s)
        return False

    baseline = await connector.replicas(component)
    if not baseline:
        log.warning("rolling restart of %s: no replicas reported", component)
        return 0
    max_unavailable = max(1, min(max_unavailable, baseline))
    cycled = 0
    while cycled < baseline:
        batch = min(max_unavailable, baseline - cycled)
        drained = 0
        for _ in range(batch):
            if not await _drain_or_remove(connector, component):
                log.warning(
                    "rolling restart of %s aborted: drain refused after "
                    "%d replica(s) cycled", component, cycled,
                )
                return cycled
            drained += 1
        for _ in range(drained):
            if not await connector.add_component(component):
                log.warning(
                    "rolling restart of %s aborted: replacement spawn "
                    "refused after %d replica(s) cycled", component, cycled,
                )
                return cycled
        # health gate: the batch's replacements must be UP (reported
        # count back at baseline) before the next batch goes down
        if not await _wait_count(baseline):
            log.warning(
                "rolling restart of %s aborted: fleet did not return to "
                "%d replicas within %.0fs (%d cycled)",
                component, baseline, health_timeout_s, cycled,
            )
            return cycled
        cycled += drained
        log.info(
            "rolling restart of %s: %d/%d replica(s) cycled",
            component, cycled, baseline,
        )
    return cycled
