"""Request preprocessing: chat templates + tokenization + option extraction."""

from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor
from dynamo_tpu.preprocessor.prompt import PromptFormatter

__all__ = ["OpenAIPreprocessor", "PromptFormatter"]
