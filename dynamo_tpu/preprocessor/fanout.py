"""ChoiceFanout: OpenAI ``n>1`` as N engine sequences.

The reference's protocol layer carries ``n`` through to its engines
(reference: lib/llm/src/protocols/common.rs SamplingOptions.n); here the
fan-out happens above the engine: one PreprocessedRequest becomes N
single-choice requests sharing the prompt (the engine's prefix cache
makes the marginal cost of each extra choice one decode row — the
prompt's KV blocks are content-addressed and reused across choices).
Outputs merge into one stream with each item tagged by choice ``index``.

Seeds: choice j samples with seed+j when the request pins a seed
(distinct streams, reproducible); unseeded requests get distinct
request-id-derived streams for free (the engine hashes request_id).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream


class _ChoiceContext(Context):
    """Per-choice context: isolated stop (one choice hitting its stop
    condition must NOT cancel its siblings — the Backend calls
    stop_generating() on ITS stream's context) while still observing
    the parent's cancellation (client disconnect kills all choices)."""

    def __init__(self, parent: Context):
        super().__init__(
            id=parent.id,
            trace_id=parent.trace_id,
            span_id=parent.span_id,
        )
        self.trace_sampled = parent.trace_sampled
        self._parent = parent

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set() or self._parent.is_stopped

    @property
    def is_killed(self) -> bool:
        return self._kill.is_set() or self._parent.is_killed


class ChoiceFanout(AsyncEngine):
    """Wraps an AsyncEngine consuming PreprocessedRequest; fans n>1 out."""

    def __init__(self, inner: AsyncEngine):
        self.inner = inner

    def generate(self, request: Any, context: Context) -> EngineStream:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.model_validate(request)
        if request.sampling.n <= 1:
            return self.inner.generate(request, context)
        return self._fan(request, context)

    async def _fan(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Any]:
        n = request.sampling.n
        queue: asyncio.Queue = asyncio.Queue()
        _DONE = object()

        async def pump(j: int) -> None:
            sub = request.model_copy(deep=True)
            sub.request_id = f"{request.request_id}-c{j}"
            sub.sampling.n = 1
            if sub.sampling.seed is not None:
                sub.sampling.seed = sub.sampling.seed + j
            try:
                async for item in self.inner.generate(
                    sub, _ChoiceContext(context)
                ):
                    if not isinstance(item, LLMEngineOutput):
                        item = LLMEngineOutput.model_validate(item)
                    item.index = j
                    # restore the parent id: choices belong to ONE
                    # completion object upstream
                    item.request_id = request.request_id
                    await queue.put(item)
            # the merger (not the loop) owns pump lifetimes: every exit —
            # including cancellation during its teardown — must enqueue the
            # exception + _DONE or the `done < n` loop hangs forever
            except BaseException as exc:  # dynalint: disable=swallowed-cancellation
                await queue.put(exc)
            finally:
                await queue.put(_DONE)

        tasks = [asyncio.create_task(pump(j)) for j in range(n)]
        done = 0
        try:
            while done < n:
                item = await queue.get()
                if item is _DONE:
                    done += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            for t in tasks:
                t.cancel()
