"""OpenAIPreprocessor: OpenAI request ⇄ engine-facing request/stream.

Analogue of the reference's preprocessor (reference:
lib/llm/src/preprocessor.rs:63-184 — chat-template render + tokenize +
sampling/stop extraction into BackendInput; backward:
transform_postprocessor_stream into SSE delta objects).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional, Union

from dynamo_tpu.preprocessor.prompt import PromptFormatter
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    CompletionRequest,
    Usage,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import Operator
from dynamo_tpu.tokenizer import Tokenizer


@dataclass
class _ReqState:
    kind: str  # "chat" | "completion"
    model: str
    request_id: str
    prompt_tokens: int
    include_usage: bool
    logprobs: bool


class OpenAIPreprocessor(Operator):
    def __init__(
        self,
        tokenizer: Tokenizer,
        formatter: Optional[PromptFormatter] = None,
        model_name: str = "",
    ):
        self.tokenizer = tokenizer
        self.formatter = formatter
        self.model_name = model_name

    # -- request adaptation ----------------------------------------------
    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        if self.formatter is None:
            raise ValueError("chat requests need a PromptFormatter (chat template)")
        ext = request.extension()
        if ext.use_raw_prompt:
            prompt = "".join(m.text_content() for m in request.messages)
        else:
            prompt = self.formatter.render(
                [m.model_dump(exclude_none=True) for m in request.messages],
                add_generation_prompt=True,
                tools=request.tools,
            )
        token_ids = self.tokenizer.encode(prompt)
        return PreprocessedRequest(
            request_id=f"chatcmpl-{uuid.uuid4().hex}",
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=request.stop_conditions(),
            output=request.output_options(),
            model=request.model,
            annotations=list(ext.annotations),
        )

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        elif prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
        elif prompt and isinstance(prompt[0], str):
            if len(prompt) != 1:
                raise ValueError("batched string prompts not supported per-request")
            token_ids = self.tokenizer.encode(prompt[0])
        elif prompt and isinstance(prompt[0], list):
            if len(prompt) != 1:
                raise ValueError("batched token prompts not supported per-request")
            token_ids = list(prompt[0])
        else:
            raise ValueError("empty prompt")
        return PreprocessedRequest(
            request_id=f"cmpl-{uuid.uuid4().hex}",
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=request.stop_conditions(),
            output=request.output_options(),
            model=request.model,
            annotations=list(request.extension().annotations),
        )

    # -- Operator interface ----------------------------------------------
    async def forward(
        self,
        request: Union[ChatCompletionRequest, CompletionRequest],
        context: Context,
    ) -> tuple[PreprocessedRequest, _ReqState]:
        if isinstance(request, ChatCompletionRequest):
            pre = self.preprocess_chat(request)
            kind = "chat"
        elif isinstance(request, CompletionRequest):
            pre = self.preprocess_completion(request)
            kind = "completion"
        else:
            raise TypeError(f"unsupported request type {type(request)}")
        # OpenAI semantics: non-streaming responses ALWAYS carry usage;
        # streaming only includes it with stream_options.include_usage
        include_usage = not request.stream or bool(
            request.stream_options and request.stream_options.include_usage
        )
        state = _ReqState(
            kind=kind,
            model=request.model or self.model_name,
            request_id=pre.request_id,
            prompt_tokens=len(pre.token_ids),
            include_usage=include_usage,
            logprobs=pre.output.logprobs is not None,
        )
        return pre, state

    async def backward(
        self,
        stream: AsyncIterator[Any],
        state: _ReqState,
        context: Context,
    ) -> AsyncIterator[Any]:
        """Map the Backend's text-delta stream into OpenAI chunk objects."""
        if state.kind == "chat":
            gen = ChatDeltaGenerator(model=state.model, request_id=state.request_id)
        else:
            gen = CompletionDeltaGenerator(model=state.model, request_id=state.request_id)
        completion_tokens = 0
        async for raw in stream:
            item = (
                raw
                if isinstance(raw, LLMEngineOutput)
                else LLMEngineOutput.model_validate(raw)
            )
            completion_tokens += len(item.token_ids)
            if item.text:
                yield gen.text_chunk(item.text)
            if item.finish_reason is not None:
                yield gen.finish_chunk(item.finish_reason)
                if state.include_usage:
                    # OpenAI semantics: usage rides a trailing chunk with
                    # an empty choices array (stream_options.include_usage);
                    # the non-streaming aggregators pick it up from there
                    ct = item.completion_tokens or completion_tokens
                    yield gen.usage_chunk(
                        Usage(
                            prompt_tokens=state.prompt_tokens,
                            completion_tokens=ct,
                            total_tokens=state.prompt_tokens + ct,
                        )
                    )
                return
