"""OpenAIPreprocessor: OpenAI request ⇄ engine-facing request/stream.

Analogue of the reference's preprocessor (reference:
lib/llm/src/preprocessor.rs:63-184 — chat-template render + tokenize +
sampling/stop extraction into BackendInput; backward:
transform_postprocessor_stream into SSE delta objects).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional, Union

from dynamo_tpu.preprocessor.prompt import PromptFormatter
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    CompletionRequest,
    Usage,
    guided_options,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import Operator
from dynamo_tpu.telemetry.hostplane import note_stage
from dynamo_tpu.tokenizer import Tokenizer


@dataclass
class _ReqState:
    kind: str  # "chat" | "completion"
    model: str
    request_id: str
    prompt_tokens: int
    include_usage: bool
    logprobs: bool
    n: int = 1  # choices (ChoiceFanout tags items with their index)
    # tool-call streaming (docs/guided_decoding.md): "forced" wraps the
    # whole (schema-guided) output as one tool call; "auto" watches the
    # stream for the inline-JSON call shape and converts on detection
    tool_mode: Optional[str] = None  # None | "forced" | "auto"
    tool_name: Optional[str] = None  # the forced function's name


class OpenAIPreprocessor(Operator):
    def __init__(
        self,
        tokenizer: Tokenizer,
        formatter: Optional[PromptFormatter] = None,
        model_name: str = "",
    ):
        self.tokenizer = tokenizer
        self.formatter = formatter
        self.model_name = model_name

    # -- request adaptation ----------------------------------------------
    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        if self.formatter is None:
            raise ValueError("chat requests need a PromptFormatter (chat template)")
        ext = request.extension()
        if ext.use_raw_prompt:
            prompt = "".join(m.text_content() for m in request.messages)
        else:
            prompt = self.formatter.render(
                [m.model_dump(exclude_none=True) for m in request.messages],
                add_generation_prompt=True,
                tools=request.tools,
            )
        token_ids = self.tokenizer.encode(prompt)
        return PreprocessedRequest(
            request_id=f"chatcmpl-{uuid.uuid4().hex}",
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=request.stop_conditions(),
            output=request.output_options(),
            model=request.model,
            annotations=list(ext.annotations),
            speculative=ext.speculative,
            migration=ext.migration,
            guided=guided_options(request),
        )

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        elif prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
        elif prompt and isinstance(prompt[0], str):
            if len(prompt) != 1:
                raise ValueError("batched string prompts not supported per-request")
            token_ids = self.tokenizer.encode(prompt[0])
        elif prompt and isinstance(prompt[0], list):
            if len(prompt) != 1:
                raise ValueError("batched token prompts not supported per-request")
            token_ids = list(prompt[0])
        else:
            raise ValueError("empty prompt")
        ext = request.extension()
        return PreprocessedRequest(
            request_id=f"cmpl-{uuid.uuid4().hex}",
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=request.stop_conditions(),
            output=request.output_options(),
            model=request.model,
            annotations=list(ext.annotations),
            speculative=ext.speculative,
            migration=ext.migration,
            guided=guided_options(request),
        )

    # -- Operator interface ----------------------------------------------
    async def forward(
        self,
        request: Union[ChatCompletionRequest, CompletionRequest],
        context: Context,
    ) -> tuple[PreprocessedRequest, _ReqState]:
        from dynamo_tpu.telemetry import get_tracer

        t_pre = time.monotonic()
        with get_tracer().span(
            "preprocess", parent=context, attrs={"service": "frontend"}
        ) as span:
            if isinstance(request, ChatCompletionRequest):
                pre = self.preprocess_chat(request)
                kind = "chat"
            elif isinstance(request, CompletionRequest):
                pre = self.preprocess_completion(request)
                kind = "completion"
            else:
                raise TypeError(f"unsupported request type {type(request)}")
            span.set_attr("prompt_tokens", len(pre.token_ids))
        # accumulates onto the frontend's body-parse stamp: the pipeline
        # runs this lazily inside the first __anext__, so without the
        # stamp the template render + tokenize would masquerade as
        # first-chunk priming in the host-cost ledger
        note_stage(context.id, "preprocess", time.monotonic() - t_pre)
        # OpenAI semantics: non-streaming responses ALWAYS carry usage;
        # streaming only includes it with stream_options.include_usage
        include_usage = not request.stream or bool(
            request.stream_options and request.stream_options.include_usage
        )
        tool_mode = tool_name = None
        if kind == "chat" and getattr(request, "tools", None):
            from dynamo_tpu.guided.tools import forced_tool_name

            if request.tool_choice != "none":
                tool_name = forced_tool_name(request.tool_choice, request.tools)
                tool_mode = "forced" if tool_name else "auto"
        state = _ReqState(
            kind=kind,
            model=request.model or self.model_name,
            request_id=pre.request_id,
            prompt_tokens=len(pre.token_ids),
            include_usage=include_usage,
            logprobs=pre.output.logprobs is not None,
            n=pre.sampling.n,
            tool_mode=tool_mode,
            tool_name=tool_name,
        )
        return pre, state

    # -- logprob payload construction -------------------------------------
    def _token_str(self, tid: int) -> str:
        return self.tokenizer.decode([tid], skip_special_tokens=False)

    def _token_bytes(self, tid: int) -> list[int]:
        """OpenAI's per-token ``bytes``: the token's RAW contribution —
        clients reassemble partial-UTF-8 tokens from these, which the
        display string (decode of one id -> U+FFFD for partial
        sequences) cannot provide."""
        try:
            return list(self.tokenizer.token_bytes(tid))
        except Exception:
            return list(self._token_str(tid).encode("utf-8"))

    def _chat_logprobs(self, item: LLMEngineOutput) -> Optional[dict]:
        """OpenAI chat logprobs content for one delta
        (reference: lib/llm/src/protocols/common.rs:323-372)."""
        if not item.token_ids or not item.log_probs:
            return None
        entries = []
        for k, tid in enumerate(item.token_ids):
            tstr = self._token_str(tid)
            tops = (
                item.top_logprobs[k]
                if item.top_logprobs and k < len(item.top_logprobs)
                else {}
            )
            alts = [
                {
                    "token": self._token_str(alt),
                    "logprob": lp,
                    "bytes": self._token_bytes(alt),
                }
                for alt, lp in tops.items()
            ]
            entries.append(
                {
                    "token": tstr,
                    "logprob": item.log_probs[k],
                    "bytes": self._token_bytes(tid),
                    "top_logprobs": alts,
                }
            )
        return {"content": entries}

    def _completion_logprobs(
        self, item: LLMEngineOutput, char_off: int
    ) -> tuple[Optional[dict], int]:
        """Legacy completions logprobs object for one delta; returns
        (payload, advanced char offset)."""
        if not item.token_ids or not item.log_probs:
            return None, char_off
        toks, offs, tops = [], [], []
        for k, tid in enumerate(item.token_ids):
            tstr = self._token_str(tid)
            toks.append(tstr)
            offs.append(char_off)
            char_off += len(tstr)
            t = (
                item.top_logprobs[k]
                if item.top_logprobs and k < len(item.top_logprobs)
                else None
            )
            if t:
                # the legacy schema keys alternatives by token STRING —
                # distinct token ids can decode to the same text (e.g.
                # multibyte fragments); keep the max logprob per string
                # so a collision never shadows the likelier (often the
                # chosen) entry
                d: dict[str, float] = {}
                for a, lp in t.items():
                    s = self._token_str(a)
                    if s not in d or lp > d[s]:
                        d[s] = lp
                tops.append(d)
            else:
                tops.append(None)
        payload = {
            "tokens": toks,
            "token_logprobs": list(item.log_probs),
            "top_logprobs": tops if any(t is not None for t in tops) else None,
            "text_offset": offs,
        }
        return payload, char_off

    async def backward(
        self,
        stream: AsyncIterator[Any],
        state: _ReqState,
        context: Context,
    ) -> AsyncIterator[Any]:
        """Map the Backend's text-delta stream into OpenAI chunk objects.

        Handles n>1 (ChoiceFanout tags items with their choice index):
        per-choice deltas/finish chunks; ONE trailing usage chunk after
        every choice has finished, completion tokens summed across
        choices (prompt counted once, OpenAI semantics).

        Tool-call streams (state.tool_mode; docs/guided_decoding.md):
        each choice's text runs through a ToolCallStreamParser — forced
        mode converts every delta into arguments fragments, auto mode
        converts on detection and flushes plain text untouched on a
        miss. A detected call finishes with reason "tool_calls";
        logprob payloads are dropped on tool-mode chat streams (the
        parser re-chunks text, so per-delta alignment no longer holds)."""
        if state.kind == "chat":
            gen = ChatDeltaGenerator(model=state.model, request_id=state.request_id)
        else:
            gen = CompletionDeltaGenerator(model=state.model, request_id=state.request_id)
        parsers: dict[int, Any] = {}
        use_tools = state.kind == "chat" and state.tool_mode is not None

        def tool_parser(idx: int):
            p = parsers.get(idx)
            if p is None:
                from dynamo_tpu.guided.tools import ToolCallStreamParser

                p = parsers[idx] = ToolCallStreamParser(
                    forced_name=(
                        state.tool_name if state.tool_mode == "forced" else None
                    )
                )
            return p

        def tool_chunks(idx: int, events):
            for ev in events:
                if ev.kind == "text":
                    yield gen.text_chunk(ev.value, index=idx)
                elif ev.kind == "tool_start":
                    yield gen.tool_start_chunk(ev.value, index=idx)
                elif ev.kind == "tool_args":
                    if ev.value:
                        yield gen.tool_args_chunk(ev.value, index=idx)

        completion_tokens: dict[int, int] = {}
        char_offsets: dict[int, int] = {}
        finished: set[int] = set()
        total_completion = 0
        async for raw in stream:
            item = (
                raw
                if isinstance(raw, LLMEngineOutput)
                else LLMEngineOutput.model_validate(raw)
            )
            idx = item.index
            completion_tokens[idx] = completion_tokens.get(idx, 0) + len(
                item.token_ids
            )
            lp_payload = None
            if state.logprobs and not use_tools:
                if state.kind == "chat":
                    lp_payload = self._chat_logprobs(item)
                else:
                    lp_payload, char_offsets[idx] = self._completion_logprobs(
                        item, char_offsets.get(idx, 0)
                    )
            if use_tools:
                if item.text:
                    t_tp = time.monotonic()
                    events = tool_parser(idx).feed(item.text)
                    note_stage(
                        context.id, "tool_parser", time.monotonic() - t_tp
                    )
                    for chunk in tool_chunks(idx, events):
                        yield chunk
            elif item.text or lp_payload:
                yield gen.text_chunk(
                    item.text or "", index=idx, logprobs=lp_payload
                )
            if item.finish_reason is not None:
                reason = item.finish_reason
                if use_tools:
                    p = tool_parser(idx)
                    t_tp = time.monotonic()
                    events = p.finish()
                    note_stage(
                        context.id, "tool_parser", time.monotonic() - t_tp
                    )
                    for chunk in tool_chunks(idx, events):
                        yield chunk
                    reason_str = (
                        reason.value
                        if isinstance(reason, FinishReason)
                        else str(reason)
                    )
                    # OpenAI semantics: only a COMPLETED call finishes
                    # with "tool_calls" — a stream truncated by
                    # max_tokens OR stopped (eos) mid-arguments keeps
                    # its real reason so clients never json.loads an
                    # unterminated fragment
                    if (
                        p.tool_call_detected
                        and p.arguments_complete
                        and reason_str == "stop"
                    ):
                        from dynamo_tpu.telemetry.instruments import (
                            TOOL_CALL_STREAMS,
                        )

                        TOOL_CALL_STREAMS.labels(state.tool_mode).inc()
                        reason = "tool_calls"
                if state.kind == "chat" and idx not in gen._started:
                    # a choice whose every token detokenized to "" never
                    # got a content delta — OpenAI streams still carry
                    # the assistant role delta for EVERY choice
                    yield gen.role_chunk(index=idx)
                yield gen.finish_chunk(reason, index=idx)
                finished.add(idx)
                total_completion += (
                    item.completion_tokens or completion_tokens.get(idx, 0)
                )
                if len(finished) < state.n:
                    continue
                if state.include_usage:
                    # OpenAI semantics: usage rides a trailing chunk with
                    # an empty choices array (stream_options.include_usage);
                    # the non-streaming aggregators pick it up from there
                    yield gen.usage_chunk(
                        Usage(
                            prompt_tokens=state.prompt_tokens,
                            completion_tokens=total_completion,
                            total_tokens=state.prompt_tokens
                            + total_completion,
                        )
                    )
                return
