"""Chat-template rendering with Jinja2.

Analogue of the reference's prompt formatter (reference:
lib/llm/src/preprocessor/prompt/template/{tokcfg,oai,formatters}.rs —
minijinja rendering of the HF tokenizer_config chat_template with pycompat
helpers). Templates come from ``tokenizer_config.json`` or an explicit
string; rendering gets the usual HF context: messages, tools, bos/eos
tokens, add_generation_prompt, plus ``raise_exception``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jinja2


class TemplateError(ValueError):
    pass


def _raise_exception(message: str) -> None:
    raise TemplateError(message)


def _strftime_now(fmt: str) -> str:
    import datetime

    return datetime.datetime.now().strftime(fmt)


class PromptFormatter:
    def __init__(
        self,
        chat_template: str,
        bos_token: str = "",
        eos_token: str = "",
        extra_context: Optional[dict[str, Any]] = None,
    ):
        self.bos_token = bos_token
        self.eos_token = eos_token
        self.extra_context = extra_context or {}
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True,
            lstrip_blocks=True,
            extensions=["jinja2.ext.loopcontrols"],
        )
        env.filters["tojson"] = lambda v, indent=None: json.dumps(v, indent=indent)
        env.globals["raise_exception"] = _raise_exception
        env.globals["strftime_now"] = _strftime_now
        self._template = env.from_string(chat_template)

    @classmethod
    def from_model_dir(cls, path: str) -> "PromptFormatter":
        """Load chat_template/bos/eos from a model dir's tokenizer_config.json."""
        cfg_path = os.path.join(path, "tokenizer_config.json")
        with open(cfg_path) as f:
            cfg = json.load(f)
        template = cfg.get("chat_template")
        if template is None:
            raise TemplateError(f"no chat_template in {cfg_path}")
        if isinstance(template, list):
            # multi-template form: pick "default"
            by_name = {t["name"]: t["template"] for t in template}
            template = by_name.get("default") or next(iter(by_name.values()))

        def _tok_str(v: Any) -> str:
            if isinstance(v, dict):  # AddedToken serialized form
                return v.get("content", "")
            return v or ""

        return cls(
            chat_template=template,
            bos_token=_tok_str(cfg.get("bos_token")),
            eos_token=_tok_str(cfg.get("eos_token")),
        )

    def render(
        self,
        messages: list[dict[str, Any]],
        add_generation_prompt: bool = True,
        tools: Optional[list[dict[str, Any]]] = None,
        **kwargs: Any,
    ) -> str:
        ctx: dict[str, Any] = {
            "messages": messages,
            "add_generation_prompt": add_generation_prompt,
            "bos_token": self.bos_token,
            "eos_token": self.eos_token,
            **self.extra_context,
            **kwargs,
        }
        if tools is not None:
            ctx["tools"] = tools
        try:
            return self._template.render(**ctx)
        except jinja2.TemplateError as exc:
            raise TemplateError(f"chat template failed: {exc}") from exc
