"""Wire protocols: OpenAI-compatible types, SSE codec, streaming envelopes.

TPU-native analogue of the reference's protocol layer
(reference: lib/llm/src/protocols/*.rs — openai types, codec.rs SSE,
common.rs sampling/stop options, annotated.rs envelope).
"""

from dynamo_tpu.protocols.annotated import Annotated
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    OutputOptions,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "Annotated",
    "FinishReason",
    "LLMEngineOutput",
    "OutputOptions",
    "SamplingOptions",
    "StopConditions",
]
