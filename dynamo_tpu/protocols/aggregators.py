"""Stream → full-response aggregation.

Analogue of the reference's delta aggregators
(reference: lib/llm/src/protocols/openai/chat_completions/aggregator.rs,
completions/aggregator.rs): fold a stream of chunks into the single
non-streaming response object, for clients that set ``stream=false``.
"""

from __future__ import annotations

from typing import AsyncIterator, Iterable

from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionChoice,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChoice,
    CompletionResponse,
    Usage,
)


class ChatAggregator:
    def __init__(self) -> None:
        self._id: str | None = None
        self._model: str | None = None
        self._created: int = 0
        self._texts: dict[int, list[str]] = {}
        self._roles: dict[int, str] = {}
        self._finish: dict[int, str | None] = {}
        self._logprobs: dict[int, list] = {}
        # per-choice tool calls: tool_call index -> {id, type, name,
        # arguments parts} (streaming deltas carry the header once, then
        # arguments fragments to concatenate — OpenAI tool-call shape)
        self._tools: dict[int, dict[int, dict]] = {}
        self._usage: Usage | None = None

    def push(self, chunk: ChatCompletionChunk) -> None:
        self._id = self._id or chunk.id
        self._model = self._model or chunk.model
        self._created = self._created or chunk.created
        if chunk.usage is not None:
            self._usage = chunk.usage
        for choice in chunk.choices:
            idx = choice.index
            if choice.delta.role:
                self._roles[idx] = choice.delta.role
            if choice.delta.content:
                self._texts.setdefault(idx, []).append(choice.delta.content)
            for tc in choice.delta.tool_calls or []:
                ti = int(tc.get("index", 0))
                acc = self._tools.setdefault(idx, {}).setdefault(
                    ti, {"id": None, "type": "function", "name": "", "args": []}
                )
                if tc.get("id"):
                    acc["id"] = tc["id"]
                fn = tc.get("function") or {}
                if fn.get("name"):
                    acc["name"] = fn["name"]
                if fn.get("arguments"):
                    acc["args"].append(fn["arguments"])
            if choice.logprobs and choice.logprobs.get("content"):
                self._logprobs.setdefault(idx, []).extend(
                    choice.logprobs["content"]
                )
            if choice.finish_reason is not None:
                self._finish[idx] = choice.finish_reason

    def _tool_calls(self, idx: int) -> list[dict] | None:
        acc = self._tools.get(idx)
        if not acc:
            return None
        return [
            {
                "id": a["id"] or f"call_{i}",
                "type": a["type"],
                "function": {
                    "name": a["name"],
                    "arguments": "".join(a["args"]),
                },
            }
            for i, a in sorted(acc.items())
        ]

    def response(self) -> ChatCompletionResponse:
        indices = sorted(
            set(self._texts) | set(self._finish) | set(self._roles)
            | set(self._tools) | {0}
        )
        choices = [
            ChatCompletionChoice(
                index=i,
                message=ChatMessage(
                    role=self._roles.get(i, "assistant"),
                    # OpenAI tool-call messages carry content=null
                    content=(
                        None
                        if self._tools.get(i)
                        else "".join(self._texts.get(i, []))
                    ),
                    tool_calls=self._tool_calls(i),
                ),
                finish_reason=self._finish.get(i),
                logprobs=(
                    {"content": self._logprobs[i]}
                    if i in self._logprobs
                    else None
                ),
            )
            for i in indices
        ]
        return ChatCompletionResponse(
            id=self._id or "chatcmpl-empty",
            created=self._created,
            model=self._model or "",
            choices=choices,
            usage=self._usage,
        )

    @classmethod
    def aggregate(cls, chunks: Iterable[ChatCompletionChunk]) -> ChatCompletionResponse:
        agg = cls()
        for c in chunks:
            agg.push(c)
        return agg.response()

    @classmethod
    async def aggregate_async(
        cls, chunks: AsyncIterator[ChatCompletionChunk]
    ) -> ChatCompletionResponse:
        agg = cls()
        async for c in chunks:
            agg.push(c)
        return agg.response()


class CompletionAggregator:
    def __init__(self) -> None:
        self._id: str | None = None
        self._model: str | None = None
        self._created: int = 0
        self._texts: dict[int, list[str]] = {}
        self._finish: dict[int, str | None] = {}
        self._logprobs: dict[int, dict] = {}
        self._usage: Usage | None = None

    def push(self, chunk: CompletionResponse) -> None:
        self._id = self._id or chunk.id
        self._model = self._model or chunk.model
        self._created = self._created or chunk.created
        if chunk.usage is not None:
            self._usage = chunk.usage
        for choice in chunk.choices:
            if choice.text:
                self._texts.setdefault(choice.index, []).append(choice.text)
            if choice.logprobs:
                # legacy format: parallel lists — concatenate across deltas
                acc = self._logprobs.setdefault(
                    choice.index,
                    {"tokens": [], "token_logprobs": [], "top_logprobs": [],
                     "text_offset": []},
                )
                lp = choice.logprobs
                acc["tokens"].extend(lp.get("tokens") or [])
                acc["token_logprobs"].extend(lp.get("token_logprobs") or [])
                tl = lp.get("top_logprobs")
                acc["top_logprobs"].extend(
                    tl if tl is not None
                    else [None] * len(lp.get("tokens") or [])
                )
                acc["text_offset"].extend(lp.get("text_offset") or [])
            if choice.finish_reason is not None:
                self._finish[choice.index] = choice.finish_reason

    def response(self) -> CompletionResponse:
        indices = sorted(set(self._texts) | set(self._finish) | {0})
        choices = [
            CompletionChoice(
                index=i,
                text="".join(self._texts.get(i, [])),
                finish_reason=self._finish.get(i),
                logprobs=self._logprobs.get(i),
            )
            for i in indices
        ]
        return CompletionResponse(
            id=self._id or "cmpl-empty",
            created=self._created,
            model=self._model or "",
            choices=choices,
            usage=self._usage,
        )

    @classmethod
    def aggregate(cls, chunks: Iterable[CompletionResponse]) -> CompletionResponse:
        agg = cls()
        for c in chunks:
            agg.push(c)
        return agg.response()

    @classmethod
    async def aggregate_async(
        cls, chunks: AsyncIterator[CompletionResponse]
    ) -> CompletionResponse:
        agg = cls()
        async for c in chunks:
            agg.push(c)
        return agg.response()
