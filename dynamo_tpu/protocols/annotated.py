"""Annotated: the SSE-able streaming envelope.

Analogue of the reference's Annotated<R>
(lib/runtime/src/protocols/annotated.rs:168): every item on a response
stream carries optional ``data`` plus SSE metadata (event name, comments,
id). Errors travel in-band as ``event="error"`` so a stream can terminate
with a structured error instead of a broken connection.
"""

from __future__ import annotations

from typing import Any, Generic, Optional, TypeVar

from pydantic import BaseModel, Field

T = TypeVar("T")


class Annotated(BaseModel, Generic[T]):
    data: Optional[T] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: list[str] = Field(default_factory=list)

    @classmethod
    def from_data(cls, data: T) -> "Annotated[T]":
        return cls(data=data)

    @classmethod
    def from_error(cls, message: str) -> "Annotated[T]":
        return cls(event="error", comment=[message])

    @classmethod
    def from_annotation(cls, name: str, value: Any) -> "Annotated[T]":
        """Out-of-band annotation events (e.g. timing traces) requested via
        request ``annotations`` (reference: nvext annotations)."""
        import json

        return cls(event=name, comment=[json.dumps(value)])

    @property
    def is_error(self) -> bool:
        return self.event == "error"

    def error_message(self) -> Optional[str]:
        if not self.is_error:
            return None
        return "; ".join(self.comment) if self.comment else "unknown error"
