"""Engine-facing protocol types shared by all frontends.

Analogue of the reference's internal request/response model
(reference: lib/llm/src/protocols/common.rs — SamplingOptions,
StopConditions, and lib/llm/src/protocols/common/llm_backend.rs —
BackendInput/BackendOutput/LLMEngineOutput). Frontend-specific types
(OpenAI chat/completions) are *adapted into* these; engines only ever see
these types, which keeps every engine frontend-agnostic.
"""

from __future__ import annotations

import enum
from typing import Any, ClassVar, Optional

from pydantic import BaseModel, Field


class FinishReason(str, enum.Enum):
    STOP = "stop"            # hit a stop token / stop string
    LENGTH = "length"        # hit max_tokens / context limit
    CANCELLED = "cancelled"  # client disconnected or kill-signalled
    TIMEOUT = "timeout"      # request deadline budget expired
    ERROR = "error"
    CONTENT_FILTER = "content_filter"
    # drain handoff marker, never client-facing: a draining worker ends
    # each active stream with this so the router re-dispatches it as a
    # resume on a healthy peer (runtime/drain.py; docs/robustness.md
    # "Graceful drain"). The router consumes the chunk — clients only
    # ever see the continuation's real finish.
    MIGRATE = "migrate"


class SamplingOptions(BaseModel):
    """Sampling knobs, engine-agnostic (reference: common.rs SamplingOptions)."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    # token id -> additive logit bias (OpenAI-style; string keys from the
    # HTTP layer are normalized to ints by the adapters)
    logit_bias: Optional[dict[int, float]] = None
    seed: Optional[int] = None
    n: int = 1
    use_greedy: bool = False

    @property
    def needs_penalties(self) -> bool:
        """True when this request needs the token-count penalty sampling
        path (a separately-compiled device step variant carrying per-slot
        token-count tables; min_p/logit_bias ride the base path)."""
        return bool(
            self.frequency_penalty
            or self.presence_penalty
            or (
                self.repetition_penalty is not None
                and self.repetition_penalty != 1.0
            )
        )

    # On-device sampling shapes the distribution on the top-TOP_K_CAP
    # logit slice (a full 128k-vocab sort costs ~50 ms/step on v5e —
    # engine/sampling.py). top_k above the cap is clamped here, at the
    # request boundary, so the behavior is documented rather than a
    # silent truncation (ADVICE r3: sampling.py top-128 bound).
    TOP_K_CAP: ClassVar[int] = 128

    def normalized(self) -> "SamplingOptions":
        """Resolve greedy mode: temperature<=0 means greedy decoding.
        Clamps top_k to TOP_K_CAP (see note above)."""
        s = self.model_copy()
        if s.temperature is not None and s.temperature <= 0.0:
            s.use_greedy = True
            s.temperature = None
        if s.top_k is not None and s.top_k > self.TOP_K_CAP:
            s.top_k = self.TOP_K_CAP
        return s


class GuidedOptions(BaseModel):
    """Constrained-decoding spec (docs/guided_decoding.md): compiled by
    the serving engine into a token-level automaton over the served
    tokenizer's vocab, whose allow-mask rides every sampling step.
    Adapters build this from OpenAI ``response_format`` /
    ``tool_choice`` (protocols/openai.py guided_options); engines treat
    it as opaque data keyed for the process-wide compile cache."""

    kind: str  # "json_schema" | "regex" | "json_object"
    json_schema: Optional[dict[str, Any]] = None
    regex: Optional[str] = None


class StopConditions(BaseModel):
    """Stop criteria (reference: common.rs StopConditions).

    ``stop_token_ids_hidden`` stop generation and are excluded from output;
    ``stop`` strings are matched against the detokenized stream.
    """

    max_tokens: Optional[int] = None
    stop: list[str] = Field(default_factory=list)
    stop_token_ids_hidden: list[int] = Field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def apply_ignore_eos(self) -> "StopConditions":
        if self.ignore_eos:
            s = self.model_copy()
            s.stop = []
            s.stop_token_ids_hidden = []
            return s
        return self


class OutputOptions(BaseModel):
    """What the caller wants back beyond text (reference: common.rs)."""

    logprobs: Optional[int] = None
    echo: bool = False
    skip_special_tokens: bool = True


class PreprocessedRequest(BaseModel):
    """Tokenized, template-rendered request — what engines consume.

    Analogue of the reference's BackendInput
    (lib/llm/src/protocols/common/llm_backend.rs).
    """

    request_id: str
    token_ids: list[int]
    sampling: SamplingOptions = Field(default_factory=SamplingOptions)
    stop: StopConditions = Field(default_factory=StopConditions)
    output: OutputOptions = Field(default_factory=OutputOptions)
    # Routing hints
    model: Optional[str] = None
    lora_name: Optional[str] = None
    # Speculative decoding opt-in/out for THIS request (OpenAI
    # ext.speculative; docs/speculative_decoding.md): None follows the
    # engine default (on when the engine has a configured drafter),
    # False forces the literal plain-decode path (its batch diverts
    # from the verify step), True is a no-op on engines without a
    # drafter. Output distribution is preserved either way — this knob
    # trades per-request latency shape (token bursts) and exact seeded
    # reproducibility vs a non-speculative engine.
    speculative: Optional[bool] = None
    # Guided decoding (docs/guided_decoding.md): a compiled-at-admission
    # token-mask constraint (JSON Schema / regex / json_object mode).
    # None = unconstrained. Per-request opt-out mirrors ext.speculative:
    # OpenAI ext.guided=False keeps response_format/tools traffic
    # unmasked (the frontend still parses tool calls from free text).
    # Guided requests require an engine serving decode_steps == 1 (the
    # mask advances on host per committed token; fused K-step windows
    # sample K tokens per dispatch with no host in the loop).
    guided: Optional[GuidedOptions] = None
    # Mid-stream migration (docs/robustness.md "Mid-stream migration"):
    # ``resume_offset`` is the number of tokens a previous worker
    # already generated AND delivered for this request before it died —
    # the router's resume re-dispatch extends token_ids by those tokens
    # and sets this offset so the engine's per-request sampling RNG
    # (seeded ``base + generated + resume_offset`` per step) continues
    # the SAME stream: greedy continuations are bit-identical and
    # seeded/request-id-hashed sampling is stream-consistent across the
    # splice. 0 for ordinary requests.
    resume_offset: int = 0
    # Per-request migration opt-out (OpenAI ext.migration): False keeps
    # the PR-5 behavior (a mid-stream worker death ends the stream with
    # a clean SSE error); None/True allow the routers to resume it.
    migration: Optional[bool] = None
    # Disaggregation: filled by the disagg router when prefill is remote
    remote_prefill: Optional[dict[str, Any]] = None
    annotations: list[str] = Field(default_factory=list)
    # Multimodal: embedding segments to inject over placeholder tokens —
    # [{"offset", "shape", "dtype", "data"(b64)}], packed/unpacked by
    # dynamo_tpu.multimodal.embeds (reference: examples/multimodal
    # encode-worker → LLM embedding handoff)
    mm_embeds: Optional[list[dict[str, Any]]] = None


class LLMEngineOutput(BaseModel):
    """One streamed engine step for one request.

    Analogue of the reference's LLMEngineOutput
    (lib/llm/src/protocols/common/llm_backend.rs): token ids (deltas), optional
    pre-detokenized text, cumulative log prob, finish reason.
    """

    request_id: str = ""
    token_ids: list[int] = Field(default_factory=list)
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    log_probs: Optional[list[float]] = None
    # Parallel to token_ids when the request asked for top_logprobs:
    # each entry maps alternative token id -> logprob (top-N slice of
    # the same post-bias/penalty distribution log_probs comes from).
    # Reference: lib/llm/src/protocols/common.rs:323-372 TopLogprob.
    top_logprobs: Optional[list[dict[int, float]]] = None
    # Choice index for n>1 fan-out (preprocessor fans a request into n
    # engine sequences; chunks carry their choice index back upstream)
    index: int = 0
    finish_reason: Optional[FinishReason] = None
    # Engine metrics piggybacked on the final chunk
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None

    @classmethod
    def final(cls, request_id: str, reason: FinishReason) -> "LLMEngineOutput":
        return cls(request_id=request_id, finish_reason=reason)

    @property
    def is_final(self) -> bool:
        return self.finish_reason is not None
