"""OpenAI-compatible API types: chat completions + completions.

Analogue of the reference's OpenAI protocol layer
(reference: lib/llm/src/protocols/openai.rs, openai/chat_completions*.rs,
openai/completions*.rs, openai/nvext.rs). Includes the ``nvext``-style
extension field (named ``ext`` here) for engine-specific knobs like
ignore_eos/greedy and annotation requests.

Delta generators build the streaming chunk objects
(reference: chat_completions/delta.rs DeltaGenerator).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, field_validator

from dynamo_tpu.protocols.common import (
    FinishReason,
    GuidedOptions,
    OutputOptions,
    SamplingOptions,
    StopConditions,
)

# ---------------------------------------------------------------------------
# Extension payload (reference: nvext.rs NvExt)
# ---------------------------------------------------------------------------


class ExtOptions(BaseModel):
    """Engine extensions carried alongside the standard OpenAI fields."""

    model_config = ConfigDict(extra="allow")

    ignore_eos: Optional[bool] = None
    greedy_sampling: Optional[bool] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    repetition_penalty: Optional[float] = None
    annotations: list[str] = Field(default_factory=list)
    use_raw_prompt: Optional[bool] = None
    # per-request speculative-decoding opt-in/out (None = engine
    # default; False = plain decode for this request; True = no-op on
    # engines without a configured drafter) — carried through the
    # preprocessor into PreprocessedRequest.speculative
    speculative: Optional[bool] = None
    # per-request mid-stream-migration opt-out (None = on; False = a
    # worker death mid-stream ends the stream with a clean SSE error
    # instead of resuming elsewhere) — carried through the preprocessor
    # into PreprocessedRequest.migration (docs/robustness.md)
    migration: Optional[bool] = None
    # per-request guided-decoding opt-out (docs/guided_decoding.md),
    # mirroring ext.speculative: False serves response_format/tools
    # traffic UNMASKED (tool-call parsing still runs on the free text);
    # None/True compile the constraint into a token mask
    guided: Optional[bool] = None
    # raw regex constraint (engine extension — no OpenAI equivalent):
    # the completion must fullmatch this pattern (guided regex subset)
    guided_regex: Optional[str] = None


def _int_logit_bias(
    bias: Optional[dict[str, float]],
) -> Optional[dict[int, float]]:
    """OpenAI carries logit_bias keyed by token-id STRINGS; engines want
    ints. Keys are validated at request-model validation time
    (_validate_logit_bias below -> 400), so this conversion can't fail
    on the engine path."""
    if not bias:
        return None
    return {int(k): float(v) for k, v in bias.items()}


def _validate_logit_bias(v: Optional[dict[str, float]]):
    """Pydantic field validator: reject non-token-id keys DURING request
    validation so clients get a 400, not a mid-generation 500."""
    for k in v or {}:
        try:
            tok = int(k)
        except ValueError:
            raise ValueError(f"logit_bias key {k!r} is not a token id")
        if tok < 0:
            raise ValueError(f"logit_bias token id {tok} is negative")
    return v


# n>1 fans out as N engine sequences sharing the prompt's prefix-cache
# blocks; the cap bounds one request's batch-slot footprint.
MAX_N_CHOICES = 16


def _validate_n(v: Optional[int]):
    if v is not None and not (1 <= v <= MAX_N_CHOICES):
        raise ValueError(f"n must be between 1 and {MAX_N_CHOICES}")
    return v


# ---------------------------------------------------------------------------
# Chat completions
# ---------------------------------------------------------------------------


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")

    role: str
    content: Union[str, list[dict[str, Any]], None] = None
    name: Optional[str] = None
    tool_calls: Optional[list[dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        # multimodal list-of-parts: concatenate text parts
        return "".join(
            p.get("text", "") for p in self.content if p.get("type") == "text"
        )


class StreamOptions(BaseModel):
    include_usage: bool = False


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    messages: list[ChatMessage]
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: Optional[int] = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    stop: Union[str, list[str], None] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    logit_bias: Optional[dict[str, float]] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    seed: Optional[int] = None
    tools: Optional[list[dict[str, Any]]] = None
    tool_choice: Optional[Union[str, dict[str, Any]]] = None
    response_format: Optional[dict[str, Any]] = None
    ext: Optional[ExtOptions] = None
    # accept the reference's field name too
    nvext: Optional[ExtOptions] = None

    _check_logit_bias = field_validator("logit_bias")(_validate_logit_bias)
    _check_n = field_validator("n")(_validate_n)

    @field_validator("top_logprobs")
    @classmethod
    def _check_top_logprobs(cls, v, info):
        if v is not None and not (0 <= v <= 20):
            raise ValueError("top_logprobs must be between 0 and 20")
        if v and not info.data.get("logprobs"):
            raise ValueError("top_logprobs requires logprobs=true")
        return v

    def extension(self) -> ExtOptions:
        return self.ext or self.nvext or ExtOptions()

    # -- adaptation into engine-facing types (reference: common.rs From impls)
    def sampling_options(self) -> SamplingOptions:
        ext = self.extension()
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=ext.top_k,
            min_p=ext.min_p,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            repetition_penalty=ext.repetition_penalty,
            logit_bias=_int_logit_bias(self.logit_bias),
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(ext.greedy_sampling),
        ).normalized()

    def stop_conditions(self) -> StopConditions:
        stop = [self.stop] if isinstance(self.stop, str) else list(self.stop or [])
        return StopConditions(
            max_tokens=self.max_completion_tokens or self.max_tokens,
            stop=stop,
            ignore_eos=bool(self.extension().ignore_eos),
        )

    def output_options(self) -> OutputOptions:
        # logprobs=true alone returns the sampled token's logprob (0 extra
        # alternatives); top_logprobs adds the top-N alternatives
        return OutputOptions(
            logprobs=(self.top_logprobs or 0) if self.logprobs else None
        )


class ChatCompletionChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: list[ChatCompletionChoice]
    usage: Optional[Usage] = None
    system_fingerprint: Optional[str] = None


class ChatDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[list[dict[str, Any]]] = None


class ChatCompletionChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: list[ChatCompletionChunkChoice]
    usage: Optional[Usage] = None


# ---------------------------------------------------------------------------
# Completions (legacy text API)
# ---------------------------------------------------------------------------


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    prompt: Union[str, list[str], list[int], list[list[int]]]
    suffix: Optional[str] = None
    max_tokens: Optional[int] = 16
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: Optional[int] = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    logprobs: Optional[int] = None
    echo: bool = False
    stop: Union[str, list[str], None] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    logit_bias: Optional[dict[str, float]] = None
    seed: Optional[int] = None
    user: Optional[str] = None
    # response_format is not part of the legacy completions API, but the
    # guided-decoding path honors it here too (json_object/json_schema)
    response_format: Optional[dict[str, Any]] = None
    ext: Optional[ExtOptions] = None
    nvext: Optional[ExtOptions] = None

    _check_logit_bias = field_validator("logit_bias")(_validate_logit_bias)
    _check_n = field_validator("n")(_validate_n)

    @field_validator("logprobs")
    @classmethod
    def _check_logprobs(cls, v):
        # legacy completions API: logprobs is the alternative count
        if v is not None and not (0 <= v <= 20):
            raise ValueError("logprobs must be between 0 and 20")
        return v

    def extension(self) -> ExtOptions:
        return self.ext or self.nvext or ExtOptions()

    def sampling_options(self) -> SamplingOptions:
        ext = self.extension()
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=ext.top_k,
            min_p=ext.min_p,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            repetition_penalty=ext.repetition_penalty,
            logit_bias=_int_logit_bias(self.logit_bias),
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(ext.greedy_sampling),
        ).normalized()

    def stop_conditions(self) -> StopConditions:
        stop = [self.stop] if isinstance(self.stop, str) else list(self.stop or [])
        return StopConditions(
            max_tokens=self.max_tokens,
            stop=stop,
            ignore_eos=bool(self.extension().ignore_eos),
        )

    def output_options(self) -> OutputOptions:
        return OutputOptions(logprobs=self.logprobs, echo=self.echo)


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: list[CompletionChoice]
    usage: Optional[Usage] = None


# ---------------------------------------------------------------------------
# Guided-decoding adaptation (docs/guided_decoding.md)
# ---------------------------------------------------------------------------


def guided_options(
    request: Union[ChatCompletionRequest, CompletionRequest],
) -> Optional[GuidedOptions]:
    """Engine-facing guided spec from the OpenAI fields, priority order:

    1. ``ext.guided=False`` — explicit opt-out, nothing is masked;
    2. a FORCING ``tool_choice`` — the named tool's ``parameters``
       schema constrains generation (the frontend wraps the output as a
       tool call, so the model emits exactly the arguments object);
    3. ``ext.guided_regex`` — raw regex constraint (engine extension);
    4. ``response_format`` — ``json_object`` or ``json_schema`` (OpenAI
       nests the schema at ``response_format.json_schema.schema``).

    Raises ValueError for malformed response_format so the request
    fails with a client error, not a mid-generation engine error."""
    from dynamo_tpu.guided.tools import forced_tool_name, tool_parameters_schema

    ext = request.extension()
    if ext.guided is False:
        return None
    tools = getattr(request, "tools", None)
    tool_choice = getattr(request, "tool_choice", None)
    forced = forced_tool_name(tool_choice, tools) if tool_choice != "none" else None
    if forced:
        schema = tool_parameters_schema(tools, forced)
        if schema is None:
            raise ValueError(
                f"tool_choice forces {forced!r} but no such tool (or no "
                "parameters schema) was provided"
            )
        return GuidedOptions(kind="json_schema", json_schema=schema)
    if ext.guided_regex:
        return GuidedOptions(kind="regex", regex=ext.guided_regex)
    rf = request.response_format
    if isinstance(rf, dict) and rf.get("type"):
        t = rf["type"]
        if t == "json_object":
            return GuidedOptions(kind="json_object")
        if t == "json_schema":
            js = rf.get("json_schema")
            schema = js.get("schema") if isinstance(js, dict) else None
            if not isinstance(schema, dict):
                raise ValueError(
                    "response_format.json_schema.schema must be an object"
                )
            return GuidedOptions(kind="json_schema", json_schema=schema)
        if t != "text":
            raise ValueError(f"unsupported response_format type {t!r}")
    return None


# ---------------------------------------------------------------------------
# Models listing
# ---------------------------------------------------------------------------


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = 0
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


# ---------------------------------------------------------------------------
# Delta generators (reference: chat_completions/delta.rs, completions/delta.rs)
# ---------------------------------------------------------------------------


def _now() -> int:
    return int(time.time())


class ChatDeltaGenerator:
    """Builds the streaming chunk sequence for one chat request."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or f"chatcmpl-{uuid.uuid4().hex}"
        self.model = model
        self.created = _now()
        # choice indices that have emitted their role delta (n>1: every
        # choice's first chunk carries role="assistant")
        self._started: set[int] = set()

    def role_chunk(self, index: int = 0) -> ChatCompletionChunk:
        self._started.add(index)
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatCompletionChunkChoice(
                    index=index, delta=ChatDelta(role="assistant", content="")
                )
            ],
        )

    def text_chunk(
        self,
        text: str,
        index: int = 0,
        logprobs: Optional[dict[str, Any]] = None,
    ) -> ChatCompletionChunk:
        delta = ChatDelta(content=text)
        if index not in self._started:
            delta.role = "assistant"
            self._started.add(index)
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatCompletionChunkChoice(
                    index=index, delta=delta, logprobs=logprobs
                )
            ],
        )

    def tool_start_chunk(
        self, name: str, index: int = 0, call_id: Optional[str] = None
    ) -> ChatCompletionChunk:
        """First tool-call delta of a choice: the id/type/name header
        with empty arguments (OpenAI streaming tool-call shape)."""
        delta = ChatDelta(
            tool_calls=[
                {
                    "index": 0,
                    "id": call_id or f"call_{uuid.uuid4().hex[:24]}",
                    "type": "function",
                    "function": {"name": name, "arguments": ""},
                }
            ]
        )
        if index not in self._started:
            delta.role = "assistant"
            self._started.add(index)
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[ChatCompletionChunkChoice(index=index, delta=delta)],
        )

    def tool_args_chunk(
        self, arguments_delta: str, index: int = 0
    ) -> ChatCompletionChunk:
        """Incremental arguments fragment; clients concatenate the
        ``function.arguments`` strings to reassemble the JSON object."""
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatCompletionChunkChoice(
                    index=index,
                    delta=ChatDelta(
                        tool_calls=[
                            {
                                "index": 0,
                                "function": {"arguments": arguments_delta},
                            }
                        ]
                    ),
                )
            ],
        )

    def finish_chunk(
        self, reason: FinishReason | str, index: int = 0
    ) -> ChatCompletionChunk:
        reason_str = reason.value if isinstance(reason, FinishReason) else reason
        # OpenAI wire format only knows stop/length/content_filter/tool_calls
        if reason_str in ("cancelled", "error"):
            reason_str = "stop"
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatCompletionChunkChoice(
                    index=index, delta=ChatDelta(), finish_reason=reason_str
                )
            ],
        )

    def usage_chunk(self, usage: Usage) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id, created=self.created, model=self.model, choices=[], usage=usage
        )


class CompletionDeltaGenerator:
    """Builds the streaming chunk sequence for one text completion request."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or f"cmpl-{uuid.uuid4().hex}"
        self.model = model
        self.created = _now()

    def text_chunk(
        self,
        text: str,
        index: int = 0,
        logprobs: Optional[dict[str, Any]] = None,
    ) -> CompletionResponse:
        return CompletionResponse(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                CompletionChoice(index=index, text=text, logprobs=logprobs)
            ],
        )

    def finish_chunk(
        self, reason: FinishReason | str, index: int = 0
    ) -> CompletionResponse:
        reason_str = reason.value if isinstance(reason, FinishReason) else reason
        if reason_str in ("cancelled", "error"):
            reason_str = "stop"
        return CompletionResponse(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[CompletionChoice(index=index, text="", finish_reason=reason_str)],
        )

    def usage_chunk(self, usage: Usage) -> CompletionResponse:
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model, choices=[],
            usage=usage,
        )
