"""Server-Sent Events codec.

Analogue of the reference's SSE codec (lib/llm/src/protocols/codec.rs:36-120):
encode ``Annotated`` items to SSE wire lines and incrementally parse SSE
byte streams back into messages. Used by the HTTP service (encode) and by
clients/recorders (decode).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

DONE_SENTINEL = "[DONE]"


@dataclass
class SseMessage:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: list[str] = field(default_factory=list)
    retry: Optional[int] = None

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE_SENTINEL

    def json(self) -> Any:
        if self.data is None:
            return None
        return json.loads(self.data)


def encode_sse(
    data: Any = None,
    event: Optional[str] = None,
    id: Optional[str] = None,
    comments: Optional[list[str]] = None,
) -> str:
    """Encode one SSE message. ``data`` may be a str or a JSON-serializable
    object (dumped compactly)."""
    lines: list[str] = []
    for c in comments or []:
        for ln in str(c).splitlines() or [""]:
            lines.append(f": {ln}")
    if id is not None:
        lines.append(f"id: {id}")
    if event is not None:
        lines.append(f"event: {event}")
    if data is not None:
        if not isinstance(data, str):
            data = json.dumps(data, separators=(",", ":"))
        for ln in data.splitlines() or [""]:
            lines.append(f"data: {ln}")
    return "\n".join(lines) + "\n\n"


def encode_done() -> str:
    return f"data: {DONE_SENTINEL}\n\n"


class SseDecoder:
    """Incremental SSE parser: feed bytes/str, yields SseMessages."""

    def __init__(self) -> None:
        self._buf = ""
        self._cur = SseMessage()
        self._data_lines: list[str] = []

    def feed(self, chunk: bytes | str) -> Iterator[SseMessage]:
        if isinstance(chunk, bytes):
            chunk = chunk.decode("utf-8", errors="replace")
        self._buf += chunk
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.rstrip("\r")
            msg = self._feed_line(line)
            if msg is not None:
                yield msg

    def _feed_line(self, line: str) -> Optional[SseMessage]:
        if line == "":
            # dispatch event if non-empty
            if self._data_lines or self._cur.event or self._cur.comments or self._cur.id:
                msg = self._cur
                msg.data = "\n".join(self._data_lines) if self._data_lines else None
                self._cur = SseMessage()
                self._data_lines = []
                return msg
            return None
        if line.startswith(":"):
            self._cur.comments.append(line[1:].lstrip(" "))
            return None
        if ":" in line:
            name, _, value = line.partition(":")
            value = value[1:] if value.startswith(" ") else value
        else:
            name, value = line, ""
        if name == "data":
            self._data_lines.append(value)
        elif name == "event":
            self._cur.event = value
        elif name == "id":
            self._cur.id = value
        elif name == "retry":
            try:
                self._cur.retry = int(value)
            except ValueError:
                pass
        return None
