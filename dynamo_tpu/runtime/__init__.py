"""Distributed runtime: component model, streaming engines, routers.

TPU-native analogue of the reference's Rust runtime crate
(reference: lib/runtime/src — Runtime/DistributedRuntime, Namespace→
Component→Endpoint, AsyncEngine, PushRouter, transports). Differences by
design:

- Control plane is the self-hosted coordinator (`dynamo_tpu.store`), not
  external etcd+NATS.
- The request plane is a **direct TCP connection to the worker** with
  multiplexed response streams — one hop, instead of the reference's
  NATS-request + worker-dials-back-TCP two-hop design
  (reference: lib/runtime/src/pipeline/network/egress/addressed_router.rs).
  Discovery/liveness still flows through store leases exactly like etcd.
"""

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.runtime.runtime import DistributedRuntime, Runtime

__all__ = [
    "AsyncEngine",
    "Context",
    "DistributedRuntime",
    "EngineStream",
    "Runtime",
]
