"""Namespace → Component → Endpoint hierarchy + discovery-backed clients.

Analogue of the reference's component model (reference:
lib/runtime/src/component.rs:106-360, component/client.rs:1-197).

Store layout (≈ the reference's etcd path scheme, component.rs:153-155):

  instances/{namespace}/{component}/{endpoint}:{lease_id_hex}
      → msgpack {host, port, instance_id}

Event subjects (≈ NATS subject scheme, component.rs:281-292):

  {namespace}.{component}.{event_name}
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

import msgpack

from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.store.base import Subscription, WatchEvent
from dynamo_tpu.telemetry.instruments import WATCH_RESTARTS
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.runtime.component")

DYN_SCHEME = "dyn://"


def parse_dyn_path(value: str) -> tuple[str, str, str]:
    """Parse dyn://namespace.component.endpoint
    (reference: lib/runtime/src/protocols.rs Endpoint path parsing)."""
    if not value.startswith(DYN_SCHEME):
        raise ValueError(f"expected {DYN_SCHEME} prefix: {value!r}")
    parts = value[len(DYN_SCHEME) :].split(".")
    if len(parts) != 3 or not all(parts):
        raise ValueError(
            f"expected dyn://namespace.component.endpoint, got {value!r}"
        )
    return parts[0], parts[1], parts[2]

INSTANCE_PREFIX = "instances"


@dataclass(frozen=True)
class Instance:
    """A live serving instance of an endpoint."""

    instance_id: int  # == lease id, as in the reference
    host: str
    port: int
    namespace: str
    component: str
    endpoint: str
    # graceful drain (docs/robustness.md): a draining instance stays in
    # the view (in-flight dials keep working) but is excluded from
    # fresh placement the moment the flag lands — no lease-TTL wait
    draining: bool = False

    @property
    def path(self) -> str:
        return (
            f"{INSTANCE_PREFIX}/{self.namespace}/{self.component}/"
            f"{self.endpoint}:{self.instance_id:x}"
        )


class Namespace:
    def __init__(self, drt: DistributedRuntime, name: str):
        if "/" in name or "." in name:
            raise ValueError(f"invalid namespace name: {name!r}")
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    # -- namespace-scoped events (≈ traits/events.rs) ---------------------
    async def publish(self, event_name: str, payload: Any) -> None:
        await self.drt.store.publish(
            f"{self.name}.{event_name}", msgpack.packb(payload, use_bin_type=True)
        )

    async def subscribe(self, event_name: str) -> "EventSubscriber":
        sub = await self.drt.store.subscribe(f"{self.name}.{event_name}")
        return EventSubscriber(sub)


class Component:
    def __init__(self, namespace: Namespace, name: str):
        if "/" in name or "." in name:
            raise ValueError(f"invalid component name: {name!r}")
        self.namespace = namespace
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.namespace.drt

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    @property
    def path(self) -> str:
        return f"{self.namespace.name}/{self.name}"

    # -- component-scoped events ------------------------------------------
    def event_subject(self, event_name: str) -> str:
        return f"{self.namespace.name}.{self.name}.{event_name}"

    async def publish(self, event_name: str, payload: Any) -> None:
        await self.drt.store.publish(
            self.event_subject(event_name), msgpack.packb(payload, use_bin_type=True)
        )

    async def subscribe(self, event_name: str) -> "EventSubscriber":
        sub = await self.drt.store.subscribe(self.event_subject(event_name))
        return EventSubscriber(sub)

    async def list_instances(self) -> list[Instance]:
        prefix = f"{INSTANCE_PREFIX}/{self.path}/"
        entries = await self.drt.store.kv_get_prefix(prefix)
        return [_decode_instance(e.key, e.value) for e in entries]


class EventSubscriber:
    def __init__(self, sub: Subscription):
        self._sub = sub

    def __aiter__(self) -> AsyncIterator[tuple[str, Any]]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[tuple[str, Any]]:
        async for subject, payload in self._sub:
            yield subject, msgpack.unpackb(payload, raw=False)

    async def close(self) -> None:
        await self._sub.close()


def _decode_instance(key: str, value: bytes) -> Instance:
    # key: instances/{ns}/{comp}/{ep}:{lease_hex}
    meta = msgpack.unpackb(value, raw=False)
    rest = key[len(INSTANCE_PREFIX) + 1 :]
    ns, comp, ep_lease = rest.split("/", 2)
    ep, _, lease_hex = ep_lease.rpartition(":")
    return Instance(
        instance_id=int(lease_hex, 16),
        host=meta["host"],
        port=meta["port"],
        namespace=ns,
        component=comp,
        endpoint=ep,
        draining=bool(meta.get("draining", False)),
    )


class Endpoint:
    def __init__(self, component: Component, name: str):
        if "/" in name or "." in name or ":" in name:
            raise ValueError(f"invalid endpoint name: {name!r}")
        self.component = component
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.component.drt

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    def instance_path(self, lease_id: int) -> str:
        return f"{INSTANCE_PREFIX}/{self.path}:{lease_id:x}"

    # -- serving ----------------------------------------------------------
    async def serve(
        self, engine: AsyncEngine, lease_id: Optional[int] = None
    ) -> Instance:
        """Register this engine on the shared worker TCP server and publish
        the instance in the store, attached to the (primary) lease.

        (reference: component/endpoint.rs serve + etcd registration)
        """
        drt = self.drt
        server = await drt.ensure_endpoint_server()
        server.register(self.path, engine)
        lid = lease_id if lease_id is not None else drt.primary_lease_id
        instance = Instance(
            instance_id=lid,
            host=drt.config.advertise_host,
            port=server.port,
            namespace=self.component.namespace.name,
            component=self.component.name,
            endpoint=self.name,
        )
        payload = msgpack.packb(
            {"host": instance.host, "port": instance.port}, use_bin_type=True
        )
        created = await drt.store.kv_create(instance.path, payload, lease_id=lid)
        if not created:
            await drt.store.kv_put(instance.path, payload, lease_id=lid)
        log.info("serving %s as instance %x on port %d", self.path, lid, server.port)
        return instance

    async def set_draining(self, instance: Instance) -> None:
        """Publish the DRAINING flag by rewriting the instance's
        discovery entry in place (same key, same lease): every watching
        Client sees the put immediately and drops the instance from
        fresh placement while keeping its address dialable for
        in-flight streams (docs/robustness.md "Graceful drain")."""
        payload = msgpack.packb(
            {"host": instance.host, "port": instance.port, "draining": True},
            use_bin_type=True,
        )
        await self.drt.store.kv_put(
            instance.path, payload, lease_id=instance.instance_id
        )

    # -- client -----------------------------------------------------------
    async def client(self, static_instance: Optional[Instance] = None) -> "Client":
        c = Client(self, static_instance=static_instance)
        if static_instance is None:
            await c._start_watch()
        return c


class Client:
    """Endpoint client: watches discovery, issues streaming requests.

    (reference: component/client.rs — etcd-watched instance list;
    pipeline/network/egress/push_router.rs for selection modes, which live
    in push_router.py on top of this.)
    """

    def __init__(self, endpoint: Endpoint, static_instance: Optional[Instance] = None):
        self.endpoint = endpoint
        self.instances: dict[int, Instance] = {}
        if static_instance is not None:
            self.instances[static_instance.instance_id] = static_instance
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_event = asyncio.Event()
        self._closed = False
        if static_instance is not None:
            self._instances_event.set()

    async def _start_watch(self) -> None:
        prefix = f"{INSTANCE_PREFIX}/{self.endpoint.path}:"
        self._watch = await self.endpoint.drt.store.watch_prefix(prefix)
        for entry in self._watch.snapshot():
            inst = _decode_instance(entry.key, entry.value)
            self.instances[inst.instance_id] = inst
        self._refresh_event()
        self._watch_task = asyncio.get_running_loop().create_task(self._watch_loop())

    async def _watch_loop(self) -> None:
        """Apply discovery events; on watch death (store restart/blip)
        resubscribe with capped backoff + jitter and resync from the
        fresh snapshot — a frozen instance view would keep routing to
        dead workers and never see new ones."""
        assert self._watch is not None
        prefix = f"{INSTANCE_PREFIX}/{self.endpoint.path}:"
        backoff = Backoff(base_s=0.5, cap_s=30.0)
        while not self._closed:
            try:
                async for ev in self._watch:
                    self._apply(ev)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("instance watch died; resubscribing")
            if self._closed:
                return
            WATCH_RESTARTS.labels("instances").inc()
            await backoff.sleep()
            try:
                self._watch = await self.endpoint.drt.store.watch_prefix(prefix)
            except Exception:
                log.warning("instance watch resubscribe failed; retrying",
                            exc_info=True)
                continue
            backoff.reset()
            try:
                fresh = {}
                for entry in self._watch.snapshot():
                    try:
                        inst = _decode_instance(entry.key, entry.value)
                    except Exception:
                        # one malformed entry must not re-freeze the view
                        log.exception("bad instance entry in resync: %s",
                                      entry.key)
                        continue
                    fresh[inst.instance_id] = inst
                self.instances.clear()
                self.instances.update(fresh)
                self._refresh_event()
                log.info("instance watch resubscribed (%d live)", len(fresh))
            except Exception:
                log.exception("instance view resync failed; watch continues")

    def _apply(self, ev: WatchEvent) -> None:
        if ev.type == "put":
            inst = _decode_instance(ev.entry.key, ev.entry.value)
            self.instances[inst.instance_id] = inst
        elif ev.type == "delete":
            _, _, lease_hex = ev.entry.key.rpartition(":")
            try:
                self.instances.pop(int(lease_hex, 16), None)
            except ValueError:
                pass
        self._refresh_event()

    def _refresh_event(self) -> None:
        """The readiness event tracks ROUTABLE (non-draining) instances:
        waiters must not unblock onto a fleet that is all on its way
        out."""
        if any(not i.draining for i in self.instances.values()):
            self._instances_event.set()
        else:
            self._instances_event.clear()

    def instance_ids(self, include_draining: bool = False) -> list[int]:
        """Instances eligible for FRESH placement. Draining instances
        are excluded by default — both routers AND the resume path pick
        from this list, so a resume can never land on a worker that is
        itself on the way out. ``include_draining=True`` returns the
        full dialable view (in-flight work, kv-index pruning)."""
        if include_draining:
            return sorted(self.instances)
        return sorted(
            i for i, inst in self.instances.items() if not inst.draining
        )

    def draining_ids(self) -> set[int]:
        return {
            i for i, inst in self.instances.items() if inst.draining
        }

    async def wait_for_instances(
        self, timeout_s: Optional[float] = None
    ) -> list[int]:
        """Block until at least one instance is live
        (reference: client.wait_for_endpoints).

        The wait is event-driven (the store-prefix watch sets
        ``_instances_event``), so the budget is pure failure detection:
        None = DYN_DISCOVERY_TIMEOUT env (default 300 s) — wide enough
        that a worker JIT-compiling its model on a loaded machine isn't
        declared dead (the r3/r4 full-suite flakes were exactly this:
        30 s budgets expiring while a healthy worker compiled)."""
        if timeout_s is None:
            timeout_s = float(os.environ.get("DYN_DISCOVERY_TIMEOUT", "300"))
        await asyncio.wait_for(self._instances_event.wait(), timeout_s)
        return self.instance_ids()

    async def generate_direct(
        self, instance_id: int, payload: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        """Stream from one specific instance."""
        inst = self.instances.get(instance_id)
        if inst is None:
            raise KeyError(f"instance {instance_id:x} not found for {self.endpoint.path}")
        pool = self.endpoint.drt.connection_pool
        try:
            conn = await pool.get(inst.host, inst.port)
            return await conn.request(self.endpoint.path, payload, context)
        except (OSError, asyncio.TimeoutError) as exc:
            # OSError covers ConnectionError plus EHOSTUNREACH/ETIMEDOUT etc.
            pool.invalidate(inst.host, inst.port)
            if isinstance(exc, ConnectionError):
                raise
            raise ConnectionError(str(exc)) from exc

    async def close(self) -> None:
        self._closed = True
        if self._watch_task is not None:
            self._watch_task.cancel()
        if self._watch is not None:
            await self._watch.close()
