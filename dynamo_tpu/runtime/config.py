"""Layered runtime configuration from environment variables.

Analogue of the reference's Figment-based config
(reference: lib/runtime/src/config.rs:26-177 — DYN_RUNTIME_*/DYN_WORKER_*
env + TOML). Here: dataclass defaults ← optional JSON/TOML file
(DYN_CONFIG_PATH) ← DYN_* env vars, later layers win.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class RuntimeConfig:
    # coordinator store location
    store_host: str = "127.0.0.1"
    store_port: int = 4222
    # run without a coordinator: single-process in-memory store
    static: bool = False
    # worker data-plane bind
    worker_host: str = "0.0.0.0"
    # host other processes should use to reach this worker
    advertise_host: str = "127.0.0.1"
    worker_port: int = 0  # 0 = ephemeral
    lease_ttl_s: float = 10.0
    lease_keepalive_s: float = 3.0
    request_timeout_s: float = 600.0
    log_level: str = "INFO"
    log_jsonl: bool = False

    ENV_PREFIX = "DYN_"

    @classmethod
    def from_settings(cls, **overrides: Any) -> "RuntimeConfig":
        values: dict[str, Any] = {}
        path = os.environ.get("DYN_CONFIG_PATH")
        if path and os.path.exists(path):
            with open(path) as f:
                if path.endswith(".toml"):
                    import tomllib

                    values.update(tomllib.loads(f.read()))
                else:
                    values.update(json.load(f))
        for f_ in dataclasses.fields(cls):
            env_key = cls.ENV_PREFIX + f_.name.upper()
            raw: Optional[str] = os.environ.get(env_key)
            if raw is None:
                continue
            if f_.type in ("int", int):
                values[f_.name] = int(raw)
            elif f_.type in ("float", float):
                values[f_.name] = float(raw)
            elif f_.type in ("bool", bool):
                values[f_.name] = raw.lower() in ("1", "true", "yes", "on")
            else:
                values[f_.name] = raw
        known = {f_.name for f_ in dataclasses.fields(cls)}
        values = {k: v for k, v in values.items() if k in known}
        values.update(overrides)
        return cls(**values)
