"""Graceful worker drain: planned departure with proactive handoff.

A kill (docs/robustness.md "Mid-stream migration") is *reactive*: the
router discovers the death from a broken stream, synthesizes the lost
finish, and replays from its commit log. A drain is *planned* — the
worker is still healthy — so the departure can be made invisible:

1. publish the DRAINING flag (the instance's discovery entry is
   rewritten in place on the same key/lease, so every watching Client
   drops it from fresh placement *immediately* instead of waiting out
   the lease TTL, while in-flight dials stay alive),
2. retier hot KV-fabric prefixes into the shared bucket so the blocks
   outlive the process and resumes onboard instead of recomputing,
3. hand off every migratable in-flight stream at a step boundary:
   the engine finishes it with ``FinishReason.MIGRATE``, which the
   router loop (runtime/migration.py) consumes — never surfacing it to
   the client — and re-dispatches as a resume with an EXACT commit log
   (every generated token was already emitted; nothing to synthesize),
4. wait for the engine to idle under ``--drain-timeout-s``; streams
   that can't migrate (guided, penalties, opted out) get the window to
   finish naturally, and past the deadline the worker exits anyway and
   the reactive machinery catches whatever is left,
5. deregister (delete the instance key) and let the process exit 0.

``worker.drain`` / ``store.publish_drain`` fault points (faults/
injector.py) hook the handoff and the flag publish so chaos runs can
exercise the deadline fallback.

The control side — ``dynamo-tpu drain <worker>`` and the planner's
scale-down — publishes ``{"op": "drain", "instance": "<hex>"}`` on the
namespace's worker-control subject; the worker's listener converges
that onto the same SIGTERM shutdown path.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Optional

from dynamo_tpu import faults
from dynamo_tpu.runtime.component import INSTANCE_PREFIX

log = logging.getLogger("dynamo_tpu.drain")

DEFAULT_DRAIN_TIMEOUT_S = 30.0

#: per-namespace pub/sub subject workers listen on for lifecycle ops
WORKER_CONTROL_SUBJECT = "worker.control"


def worker_control_subject(namespace: str) -> str:
    return f"{namespace}.{WORKER_CONTROL_SUBJECT}"


def drain_timeout_from_env(default: float = DEFAULT_DRAIN_TIMEOUT_S) -> float:
    try:
        return float(os.environ.get("DYN_DRAIN_TIMEOUT_S", default))
    except ValueError:
        return default


@dataclass
class DrainResult:
    #: "completed" (idle before deadline) | "deadline" (streams left;
    #: reactive fallback catches them) | "no_peer" (nowhere to hand
    #: off; served out the window instead of migrating)
    result: str
    streams_migrated: int
    elapsed_s: float
    fabric_blocks_shared: int = 0


class DrainCoordinator:
    """Runs the drain sequence for one serving worker.

    Built in worker mode (cli/main.py) next to ``endpoint.serve``;
    ``drain()`` runs after ``wait_shutdown()`` returns — whether that
    was SIGTERM, a ``worker.drain`` control call, or Ctrl-C — and
    before ``drt.shutdown()`` revokes the lease.
    """

    def __init__(
        self,
        drt: Any,
        component: Any,
        endpoint: Any,
        instance: Any,
        engine: Any = None,
        timeout_s: Optional[float] = None,
        poll_interval_s: float = 0.05,
    ):
        self.drt = drt
        self.component = component
        self.endpoint = endpoint
        self.instance = instance
        self.engine = engine
        self.timeout_s = (
            timeout_s if timeout_s is not None else drain_timeout_from_env()
        )
        self.poll_interval_s = poll_interval_s

    async def drain(self) -> DrainResult:
        from dynamo_tpu.telemetry.instruments import (
            DRAIN_HANDOFF_SECONDS,
            DRAIN_STREAMS_MIGRATED,
            WORKER_DRAINS,
        )

        t0 = time.monotonic()
        deadline = t0 + self.timeout_s
        migrated_before = (
            self.engine.drain_migrated if self.engine is not None else 0
        )

        # 1. DRAINING flag — routers stop fresh placement immediately.
        # A failed publish (store down, injected fault) degrades to the
        # lease-TTL path the reactive machinery already covers; the
        # drain itself proceeds.
        try:
            if faults.ACTIVE is not None:
                await faults.ACTIVE.fire_async(
                    "store.publish_drain",
                    instance=f"{self.instance.instance_id:x}",
                )
            await self.endpoint.set_draining(self.instance)
        except Exception as exc:
            log.warning(
                "drain: DRAINING publish failed (%s); routers will "
                "learn from lease expiry instead", exc,
            )

        # 2. KV fabric: push hot prefixes into the shared bucket so the
        # resumes this drain is about to hand off onboard cheaply on
        # the peer (and survive our exit). Fabric is engine-thread
        # affine; call_on_thread work drains even while draining.
        blocks_shared = 0
        fabric = self._fabric()
        if fabric is not None and self.engine is not None:
            try:
                blocks_shared = await asyncio.wait_for(
                    self.engine.acall_on_thread(fabric.on_drain),
                    timeout=max(1.0, self.timeout_s / 3),
                )
            except Exception as exc:
                log.warning("drain: fabric handoff skipped: %s", exc)

        # 3. Peer check: with no healthy non-draining peer there is
        # nobody to migrate onto — MIGRATE handoffs would only bounce.
        # Serve out the window instead and let the deadline cap it.
        has_peer = await self._has_healthy_peer()

        result = "completed"
        if self.engine is not None:
            active0 = self.engine.active_streams()
            if has_peer:
                try:
                    if faults.ACTIVE is not None:
                        await faults.ACTIVE.fire_async(
                            "worker.drain",
                            instance=f"{self.instance.instance_id:x}",
                        )
                    self.engine.begin_drain()
                except Exception as exc:
                    # injected stall/error in the handoff: skip the
                    # proactive sweep — the deadline fallback (and the
                    # routers' reactive resume after exit) take over
                    log.warning("drain: proactive handoff failed: %s", exc)
                    result = "deadline"
            if not await self._wait_idle(deadline):
                result = "deadline"
            if not has_peer and active0 > 0:
                # streams were live with nowhere to hand them: the
                # window served what it could, the rest is on the
                # reactive path — distinct failure mode for operators
                result = "no_peer"

        migrated = (
            self.engine.drain_migrated - migrated_before
            if self.engine is not None
            else 0
        )
        elapsed = time.monotonic() - t0

        WORKER_DRAINS.labels(result).inc()
        DRAIN_HANDOFF_SECONDS.observe(elapsed)
        if migrated:
            DRAIN_STREAMS_MIGRATED.inc(migrated)

        # 5. Deregister: the watchers see a delete (not a TTL lapse) so
        # the instance disappears the moment we stop serving.
        try:
            await self.drt.store.kv_delete(self.instance.path)
        except Exception as exc:
            log.warning("drain: deregister failed (%s); lease revoke "
                        "at shutdown cleans up", exc)

        log.info(
            "drain %s: %s in %.2fs (%d stream(s) migrated, %d block(s) "
            "to shared)", f"{self.instance.instance_id:x}", result,
            elapsed, migrated, blocks_shared,
        )
        return DrainResult(
            result=result,
            streams_migrated=migrated,
            elapsed_s=elapsed,
            fabric_blocks_shared=blocks_shared,
        )

    def _fabric(self) -> Any:
        eng = self.engine
        kvbm = getattr(eng, "kvbm", None) if eng is not None else None
        return getattr(kvbm, "fabric", None) if kvbm is not None else None

    async def _has_healthy_peer(self) -> bool:
        try:
            instances = await self.component.list_instances()
        except Exception as exc:
            log.warning("drain: peer listing failed: %s", exc)
            return False
        me = self.instance.instance_id
        return any(
            i.instance_id != me and not i.draining for i in instances
        )

    async def _wait_idle(self, deadline: float) -> bool:
        """Poll the engine toward zero attached streams. True = idle."""
        assert self.engine is not None
        while True:
            if self.engine.active_streams() == 0:
                return True
            if time.monotonic() >= deadline:
                log.warning(
                    "drain deadline: %d stream(s) still active; the "
                    "reactive resume path takes over after exit",
                    self.engine.active_streams(),
                )
                return False
            await asyncio.sleep(self.poll_interval_s)


async def serve_drain_control(
    drt: Any, namespace: str, instance: Any, runtime: Any
) -> None:
    """Worker-side listener for ``worker.drain`` control calls.

    A matching ``{"op": "drain", "instance": "<hex>"}`` (or one with no
    instance — "drain whoever hears this") converges onto the SIGTERM
    path by setting the runtime shutdown event; worker mode then runs
    the DrainCoordinator before exiting. Acks on ``reply_to`` when the
    caller asked for one.
    """
    sub = await drt.store.subscribe(worker_control_subject(namespace))
    me = f"{instance.instance_id:x}"
    async for _subject, payload in sub:
        try:
            cmd = json.loads(payload.decode())
        except Exception:
            log.warning("malformed worker-control payload: %r", payload[:80])
            continue
        if cmd.get("op") != "drain":
            continue
        target = cmd.get("instance")
        if target is not None and str(target).lower() != me:
            continue
        log.info("drain requested via control call")
        reply_to = cmd.get("reply_to")
        if reply_to:
            try:
                await drt.store.publish(
                    reply_to,
                    json.dumps({"ok": True, "instance": me}).encode(),
                )
            except Exception:
                pass
        runtime.shutdown()


async def request_drain(
    store: Any,
    namespace: str,
    instance_hex: str,
    timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S + 15.0,
    poll_interval_s: float = 0.25,
) -> bool:
    """Client side of ``dynamo-tpu drain <worker>`` / planner scale-down:
    publish the control call, then poll discovery until the instance
    key disappears (the worker deletes it as its last act). True iff
    the worker departed within ``timeout_s``."""
    target = instance_hex.lower().lstrip("0x") or "0"
    await store.publish(
        worker_control_subject(namespace),
        json.dumps({"op": "drain", "instance": target}).encode(),
    )
    suffix = f":{target}"
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        entries = await store.kv_get_prefix(
            f"{INSTANCE_PREFIX}/{namespace}/"
        )
        if not any(e.key.endswith(suffix) for e in entries):
            return True
        await asyncio.sleep(poll_interval_s)
    return False
