"""The universal streaming-engine abstraction.

Analogue of the reference's AsyncEngine trait + AsyncEngineContext
(reference: lib/runtime/src/engine.rs:47-168): every unit of work in the
system — preprocessors, routers, model engines — is "a thing that takes one
request and returns a stream of responses", with per-request cancellation
(graceful ``stop`` vs immediate ``kill``).
"""

from __future__ import annotations

import abc
import asyncio
import time
import uuid
from typing import Any, AsyncIterator, Awaitable, Callable, Generic, Optional, TypeVar

Req = TypeVar("Req")
Resp = TypeVar("Resp")


class Context:
    """Per-request control: id + cooperative cancellation + trace link.

    ``stop`` asks the producer to finish gracefully (emit what it has);
    ``kill`` demands immediate termination (reference: engine.rs
    AsyncEngineContext stop_generating/kill).

    ``trace_id``/``span_id`` carry the request's trace context through
    component calls (and across the wire — runtime/service.py ships them
    in the ``ctx`` frame): ``span_id`` is the currently-active parent
    span downstream spans should attach to. Both stay None when tracing
    is off, so the fields are pure baggage on the hot path.
    """

    def __init__(
        self,
        id: Optional[str] = None,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
    ):
        self.id = id or uuid.uuid4().hex
        self.trace_id = trace_id
        self.span_id = span_id
        # request deadline (docs/robustness.md): a time.monotonic()
        # instant, or None for no budget. The REMAINING budget rides
        # the wire (runtime/service.py ships deadline_ms; the receiver
        # re-anchors to its own clock), so cross-process propagation
        # never compares wall clocks.
        self.deadline: Optional[float] = None
        # None = no sampling decision seen; False = the trace head
        # explicitly sampled this request OUT — downstream tracers must
        # not start fresh roots for it (the mark rides the wire)
        self.trace_sampled: Optional[bool] = None
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()

    def trace_context(self) -> Optional[dict]:
        """Propagation dict for the wire / telemetry spans, or None.
        A negative sampling decision propagates as ``{"sampled": False}``
        so one head decision governs the whole distributed trace."""
        if self.trace_sampled is False:
            return {"sampled": False}
        if self.trace_id is None:
            return None
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def set_trace(self, span: Any) -> None:
        """Adopt ``span`` (a telemetry Span or trace-context dict) as the
        parent for downstream work. No-op for null/disabled spans."""
        ctx = span if isinstance(span, dict) else getattr(
            span, "trace_context", lambda: None
        )()
        if ctx and ctx.get("sampled") is False:
            self.trace_sampled = False
        elif ctx and ctx.get("trace_id"):
            self.trace_id = ctx["trace_id"]
            self.span_id = ctx.get("span_id")
            self.trace_sampled = True

    def set_deadline_ms(self, budget_ms: Optional[float]) -> None:
        """Arm (or clear, with None) a deadline ``budget_ms`` from now."""
        self.deadline = (
            time.monotonic() + budget_ms / 1e3
            if budget_ms is not None else None
        )

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds of budget left (None = no deadline; >= 0.0)."""
        if self.deadline is None:
            return None
        return max(0.0, (self.deadline - time.monotonic()) * 1e3)

    @property
    def is_expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline

    def stop_generating(self) -> None:
        self._stop.set()

    def kill(self) -> None:
        self._stop.set()
        self._kill.set()

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def is_killed(self) -> bool:
        return self._kill.is_set()

    async def wait_stopped(self) -> None:
        await self._stop.wait()

    def child(self) -> "Context":
        """A linked context sharing cancellation with this one."""
        c = Context(id=self.id, trace_id=self.trace_id, span_id=self.span_id)
        c.trace_sampled = self.trace_sampled
        c.deadline = self.deadline
        c._stop = self._stop
        c._kill = self._kill
        return c


EngineStream = AsyncIterator[Resp]


class AsyncEngine(abc.ABC, Generic[Req, Resp]):
    """A streaming engine: one request in, an async stream of responses out."""

    @abc.abstractmethod
    def generate(self, request: Req, context: Context) -> EngineStream:
        """Returns an async iterator of responses. Implementations should
        poll ``context.is_stopped`` between items and terminate early."""


class FnEngine(AsyncEngine[Req, Resp]):
    """Wrap an async-generator function as an engine (test/mock helper;
    ≈ reference tests/common/engines.rs LambdaEngine)."""

    def __init__(
        self, fn: Callable[[Req, Context], AsyncIterator[Resp]], name: str = "fn"
    ):
        self._fn = fn
        self.name = name

    def generate(self, request: Req, context: Context) -> EngineStream:
        return self._fn(request, context)


class UnaryFnEngine(AsyncEngine[Req, Resp]):
    """Wrap a plain async function returning one response."""

    def __init__(self, fn: Callable[[Req, Context], Awaitable[Resp]]):
        self._fn = fn

    async def _gen(self, request: Req, context: Context) -> AsyncIterator[Resp]:
        yield await self._fn(request, context)

    def generate(self, request: Req, context: Context) -> EngineStream:
        return self._gen(request, context)


async def collect(stream: EngineStream) -> list[Any]:
    """Drain a stream into a list (test helper)."""
    return [item async for item in stream]
