"""Logging setup: console or JSONL structured logs.

Analogue of the reference's tracing-subscriber init
(reference: lib/runtime/src/logging.rs:20-344 — env-filter levels,
DYN_LOGGING_JSONL structured output).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def init_logging(level: str | None = None, jsonl: bool | None = None) -> None:
    level = level or os.environ.get("DYN_LOG_LEVEL", "INFO")
    if jsonl is None:
        jsonl = os.environ.get("DYN_LOGGING_JSONL", "").lower() in ("1", "true")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)-7s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
