"""Logging setup: env-filtered console/JSONL structured logs.

Analogue of the reference's tracing-subscriber init
(reference: lib/runtime/src/logging.rs:20-344):

- ``DYN_LOG_LEVEL`` accepts an env-filter string — a default level plus
  per-target overrides, e.g. ``info,dynamo_tpu.engine=debug,aiohttp=warning``
  (same shape as Rust's ``RUST_LOG``/EnvFilter the reference uses).
- ``DYN_LOGGING_JSONL=1`` switches to one-JSON-object-per-line output.
- ``DYN_LOGGING_CONFIG_PATH`` points at a TOML or JSON config file with
  keys ``level``, ``jsonl``, ``file``, ``local_tz`` (reference:
  logging.rs TOML config via the same env var).
- ``DYN_LOG_FILE`` appends to a file instead of stderr.
- ``DYN_LOGGING_LOCAL_TZ=1`` stamps local time instead of UTC
  (reference: logging.rs use_local_tz).

Precedence: explicit args > env vars > config file > defaults.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import sys
import time
from typing import Any, Optional

# -- per-request log correlation (ISSUE 2 satellite: logs, traces, and
# client reports join on one id) --------------------------------------------
# Set by the HTTP frontend for the lifetime of a request's handler task;
# contextvars follow the asyncio task, so concurrent requests don't
# cross-stamp. Records emitted from other threads (e.g. the jax-engine
# step thread) simply carry no request id.
_request_id_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dyn_request_id", default=None
)
_trace_id_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dyn_trace_id", default=None
)


def set_log_request_id(
    request_id: Optional[str], trace_id: Optional[str] = None
) -> None:
    """Stamp subsequent log records in this task with the request id
    (and optionally its trace id)."""
    _request_id_var.set(request_id)
    _trace_id_var.set(trace_id)


def current_log_request_id() -> Optional[str]:
    return _request_id_var.get()


class RequestIdFilter(logging.Filter):
    """Copies the contextvars onto each record: ``record.request_id`` /
    ``record.trace_id`` (None when outside a request), plus a preformatted
    ``record.rid_suffix`` for the plain-text formatter."""

    def filter(self, record: logging.LogRecord) -> bool:
        rid = _request_id_var.get()
        record.request_id = rid
        record.trace_id = _trace_id_var.get()
        record.rid_suffix = f" [rid={rid}]" if rid else ""
        return True


def parse_env_filter(spec: str) -> tuple[int, dict[str, int]]:
    """``"info,dynamo_tpu.engine=debug"`` -> (default level, per-target
    overrides). Unknown level names fall back to INFO."""

    def lvl(name: str) -> int:
        return getattr(logging, name.strip().upper(), logging.INFO)

    default = logging.INFO
    targets: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, _, name = part.partition("=")
            targets[target.strip()] = lvl(name)
        else:
            default = lvl(part)
    return default, targets


class JsonlFormatter(logging.Formatter):
    def __init__(self, local_tz: bool = False):
        super().__init__()
        self.local_tz = local_tz

    def format(self, record: logging.LogRecord) -> str:
        if self.local_tz:
            stamp = time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
            ) + f".{int(record.msecs):03d}"
        else:
            stamp = time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            ) + f".{int(record.msecs):03d}Z"
        out = {
            "ts": stamp,
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        # request/trace correlation (set by RequestIdFilter when the
        # record was emitted inside a request's task)
        if getattr(record, "request_id", None):
            out["request_id"] = record.request_id
        if getattr(record, "trace_id", None):
            out["trace_id"] = record.trace_id
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def _load_config_file(path: str) -> dict[str, Any]:
    try:
        if path.endswith(".toml"):
            try:
                import tomllib  # py311+
            except ImportError:
                # 3.10: the vendored tomli this environment ships. A
                # bare `import tomllib` here used to land in the broad
                # except below, silently IGNORING the whole config file
                # (tier-1 test_logging caught it).
                import tomli as tomllib  # type: ignore[no-redef]

            with open(path, "rb") as f:
                return tomllib.load(f)
        with open(path) as f:
            return json.load(f)
    except Exception as e:  # bad config must not take the process down
        print(f"dynamo-tpu: bad logging config {path}: {e}", file=sys.stderr)
        return {}


def init_logging(
    level: Optional[str] = None,
    jsonl: Optional[bool] = None,
    log_file: Optional[str] = None,
    local_tz: Optional[bool] = None,
) -> None:
    cfg: dict[str, Any] = {}
    cfg_path = os.environ.get("DYN_LOGGING_CONFIG_PATH")
    if cfg_path:
        cfg = _load_config_file(cfg_path)

    def env_bool(name: str) -> Optional[bool]:
        v = os.environ.get(name)
        if v is None:
            return None
        return v.lower() in ("1", "true", "yes")

    level = level or os.environ.get("DYN_LOG_LEVEL") or cfg.get("level") or "INFO"
    if jsonl is None:
        jsonl = env_bool("DYN_LOGGING_JSONL")
    if jsonl is None:
        jsonl = bool(cfg.get("jsonl", False))
    if log_file is None:
        log_file = os.environ.get("DYN_LOG_FILE") or cfg.get("file")
    if local_tz is None:
        local_tz = env_bool("DYN_LOGGING_LOCAL_TZ")
    if local_tz is None:
        local_tz = bool(cfg.get("local_tz", False))

    handler: logging.Handler
    if log_file:
        handler = logging.FileHandler(log_file)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.addFilter(RequestIdFilter())
    if jsonl:
        handler.setFormatter(JsonlFormatter(local_tz=local_tz))
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)-7s %(name)s: %(message)s%(rid_suffix)s",
                datefmt="%H:%M:%S",
            )
        )
    default, targets = parse_env_filter(str(level))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(default)
    # reset overrides from a previous init_logging: a re-init with a
    # plainer filter must not leave stale per-target levels pinned
    global _overridden_targets
    for stale in _overridden_targets - set(targets):
        logging.getLogger(stale).setLevel(logging.NOTSET)
    for target, lv in targets.items():
        logging.getLogger(target).setLevel(lv)
    _overridden_targets = set(targets)


_overridden_targets: set = set()
