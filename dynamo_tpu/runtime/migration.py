"""Mid-stream request migration: streams that survive worker death.

The shared failover/resume engine behind both routers
(``runtime/push_router.py PushRouter`` and ``kv_router/router.py
KvPushRouter``). PR 5 gave the routers pre-first-token failover and a
clean abort (``WorkerStreamLostError`` → SSE ``error``) once tokens had
streamed; this module turns that abort into the *fallback*: when a
worker dies after emitting tokens, the request is re-dispatched to a
surviving worker as a **resume** and the continuation is spliced into
the original stream (docs/robustness.md "Mid-stream migration").

Resume semantics (the contract the engine implements):

- the resume request's ``token_ids`` is the original prompt extended by
  every token already **delivered** to the client — the new worker
  prefills that prefix and generates the continuation from the exact
  splice point, so there is nothing to dedup: tokens the dead worker
  generated but never delivered are simply regenerated;
- ``stop.max_tokens`` (and ``min_tokens``) shrink by the delivered
  count so length accounting is seamless across the splice;
- ``resume_offset`` carries the delivered count into the engine's
  per-request RNG: the engine seeds step ``p`` of a sequence with
  ``base + generated + resume_offset``, so the continuation draws the
  SAME sample stream the original request would have at those positions
  — greedy output is bit-identical and seeded (or request-id-hashed)
  sampling is stream-consistent across the migration;
- requests using token-count penalties (frequency/presence/repetition)
  are NOT migratable: their penalty state counts *generated* tokens,
  which a resume would reclassify as prompt. They keep the PR-5 abort.
- ``usage``/``cum_log_probs`` on the continuation are re-anchored here
  (the resumed engine sees an extended prompt and counts only its own
  tokens), so upstream consumers observe one uninterrupted stream.

Resume attempts are deadline-clamped through the shared ``Backoff``;
``dynamo_midstream_resumes_total{result}`` and
``dynamo_midstream_resume_seconds`` observe every splice, and the
``router.resume`` fault point lets ``DYN_FAULTS`` kill the resume
itself (double fault → the abort fallback).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu import faults
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.service import ConnectionLostError
from dynamo_tpu.telemetry import autopsy
from dynamo_tpu.telemetry.instruments import (
    FAILOVER_RETRIES,
    MIDSTREAM_ABORTS,
    MIDSTREAM_RESUMES,
    RESUME_SECONDS,
)
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.runtime.migration")

# faults/injector.py point: fired before every resume dispatch so chaos
# plans can fail (or kill) the migration machinery itself
FAULT_POINT = "router.resume"


class WorkerStreamLostError(RuntimeError):
    """A worker died after streaming part of a response and the stream
    could not be resumed (migration disabled, opted out, ineligible, or
    every resume attempt exhausted). Carries a clean, client-presentable
    message; the HTTP layer renders it as an SSE ``error`` event."""


# A dial callable: (request, excluded instance ids, resume flag, bounded
# instance-wait budget or None) -> (instance id, response stream, segment
# cleanup callback or None). Router-specific: PushRouter picks by mode,
# KvPushRouter schedules KV-aware (cache-hot-biased for resumes).
Dial = Callable[
    [Any, set, bool, Optional[float]],
    Awaitable[tuple[int, AsyncIterator[Any], Optional[Callable[[], None]]]],
]


class DialFailedError(Exception):
    """A PICKED instance could not be dialed. Dial implementations wrap
    transport failures in this so the loop can exclude the dead
    instance before retrying — without it, a scheduler that
    deterministically prefers the dead worker would re-pick it until
    the whole attempt budget burned (the PR-5 routers excluded on dial
    failure; this preserves that)."""

    def __init__(self, instance_id: int, cause: BaseException):
        super().__init__(f"instance {instance_id:x}: {cause}")
        self.instance_id = instance_id
        self.__cause__ = cause

# failures that mean "this worker/attempt is gone, try another"
# (CancelledError is deliberately NOT here). Dial implementations wrap
# transport failures in DialFailedError; the instance wait raises
# asyncio.TimeoutError and an emptied candidate set raises
# RuntimeError. Anything else a dial raises is a programming/input bug
# and must crash at the fault, not burn retries as fake fleet
# unavailability. Stream iteration likewise retries only
# transport-shaped errors.
_DIAL_ERRORS = (DialFailedError, asyncio.TimeoutError, RuntimeError)
_STREAM_ERRORS = (ConnectionLostError, OSError, asyncio.TimeoutError, KeyError)


@dataclass
class MigrationConfig:
    """Mid-stream migration knobs (env-tunable; docs/robustness.md)."""

    enabled: bool = True
    # consecutive resume attempts without a spliced token before the
    # abort fallback (a splice that delivers tokens resets the budget)
    max_resumes: int = 3
    # per-attempt bound on waiting for a live instance: a resume must
    # fail fast toward the abort, not park on the 300 s discovery budget
    instance_wait_s: float = 5.0

    @classmethod
    def from_env(cls) -> "MigrationConfig":
        return cls(
            enabled=os.environ.get("DYN_MIGRATION", "1").strip().lower()
            not in ("0", "false", "off"),
            max_resumes=int(os.environ.get("DYN_MIGRATION_MAX_RESUMES", "3")),
            instance_wait_s=float(
                os.environ.get("DYN_MIGRATION_WAIT_S", "5.0")
            ),
        )


def _get(obj: Any, key: str, default: Any = None) -> Any:
    if isinstance(obj, dict):
        return obj.get(key, default)
    return getattr(obj, key, default)


def _set(obj: Any, key: str, value: Any) -> None:
    if isinstance(obj, dict):
        obj[key] = value
    else:
        setattr(obj, key, value)


def resumable(request: Any) -> bool:
    """Whether a request is eligible for mid-stream migration: it must
    be token-shaped (a PreprocessedRequest or wire dict), not opted out
    (``migration=False``), penalty-free (see module docstring), and not
    guided — a resume folds delivered tokens into token_ids with no
    prompt/generated boundary, so the guided automaton cursor could not
    be reconstructed on the new worker (docs/guided_decoding.md)."""
    token_ids = _get(request, "token_ids")
    if not isinstance(token_ids, list) or not token_ids:
        return False
    if _get(request, "migration") is False:
        return False
    if _get(request, "guided") is not None:
        return False
    sampling = _get(request, "sampling")
    if sampling is not None:
        needs = _get(sampling, "needs_penalties")
        if needs is None and isinstance(sampling, dict):
            # dict-shaped wire request: judge with the SAME predicate
            # the typed model defines, so the two can never drift
            from dynamo_tpu.protocols.common import SamplingOptions

            try:
                needs = SamplingOptions.model_validate(
                    sampling
                ).needs_penalties
            except Exception:
                return False  # unparseable sampling: don't risk it
        if needs:
            return False
    return True


class StreamProgress:
    """The commit log of one migratable stream: every token the client
    has received, plus the stitching state that makes a resumed
    continuation indistinguishable from the original stream."""

    def __init__(self, request: Any):
        self.request = request
        self.prompt_len = len(_get(request, "token_ids") or [])
        self.emitted: list[int] = []
        self.segments = 1
        # the finish chunk reached the client: the answer is complete,
        # a later transport death killed only the stream's trailing
        # frame and must NOT trigger a resume
        self.finished = False
        # cum_log_probs carried out of completed segments: the resumed
        # engine restarts its cumulation at 0 for the continuation
        self.cum_base: float = 0.0
        self._last_cum: Optional[float] = None
        self._dict_items = False  # shape of the last item seen

    def note(self, item: Any) -> Any:
        """Record one delivered item; re-anchors continuation items
        (cum_log_probs, final-chunk usage) onto the original request's
        frame of reference. Returns the (possibly adjusted) item."""
        self._dict_items = isinstance(item, dict)
        toks = _get(item, "token_ids") or []
        self.emitted.extend(toks)
        cum = _get(item, "cum_log_probs")
        if cum is not None:
            if self.segments > 1 and self.cum_base:
                cum = cum + self.cum_base
                _set(item, "cum_log_probs", cum)
            self._last_cum = cum
        if _get(item, "finish_reason") is not None:
            self.finished = True
            if self.segments > 1:
                if _get(item, "prompt_tokens") is not None:
                    _set(item, "prompt_tokens", self.prompt_len)
                if _get(item, "completion_tokens") is not None:
                    _set(item, "completion_tokens", len(self.emitted))
        return item

    def budget_left(self) -> Optional[int]:
        """Tokens of max_tokens budget the continuation may still emit
        (None = unbounded)."""
        stop = _get(self.request, "stop")
        mt = _get(stop, "max_tokens") if stop is not None else None
        if mt is None:
            return None
        return mt - len(self.emitted)

    def resume_request(self) -> Any:
        """The continuation request: prompt extended by every delivered
        token, length budgets shrunk, RNG offset advanced. Always built
        from the ORIGINAL request so repeated migrations compose."""
        req = self.request
        if hasattr(req, "model_copy"):
            r = req.model_copy(deep=True)
        else:
            r = copy.deepcopy(req)
        n = len(self.emitted)
        _set(
            r, "token_ids",
            list(_get(req, "token_ids")) + list(self.emitted),
        )
        stop = _get(r, "stop")
        if stop is not None:
            mt = _get(stop, "max_tokens")
            if mt is not None:
                _set(stop, "max_tokens", max(1, mt - n))
            mn = _get(stop, "min_tokens")
            if mn:
                _set(stop, "min_tokens", max(0, mn - n))
        out = _get(r, "output")
        if out is not None and _get(out, "echo"):
            # the echo (if any) already streamed with the first segment
            _set(out, "echo", False)
        base_off = _get(req, "resume_offset", 0) or 0
        _set(r, "resume_offset", base_off + n)
        self.cum_base = self._last_cum if self._last_cum is not None else 0.0
        self.segments += 1
        return r

    def synthesize_final(self, reason: str = "length") -> Any:
        """A final chunk for the edge where the worker died having
        delivered its entire token budget — only the finish marker was
        lost, so nothing remains to resume."""
        chunk = {
            "request_id": _get(self.request, "request_id", "") or "",
            "token_ids": [],
            "finish_reason": reason,
            "prompt_tokens": self.prompt_len,
            "completion_tokens": len(self.emitted),
        }
        if self._dict_items:
            return chunk
        from dynamo_tpu.protocols.common import LLMEngineOutput

        return LLMEngineOutput.model_validate(chunk)


async def deadline_backoff_sleep(backoff: Backoff, context: Context) -> None:
    """One failover/resume backoff, clamped to the request's remaining
    deadline budget; raises TimeoutError instead of retrying past the
    deadline. Shared by PushRouter and KvPushRouter."""
    delay = backoff.next_delay()
    remaining = context.remaining_ms()
    if remaining is not None:
        if remaining <= 0:
            raise asyncio.TimeoutError(
                "request deadline exceeded during failover"
            )
        delay = min(delay, remaining / 1e3)
    await asyncio.sleep(delay)


async def migrating_stream(
    request: Any,
    context: Context,
    dial: Dial,
    config: Optional[MigrationConfig] = None,
    *,
    admission: Any = None,
    span: Any = None,
    max_attempts: int = 3,
    backoff_base_s: float = 0.05,
    backoff_cap_s: float = 2.0,
    endpoint_name: str = "",
) -> AsyncIterator[Any]:
    """Stream a routed request with failover AND mid-stream migration.

    Phase 1 (pre-first-token) keeps PR-5 semantics: dial failures and
    streams that die with nothing delivered re-dispatch under
    ``max_attempts`` with backoff (``dynamo_failover_retries_total``).
    Once tokens have been delivered, a worker death triggers migration:
    the request is rebuilt as a resume (:class:`StreamProgress`) and
    re-dispatched; a successful splice resets the resume budget, so a
    long stream survives any number of *spaced* worker deaths.
    ``config.max_resumes`` consecutive no-progress attempts (or a
    request that is not :func:`resumable`) fall back to the PR-5 abort:
    ``dynamo_midstream_aborts_total`` + :class:`WorkerStreamLostError`.
    """
    cfg = config or MigrationConfig.from_env()
    exclude: set[int] = set()
    backoff = Backoff(base_s=backoff_base_s, cap_s=backoff_cap_s)
    if not cfg.enabled:
        progress, no_resume_why = None, "migration disabled"
    elif not resumable(request):
        progress, no_resume_why = None, "stream is not resumable"
    else:
        progress, no_resume_why = StreamProgress(request), ""
    cur_req = request
    started = False  # any item delivered upstream
    attempt = 0  # consecutive failures in the current phase
    death_t: Optional[float] = None  # first loss of the active migration
    death_instance: Optional[int] = None  # the worker that loss took
    resumes = 0
    # why the active migration window opened: "crash" (worker died under
    # us) vs "drain" (the worker handed the stream off on purpose) —
    # stamped on the resume_splice autopsy event
    loss_reason = "crash"

    def _abort(
        exc: Exception, detail: Optional[str] = None
    ) -> WorkerStreamLostError:
        MIDSTREAM_ABORTS.inc()
        if span:
            span.set_attr("midstream_abort", True)
        if detail is None:
            detail = no_resume_why or "resume attempts exhausted"
        # autopsy: a worker death that migration could NOT save —
        # flagged so the exemplar survives tail retention
        autopsy.note_event(
            context.id, "midstream_abort", flag="aborted", detail=detail
        )
        return WorkerStreamLostError(
            f"worker connection lost mid-stream; {detail}"
        )

    async def _pace(exc: Exception) -> None:
        """Backoff before the next attempt; past the deadline, finish
        the way this phase fails (abort vs plain timeout)."""
        try:
            await deadline_backoff_sleep(backoff, context)
        except asyncio.TimeoutError:
            if started:
                raise _abort(
                    exc, "request deadline exceeded during resume"
                ) from exc
            raise

    while True:
        resume = started
        done_cb: Optional[Callable[[], None]] = None
        try:
            if resume:
                if faults.ACTIVE is not None:
                    await faults.ACTIVE.fire_async(
                        FAULT_POINT, request_id=context.id
                    )
                if admission is not None and attempt == 0:
                    # resumes already paid for admission; check() with
                    # resume=True NEVER sheds (it returns None by
                    # contract, locked by tests) but keeps the books —
                    # consulted once per migration window, not per
                    # retry, so resumed_total counts windows
                    admission.check(resume=True)
            wait_s = None
            if resume:
                wait_s = cfg.instance_wait_s
                remaining = context.remaining_ms()
                if remaining is not None:
                    wait_s = min(wait_s, max(0.05, remaining / 1e3))
            instance_id, stream, done_cb = await dial(
                cur_req, exclude, resume, wait_s
            )
        except asyncio.CancelledError:
            raise
        except _DIAL_ERRORS as exc:
            if isinstance(exc, DialFailedError):
                # the picked instance is unreachable: never re-pick it
                exclude.add(exc.instance_id)
            attempt += 1
            if resume:
                MIDSTREAM_RESUMES.labels("failed").inc()
                autopsy.note_event(
                    context.id, "resume_dial_failed", attempt=attempt,
                    error=f"{type(exc).__name__}",
                )
                log.warning(
                    "resume dispatch failed for %s (attempt %d/%d): %s",
                    context.id, attempt, cfg.max_resumes, exc,
                )
                if attempt >= cfg.max_resumes:
                    raise _abort(exc) from exc
            else:
                log.warning(
                    "dispatch failed for %s (attempt %d/%d): %s",
                    endpoint_name or context.id, attempt, max_attempts, exc,
                )
                if attempt >= max_attempts:
                    raise RuntimeError(
                        f"all attempts failed for {endpoint_name}: {exc}"
                    ) from exc
                FAILOVER_RETRIES.inc()
            await _pace(exc)
            continue

        if span:
            span.set_attr("instance", f"{instance_id:x}")
            if attempt and not resume:
                span.set_attr("retries", attempt)
        segment_tokens = False
        drain_handoff = False
        try:
            async for item in stream:
                fr = _get(item, "finish_reason")
                if fr is not None and str(getattr(fr, "value", fr)) == "migrate":
                    # drain handoff sentinel (docs/robustness.md
                    # "Graceful drain"): the worker is leaving on
                    # purpose and ended the stream at a step boundary
                    # with every generated token already flushed, so
                    # the commit log below is EXACT. Consume the marker
                    # — it is never client-facing — and re-dispatch as
                    # a resume on a healthy peer.
                    drain_handoff = True
                    break
                has_tokens = bool(_get(item, "token_ids"))
                if resume and has_tokens and death_t is not None:
                    # the splice is live: the continuation's first TOKEN
                    # arrived and the client never saw the seam (a
                    # token-less finish chunk — e.g. an instant
                    # deadline/cancel on the resumed engine — is not a
                    # successful splice and must not count as one)
                    gap_s = time.monotonic() - death_t
                    RESUME_SECONDS.observe(gap_s)
                    MIDSTREAM_RESUMES.labels("ok").inc()
                    resumes += 1
                    if span:
                        span.set_attr("resumes", resumes)
                    # autopsy: the splice point, with BOTH worker ids —
                    # the waterfall shows where one worker's segment
                    # ends and the survivor's begins
                    autopsy.note_event(
                        context.id, "resume_splice", flag="migrated",
                        reason=loss_reason,
                        from_worker=(
                            f"{death_instance:x}"
                            if death_instance is not None else ""
                        ),
                        to_worker=f"{instance_id:x}",
                        gap_ms=round(gap_s * 1e3, 3),
                        delivered=(
                            len(progress.emitted)
                            if progress is not None else 0
                        ),
                    )
                    death_t = None
                    death_instance = None
                    loss_reason = "crash"
                    attempt = 0
                    backoff.reset()
                segment_tokens = segment_tokens or has_tokens
                started = True
                if progress is not None:
                    item = progress.note(item)
                yield item
            if not drain_handoff:
                return
        except asyncio.CancelledError:
            raise
        except _STREAM_ERRORS as exc:
            exclude.add(instance_id)
            if progress is not None and progress.finished:
                # the finish chunk was already delivered — the death
                # took only the stream's trailing completion frame;
                # resuming would emit tokens AFTER the client's finish
                return
            if not started:
                # pre-first-token: classic failover, replay from scratch
                attempt += 1
                log.warning(
                    "instance %x died before first item (attempt %d/%d); "
                    "failing over", instance_id, attempt, max_attempts,
                )
                if attempt >= max_attempts:
                    raise RuntimeError(
                        f"all attempts failed for {endpoint_name}: {exc}"
                    ) from exc
                FAILOVER_RETRIES.inc()
                autopsy.note_event(
                    context.id, "failover_retry",
                    worker=f"{instance_id:x}", attempt=attempt,
                )
                await _pace(exc)
                continue
            if progress is None:
                # tokens delivered but the stream is not resumable:
                # the PR-5 clean abort
                raise _abort(exc) from exc
            if segment_tokens:
                # this segment delivered tokens: a fresh migration
                # window with a full resume budget
                attempt = 0
                backoff.reset()
            else:
                attempt += 1
                MIDSTREAM_RESUMES.labels("failed").inc()
                if attempt >= cfg.max_resumes:
                    raise _abort(exc) from exc
            if death_t is None:
                death_t = time.monotonic()
            if death_instance is None:
                death_instance = instance_id
                # autopsy: the dead worker's engine segment can never
                # ship (its process is gone) — synthesize its side of
                # the waterfall from what the frontend observed, so a
                # migrated request still shows both workers' segments
                autopsy.publish_segment(context.id, {
                    "source": "worker_died",
                    "worker": f"{instance_id:x}",
                    "tokens": len(progress.emitted),
                    "segments_delivered": progress.segments,
                })
            left = progress.budget_left()
            if left is not None and left <= 0:
                # the dead worker had delivered its entire token budget;
                # only the finish marker was lost
                yield progress.synthesize_final("length")
                return
            log.warning(
                "instance %x died mid-stream for %s after %d token(s); "
                "migrating", instance_id, context.id, len(progress.emitted),
            )
            cur_req = progress.resume_request()
            await _pace(exc)
            continue
        finally:
            if done_cb is not None:
                done_cb()

        # -- drain handoff (reached only via the sentinel break) ----------
        # The draining worker is excluded for the rest of this stream; a
        # healthy peer takes the resume. No backoff: this is a PLANNED
        # handoff — the fleet has capacity by construction, and every
        # waiting millisecond is client-visible gap.
        exclude.add(instance_id)
        loss_reason = "drain"
        if not started:
            # nothing delivered yet: replay from scratch on a peer
            attempt += 1
            if attempt >= max_attempts:
                raise RuntimeError(
                    f"all attempts failed for {endpoint_name}: "
                    "worker drained before first item"
                )
            FAILOVER_RETRIES.inc()
            autopsy.note_event(
                context.id, "failover_retry", worker=f"{instance_id:x}",
                attempt=attempt, reason="drain",
            )
            continue
        if progress is None:
            # tokens delivered but this stream cannot resume (migration
            # disabled frontend-side / ineligible shape): same clean
            # abort a crash would produce
            raise _abort(RuntimeError("worker drained mid-stream"))
        if segment_tokens:
            attempt = 0
            backoff.reset()
        else:
            # a resume that spliced nothing before the NEXT handoff
            # still burns resume budget — the same no-progress guard
            # the crash path applies
            attempt += 1
            if attempt >= cfg.max_resumes:
                raise _abort(RuntimeError("worker drained mid-stream"))
        if death_t is None:
            death_t = time.monotonic()
        if death_instance is None:
            death_instance = instance_id
        autopsy.note_event(
            context.id, "drain_handoff", worker=f"{instance_id:x}",
            delivered=len(progress.emitted),
        )
        left = progress.budget_left()
        if left is not None and left <= 0:
            # the handoff raced the length finish: the full budget was
            # delivered, only the finish marker remains
            yield progress.synthesize_final("length")
            return
        log.info(
            "instance %x draining; migrating %s after %d token(s)",
            instance_id, context.id, len(progress.emitted),
        )
        cur_req = progress.resume_request()
        continue
