"""Composable request/response pipeline.

Analogue of the reference's pipeline node graph (reference:
lib/runtime/src/pipeline/{nodes.rs, nodes/sources.rs, nodes/sinks.rs}):
ServiceFrontend → Operator(s) → ServiceBackend with forward (request) and
backward (response-stream) edges. Here an Operator is one object with a
forward transform and a backward stream transform; ``build_pipeline`` folds
operators onto a terminal engine, yielding a plain AsyncEngine.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Generic, TypeVar

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream

Req = TypeVar("Req")
DownReq = TypeVar("DownReq")
Resp = TypeVar("Resp")
DownResp = TypeVar("DownResp")


class Operator(abc.ABC, Generic[Req, DownReq, DownResp, Resp]):
    """A bidirectional pipeline stage.

    forward: transform the incoming request into the downstream request,
    returning per-request state shared with the backward edge.
    backward: transform the downstream response stream into the upstream one.
    (reference: pipeline/nodes.rs Operator fwd/bwd edges; e.g. the
    OpenAIPreprocessor renders+tokenizes forward and detokenizes backward.)
    """

    @abc.abstractmethod
    async def forward(self, request: Req, context: Context) -> tuple[DownReq, Any]: ...

    @abc.abstractmethod
    def backward(
        self, stream: AsyncIterator[DownResp], state: Any, context: Context
    ) -> AsyncIterator[Resp]: ...


class _OperatorEngine(AsyncEngine):
    def __init__(self, op: Operator, inner: AsyncEngine):
        self.op = op
        self.inner = inner

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        down_req, state = await self.op.forward(request, context)
        down_stream = self.inner.generate(down_req, context)
        async for item in self.op.backward(down_stream, state, context):
            yield item

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


def build_pipeline(*stages: Any) -> AsyncEngine:
    """Fold ``(op1, op2, ..., engine)`` into a single AsyncEngine.

    The last element must be an AsyncEngine (the sink); the rest Operators.
    """
    if not stages:
        raise ValueError("pipeline needs at least a terminal engine")
    engine = stages[-1]
    if not isinstance(engine, AsyncEngine):
        raise TypeError(f"pipeline sink must be an AsyncEngine, got {type(engine)}")
    for op in reversed(stages[:-1]):
        if not isinstance(op, Operator):
            raise TypeError(f"pipeline stage must be an Operator, got {type(op)}")
        engine = _OperatorEngine(op, engine)
    return engine
