"""PushRouter: instance selection + streaming dispatch.

Analogue of the reference's PushRouter (reference:
lib/runtime/src/pipeline/network/egress/push_router.rs:34-204) with the
same modes: random, round-robin, direct, and a pluggable selector hook the
KV-aware router uses (reference: lib/llm/src/kv_router.rs KvPushRouter).

Failover + migration (docs/robustness.md): dispatch failures AND streams
that die before yielding a single item are re-dispatched to a different
instance under a bounded retry budget with exponential backoff + jitter.
A stream that dies AFTER items were yielded is *migrated*: the request
is re-dispatched as a resume (prompt extended by the delivered tokens,
length budgets shrunk, RNG offset advanced — runtime/migration.py) and
the continuation splices into the original stream. Only when migration
is disabled, opted out, or exhausted does the stream terminate with a
clean ``WorkerStreamLostError`` the HTTP layer turns into an SSE
``error`` event — never a hung connection.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import time
from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.runtime.migration import (
    DialFailedError,
    MigrationConfig,
    WorkerStreamLostError,
    deadline_backoff_sleep,
    migrating_stream,
)

__all__ = [
    "PushRouter",
    "RouterMode",
    "Selector",
    "WorkerStreamLostError",
    "deadline_backoff_sleep",
]

log = logging.getLogger("dynamo_tpu.runtime.push_router")

# A selector maps (request, live instance ids) -> chosen instance id.
Selector = Callable[[Any, list[int]], Awaitable[int]]


class RouterMode(str, enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    CUSTOM = "custom"  # external selector (e.g. KV-aware)


class PushRouter(AsyncEngine):
    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        selector: Optional[Selector] = None,
        max_attempts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        migration: Optional[MigrationConfig] = None,
        admission: Any = None,
    ):
        self.client = client
        self.mode = mode
        self.selector = selector
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # mid-stream migration config (None = env defaults) and the
        # frontend's AdmissionController when co-located: resumes report
        # through check(resume=True), which never sheds them
        self.migration = migration or MigrationConfig.from_env()
        self.admission = admission
        self._rr_index = 0
        if mode == RouterMode.CUSTOM and selector is None:
            raise ValueError("CUSTOM mode requires a selector")

    async def _pick(
        self,
        request: Any,
        exclude: set[int],
        wait_timeout_s: Optional[float] = None,
    ) -> int:
        ids = [i for i in self.client.instance_ids() if i not in exclude]
        if not ids:
            live = await self.client.wait_for_instances(wait_timeout_s)
            ids = [i for i in live if i not in exclude]
            if not ids:
                # every live instance is excluded: fall back to the full
                # set (mirrors KvRouter.schedule) — a transient dial
                # failure must not permanently bar a recovered worker
                # while a stream's resume budget burns
                ids = list(live)
            if not ids:
                raise RuntimeError(
                    f"no live instances for {self.client.endpoint.path}"
                )
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        if self.mode == RouterMode.ROUND_ROBIN:
            self._rr_index = (self._rr_index + 1) % len(ids)
            return ids[self._rr_index]
        if self.mode == RouterMode.CUSTOM:
            assert self.selector is not None
            return await self.selector(request, ids)
        raise ValueError(f"cannot auto-pick in mode {self.mode}")

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        from dynamo_tpu.telemetry import get_tracer

        # one span for the whole routed dispatch (pick + stream + any
        # resumes); the worker's own span parents here via the wire's
        # trace context
        span = get_tracer().span(
            "router.dispatch", parent=context,
            attrs={"service": "frontend", "mode": self.mode.value},
        )
        if span:
            context = context.child()
            context.set_trace(span)

        async def dial(req, exclude, resume, wait_timeout_s):
            from dynamo_tpu.telemetry import autopsy
            from dynamo_tpu.telemetry.hostplane import note_stage

            t_dial = time.monotonic()
            instance_id = await self._pick(req, exclude, wait_timeout_s)
            # request autopsy: every dial (first dispatch, failover
            # retry, migration resume) lands on the request's timeline
            autopsy.note_router(
                context.id, instance_id,
                resume=resume, mode=self.mode.value,
            )
            try:
                stream = await self.client.generate_direct(
                    instance_id, req, context
                )
            except (OSError, asyncio.TimeoutError, KeyError) as exc:
                # worker vanished between discovery and dial: carry the
                # id out so the retry excludes it
                raise DialFailedError(instance_id, exc) from exc
            finally:
                # host-cost ledger: instance pick + dial (accumulates
                # across migration re-dials — re-dispatch is host cost)
                note_stage(context.id, "dispatch", time.monotonic() - t_dial)
            return instance_id, stream, None

        try:
            async for item in migrating_stream(
                request, context, dial, self.migration,
                admission=self.admission, span=span,
                max_attempts=self.max_attempts,
                backoff_base_s=self.backoff_base_s,
                backoff_cap_s=self.backoff_cap_s,
                endpoint_name=self.client.endpoint.path,
            ):
                yield item
        finally:
            span.end()

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)

    async def generate_direct(
        self, instance_id: int, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        return await self.client.generate_direct(instance_id, request, context)
