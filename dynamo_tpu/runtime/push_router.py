"""PushRouter: instance selection + streaming dispatch.

Analogue of the reference's PushRouter (reference:
lib/runtime/src/pipeline/network/egress/push_router.rs:34-204) with the
same modes: random, round-robin, direct, and a pluggable selector hook the
KV-aware router uses (reference: lib/llm/src/kv_router.rs KvPushRouter).
Retries on connection failure against a different instance.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream

log = logging.getLogger("dynamo_tpu.runtime.push_router")

# A selector maps (request, live instance ids) -> chosen instance id.
Selector = Callable[[Any, list[int]], Awaitable[int]]


class RouterMode(str, enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    CUSTOM = "custom"  # external selector (e.g. KV-aware)


class PushRouter(AsyncEngine):
    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        selector: Optional[Selector] = None,
        max_attempts: int = 3,
    ):
        self.client = client
        self.mode = mode
        self.selector = selector
        self.max_attempts = max_attempts
        self._rr_index = 0
        if mode == RouterMode.CUSTOM and selector is None:
            raise ValueError("CUSTOM mode requires a selector")

    async def _pick(self, request: Any, exclude: set[int]) -> int:
        ids = [i for i in self.client.instance_ids() if i not in exclude]
        if not ids:
            ids = await self.client.wait_for_instances()
            ids = [i for i in ids if i not in exclude]
            if not ids:
                raise RuntimeError(
                    f"no live instances for {self.client.endpoint.path}"
                )
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        if self.mode == RouterMode.ROUND_ROBIN:
            self._rr_index = (self._rr_index + 1) % len(ids)
            return ids[self._rr_index]
        if self.mode == RouterMode.CUSTOM:
            assert self.selector is not None
            return await self.selector(request, ids)
        raise ValueError(f"cannot auto-pick in mode {self.mode}")

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        from dynamo_tpu.telemetry import get_tracer

        exclude: set[int] = set()
        last_err: Exception | None = None
        # one span for the whole routed dispatch (pick + stream); the
        # worker's own span parents here via the wire's trace context
        span = get_tracer().span(
            "router.dispatch", parent=context,
            attrs={"service": "frontend", "mode": self.mode.value},
        )
        if span:
            context = context.child()
            context.set_trace(span)
        try:
            for attempt in range(self.max_attempts):
                instance_id = await self._pick(request, exclude)
                try:
                    stream = await self.client.generate_direct(
                        instance_id, request, context
                    )
                except (OSError, asyncio.TimeoutError, KeyError) as exc:
                    # worker vanished between discovery and dial: try another
                    log.warning("instance %x unreachable: %s", instance_id, exc)
                    exclude.add(instance_id)
                    last_err = exc
                    continue
                span.set_attr("instance", f"{instance_id:x}")
                if attempt:
                    span.set_attr("retries", attempt)
                async for item in stream:
                    yield item
                return
            raise RuntimeError(
                f"all attempts failed for {self.client.endpoint.path}: {last_err}"
            )
        finally:
            span.end()

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)

    async def generate_direct(
        self, instance_id: int, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        return await self.client.generate_direct(instance_id, request, context)
