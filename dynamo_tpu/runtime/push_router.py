"""PushRouter: instance selection + streaming dispatch.

Analogue of the reference's PushRouter (reference:
lib/runtime/src/pipeline/network/egress/push_router.rs:34-204) with the
same modes: random, round-robin, direct, and a pluggable selector hook the
KV-aware router uses (reference: lib/llm/src/kv_router.rs KvPushRouter).

Failover (docs/robustness.md): dispatch failures AND streams that die
before yielding a single item are re-dispatched to a different instance
under a bounded retry budget with exponential backoff + jitter. A
stream that dies AFTER items were yielded cannot be replayed (tokens
already reached the client); it terminates with a clean error the HTTP
layer turns into an SSE ``error`` event — never a hung connection.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.runtime.service import ConnectionLostError
from dynamo_tpu.telemetry.instruments import (
    FAILOVER_RETRIES,
    MIDSTREAM_ABORTS,
)
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.runtime.push_router")


class WorkerStreamLostError(RuntimeError):
    """A worker died after streaming part of a response; the stream is
    not replayable. Carries a clean, client-presentable message."""


async def deadline_backoff_sleep(backoff: Backoff, context: Context) -> None:
    """One failover backoff, clamped to the request's remaining deadline
    budget; raises TimeoutError instead of retrying past the deadline.
    Shared by PushRouter and KvPushRouter."""
    delay = backoff.next_delay()
    remaining = context.remaining_ms()
    if remaining is not None:
        if remaining <= 0:
            raise asyncio.TimeoutError(
                "request deadline exceeded during failover"
            )
        delay = min(delay, remaining / 1e3)
    await asyncio.sleep(delay)

# A selector maps (request, live instance ids) -> chosen instance id.
Selector = Callable[[Any, list[int]], Awaitable[int]]


class RouterMode(str, enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    CUSTOM = "custom"  # external selector (e.g. KV-aware)


class PushRouter(AsyncEngine):
    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        selector: Optional[Selector] = None,
        max_attempts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
    ):
        self.client = client
        self.mode = mode
        self.selector = selector
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rr_index = 0
        if mode == RouterMode.CUSTOM and selector is None:
            raise ValueError("CUSTOM mode requires a selector")

    async def _pick(self, request: Any, exclude: set[int]) -> int:
        ids = [i for i in self.client.instance_ids() if i not in exclude]
        if not ids:
            ids = await self.client.wait_for_instances()
            ids = [i for i in ids if i not in exclude]
            if not ids:
                raise RuntimeError(
                    f"no live instances for {self.client.endpoint.path}"
                )
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        if self.mode == RouterMode.ROUND_ROBIN:
            self._rr_index = (self._rr_index + 1) % len(ids)
            return ids[self._rr_index]
        if self.mode == RouterMode.CUSTOM:
            assert self.selector is not None
            return await self.selector(request, ids)
        raise ValueError(f"cannot auto-pick in mode {self.mode}")

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        from dynamo_tpu.telemetry import get_tracer

        exclude: set[int] = set()
        last_err: Exception | None = None
        backoff = Backoff(base_s=self.backoff_base_s, cap_s=self.backoff_cap_s)
        # one span for the whole routed dispatch (pick + stream); the
        # worker's own span parents here via the wire's trace context
        span = get_tracer().span(
            "router.dispatch", parent=context,
            attrs={"service": "frontend", "mode": self.mode.value},
        )
        if span:
            context = context.child()
            context.set_trace(span)
        try:
            for attempt in range(self.max_attempts):
                if attempt:
                    FAILOVER_RETRIES.inc()
                    await deadline_backoff_sleep(backoff, context)
                instance_id = await self._pick(request, exclude)
                try:
                    stream = await self.client.generate_direct(
                        instance_id, request, context
                    )
                except (OSError, asyncio.TimeoutError, KeyError) as exc:
                    # worker vanished between discovery and dial: try another
                    log.warning("instance %x unreachable: %s", instance_id, exc)
                    exclude.add(instance_id)
                    last_err = exc
                    continue
                span.set_attr("instance", f"{instance_id:x}")
                if attempt:
                    span.set_attr("retries", attempt)
                yielded = False
                try:
                    async for item in stream:
                        yielded = True
                        yield item
                    return
                except ConnectionLostError as exc:
                    # the WORKER died while this stream was open
                    exclude.add(instance_id)
                    last_err = exc
                    if yielded:
                        # tokens already reached the client: a silent
                        # re-dispatch would replay/duplicate them. End
                        # with a clean error instead (the HTTP layer
                        # turns this into an SSE `error` event).
                        MIDSTREAM_ABORTS.inc()
                        span.set_attr("midstream_abort", True)
                        raise WorkerStreamLostError(
                            "worker connection lost mid-stream; partial "
                            "response cannot be resumed"
                        ) from exc
                    log.warning(
                        "instance %x died before first item; failing over",
                        instance_id,
                    )
                    continue
            raise RuntimeError(
                f"all attempts failed for {self.client.endpoint.path}: {last_err}"
            )
        finally:
            span.end()

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)

    async def generate_direct(
        self, instance_id: int, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        return await self.client.generate_direct(instance_id, request, context)
