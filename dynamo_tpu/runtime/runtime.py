"""Runtime and DistributedRuntime: process-level handles.

Analogue of the reference's Runtime/DistributedRuntime/Worker
(reference: lib/runtime/src/{lib.rs:62-91, distributed.rs:32-176,
worker.rs:61-117}). A ``DistributedRuntime`` owns:

- the store connection (coordinator client, or in-process MemoryStore in
  "static" single-process mode),
- the primary lease + background keepalive (liveness primitive: if this
  process dies, everything it registered vanishes from discovery),
- one shared TCP EndpointServer for all endpoints served by this process.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Optional

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.service import ConnectionPool, EndpointServer
from dynamo_tpu.store.base import Store
from dynamo_tpu.store.client import StoreClient
from dynamo_tpu.store.memory import MemoryStore

log = logging.getLogger("dynamo_tpu.runtime")


class Runtime:
    """Process-level runtime: the event loop + shutdown signal."""

    def __init__(self) -> None:
        self._shutdown = asyncio.Event()

    def shutdown(self) -> None:
        self._shutdown.set()

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()

    def install_signal_handlers(self) -> None:
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self.shutdown)
            except NotImplementedError:  # pragma: no cover
                pass


class DistributedRuntime:
    def __init__(
        self,
        runtime: Runtime,
        store: Store,
        config: RuntimeConfig,
        primary_lease_id: int,
    ):
        self.runtime = runtime
        self.store = store
        self.config = config
        self.primary_lease_id = primary_lease_id
        self.endpoint_server = EndpointServer(
            host=config.worker_host, port=config.worker_port
        )
        self.connection_pool = ConnectionPool()
        self._keepalive_task: Optional[asyncio.Task] = None
        self._server_started = False

    @classmethod
    async def create(
        cls,
        config: Optional[RuntimeConfig] = None,
        runtime: Optional[Runtime] = None,
        store: Optional[Store] = None,
    ) -> "DistributedRuntime":
        """Connect to the coordinator (or spin an in-process store in static
        mode), grant the primary lease, start keepalive."""
        config = config or RuntimeConfig.from_settings()
        runtime = runtime or Runtime()
        if store is None:
            if config.static:
                store = MemoryStore()
            else:
                # reconnect: a coordinator blip redials on backoff instead
                # of bricking the client (docs/robustness.md); the lease
                # keepalive below decides whether the process survives it
                store = await StoreClient.connect(
                    config.store_host, config.store_port, reconnect=True
                )
        lease_id = await store.lease_grant(config.lease_ttl_s)
        drt = cls(runtime, store, config, lease_id)
        drt._keepalive_task = asyncio.get_running_loop().create_task(
            drt._keepalive_loop()
        )
        return drt

    async def _keepalive_loop(self) -> None:
        # transient store disconnects are tolerated for up to the lease
        # TTL (the client is redialing on backoff underneath); once the
        # TTL has certainly lapsed the lease is gone server-side anyway,
        # so the process shuts down rather than serve unregistered
        down_since: Optional[float] = None
        while not self.runtime.is_shutdown:
            await asyncio.sleep(self.config.lease_keepalive_s)
            try:
                ok = await self.store.lease_keepalive(self.primary_lease_id)
            except ConnectionError:
                now = asyncio.get_running_loop().time()
                if down_since is None:
                    down_since = now
                    log.warning(
                        "store unreachable; retrying keepalive within the "
                        "lease TTL (%.0fs)", self.config.lease_ttl_s,
                    )
                if now - down_since >= self.config.lease_ttl_s:
                    log.error("store connection lost; shutting down")
                    self.runtime.shutdown()
                    return
                continue
            down_since = None
            if not ok:
                log.error("primary lease lost; shutting down")
                self.runtime.shutdown()
                return

    async def ensure_endpoint_server(self) -> EndpointServer:
        if not self._server_started:
            await self.endpoint_server.start()
            self._server_started = True
        return self.endpoint_server

    def namespace(self, name: str):
        from dynamo_tpu.runtime.component import Namespace

        return Namespace(self, name)

    async def shutdown(self) -> None:
        self.runtime.shutdown()
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        try:
            await self.store.lease_revoke(self.primary_lease_id)
        except (ConnectionError, RuntimeError):
            pass
        await self.endpoint_server.stop()
        await self.connection_pool.close()
        await self.store.close()
