"""Worker data plane: TCP endpoint server + client with multiplexed streams.

Analogue of the reference's request/response planes (reference:
lib/runtime/src/pipeline/network/{egress/addressed_router.rs,
ingress/push_handler.rs, tcp/server.rs, codec/two_part.rs}) collapsed into
one direct connection: the caller dials the worker's TCP port (discovered
via the store) and sends a two-part message (control header + payload);
response items stream back on the same connection, multiplexed by stream id.
This removes the NATS hop and the reverse TCP dial of the reference design.

Wire frames (length-prefixed msgpack, see store/wire.py):
  caller→worker: {t:"req",  sid, ep, ctx:{id, trace_id?, span_id?}, p: payload}
                 {t:"stop", sid} | {t:"kill", sid}
  worker→caller: {t:"item", sid, p} | {t:"err", sid, e} | {t:"fin", sid}
                 {t:"seg",  sid, p: {segments, events}}  (request autopsy:
                 the worker's engine-side timeline for the stream's rid,
                 sent once before fin; consumers that predate it ignore
                 unknown frame types)
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu import faults
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.store.wire import read_frame, shutdown_server, write_frame
from dynamo_tpu.telemetry import autopsy, get_tracer, propagation_context

log = logging.getLogger("dynamo_tpu.runtime.service")


class ConnectionLostError(ConnectionError):
    """The worker connection died while a response stream was open.

    Raised to stream consumers (instead of a bare RuntimeError) so
    routers can distinguish a vanished WORKER — retryable before the
    first token — from a genuine engine error, which is not."""


def to_wire(obj: Any) -> Any:
    """Make a payload msgpack-safe: pydantic models become dicts.

    Engines on both sides of the wire accept dicts (they re-validate), so
    the data plane only ever carries plain msgpack types.
    """
    if hasattr(obj, "model_dump"):
        return obj.model_dump(exclude_none=True)
    return obj


class EndpointServer:
    """Serves one or more named endpoints, each backed by an AsyncEngine."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.host = host
        self.port = port
        self._endpoints: dict[str, AsyncEngine] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conn_writers: set[asyncio.StreamWriter] = set()
        self.active_requests = 0

    def register(self, name: str, engine: AsyncEngine) -> None:
        self._endpoints[name] = engine

    def unregister(self, name: str) -> None:
        self._endpoints.pop(name, None)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        log.debug("endpoint server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        await shutdown_server(self._server, self._conn_writers)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conn_writers.add(writer)
        write_lock = asyncio.Lock()
        streams: dict[int, tuple[asyncio.Task, Context]] = {}

        async def send(obj: Any) -> None:
            async with write_lock:
                write_frame(writer, obj)
                await writer.drain()

        async def run_stream(sid: int, ep: str, ctx: Context, payload: Any) -> None:
            self.active_requests += 1
            # one span per served stream, linked to the caller's trace
            # context from the wire; downstream engine spans parent here
            span = get_tracer().span(
                "worker.generate", parent=ctx,
                attrs={"service": "worker", "endpoint": ep},
            )
            # a real span re-parents downstream work; a NULL span still
            # propagates the inbound context or, when WE are the head
            # and sampling dropped the root, the negative mark
            ctx.set_trace(propagation_context(span, ctx) or {})
            try:
                engine = self._endpoints.get(ep)
                if engine is None:
                    await send({"t": "err", "sid": sid, "e": f"no such endpoint: {ep}"})
                    return
                try:
                    async for item in engine.generate(payload, ctx):
                        if ctx.is_killed:
                            break
                        # request autopsy: ship anything the engine has
                        # published so far AHEAD of the item it precedes.
                        # The engine finalizes its segment before queuing
                        # the LAST TOKEN item (engine.py
                        # _finalize_observability) because consumers
                        # abandon the stream right there — at max_tokens,
                        # before the finish-marked item — so a payload
                        # sent any later is never read by the caller
                        seg = autopsy.take_pending(ctx.id)
                        if seg is not None and (
                            seg.get("segments") or seg.get("events")
                        ):
                            await send({"t": "seg", "sid": sid, "p": seg})
                        await send({"t": "item", "sid": sid, "p": to_wire(item)})
                    # fallback for payloads published after the last item
                    # (aborts, engines without the early finalize): ride
                    # one frame before fin for callers that drain fully
                    seg = autopsy.take_pending(ctx.id)
                    if seg is not None and (
                        seg.get("segments") or seg.get("events")
                    ):
                        await send({"t": "seg", "sid": sid, "p": seg})
                    await send({"t": "fin", "sid": sid})
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    log.exception("engine error on %s", ep)
                    await send({"t": "err", "sid": sid, "e": f"{type(exc).__name__}: {exc}"})
            except asyncio.CancelledError:
                # connection teardown cancels in-flight streams; the task
                # must end *cancelled* (not "done") or the canceller in
                # _handle's finally believes it finished cleanly
                raise
            except ConnectionError:
                pass
            finally:
                if ctx.is_killed:
                    span.set_attr("killed", True)
                span.end()
                self.active_requests -= 1
                streams.pop(sid, None)

        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                t = msg.get("t")
                if t == "req":
                    sid = msg["sid"]
                    wire_ctx = msg.get("ctx", {})
                    ctx = Context(
                        id=wire_ctx.get("id"),
                        trace_id=wire_ctx.get("trace_id"),
                        span_id=wire_ctx.get("span_id"),
                    )
                    if wire_ctx.get("sampled") is False:
                        ctx.trace_sampled = False
                    if wire_ctx.get("deadline_ms") is not None:
                        # re-anchor the caller's REMAINING budget to our
                        # own monotonic clock (wall clocks never compared)
                        ctx.set_deadline_ms(float(wire_ctx["deadline_ms"]))
                    task = asyncio.get_running_loop().create_task(
                        run_stream(sid, msg["ep"], ctx, msg.get("p"))
                    )
                    streams[sid] = (task, ctx)
                elif t in ("stop", "kill"):
                    entry = streams.get(msg["sid"])
                    if entry is not None:
                        _, ctx = entry
                        ctx.kill() if t == "kill" else ctx.stop_generating()
                elif t == "ping":
                    await send({"t": "pong"})
        finally:
            # connection gone: kill all in-flight streams for this caller
            for task, ctx in streams.values():
                ctx.kill()
                task.cancel()
            self._conn_writers.discard(writer)
            writer.close()


class EndpointConnection:
    """One pooled connection to a worker; multiplexes many request streams."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._sids = itertools.count(1)
        self._queues: dict[int, asyncio.Queue] = {}
        self._rx: asyncio.Task | None = None
        self._lock = asyncio.Lock()
        self.closed = False

    @classmethod
    async def connect(
        cls, host: str, port: int, timeout_s: float = 5.0
    ) -> "EndpointConnection":
        conn = cls(host, port)
        conn._reader, conn._writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=timeout_s
        )
        conn._rx = asyncio.get_running_loop().create_task(conn._rx_loop())
        return conn

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                msg = await read_frame(self._reader)
                if faults.ACTIVE is not None:
                    # injected recv faults: a `drop` here is a realistic
                    # peer-vanished teardown (ConnectionError ends the
                    # loop and fails every waiter below)
                    await faults.ACTIVE.fire_async(
                        "transport.recv", sid=msg.get("sid") or ""
                    )
                q = self._queues.get(msg.get("sid"))
                if q is not None:
                    q.put_nowait(msg)
        except asyncio.CancelledError:
            raise  # close() cancels us; finally below still fails waiters
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.closed = True
            for q in self._queues.values():
                q.put_nowait({"t": "err", "e": "connection lost", "lost": True})

    async def _send(self, obj: Any) -> None:
        if self._writer is None or self.closed:
            raise ConnectionError("endpoint connection closed")
        if faults.ACTIVE is not None:
            await faults.ACTIVE.fire_async(
                "transport.send",
                endpoint=obj.get("ep") or "",
                request_id=(obj.get("ctx") or {}).get("id") or "",
            )
        async with self._lock:
            write_frame(self._writer, obj)
            await self._writer.drain()

    async def request(
        self, endpoint: str, payload: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        """Send one request; yields response items until fin/err."""
        ctx = context or Context()
        sid = next(self._sids)
        q: asyncio.Queue = asyncio.Queue()
        self._queues[sid] = q
        loop = asyncio.get_running_loop()
        wire_ctx: dict = {"id": ctx.id}
        if ctx.deadline is not None:
            # ship the REMAINING budget; the worker re-anchors it to its
            # own monotonic clock (see EndpointServer._handle)
            wire_ctx["deadline_ms"] = ctx.remaining_ms()
        if ctx.trace_sampled is False:
            # the head's negative sampling decision rides the wire so
            # downstream tracers stay quiet for this request too
            wire_ctx["sampled"] = False
        elif ctx.trace_id is not None:
            # trace context rides the existing control frame — no extra
            # hop, and workers join the caller's trace (telemetry/spans.py)
            wire_ctx["trace_id"] = ctx.trace_id
            wire_ctx["span_id"] = ctx.span_id
        await self._send(
            {"t": "req", "sid": sid, "ep": endpoint, "ctx": wire_ctx, "p": to_wire(payload)}
        )

        # Cancellation rides the Context, not the consumer: the moment the
        # caller stops/kills the context, the worker is notified — even if
        # the consumer has abandoned the stream (generator finalization is
        # GC-deferred in CPython, so it can't be the cancel path).
        async def cancel_notifier() -> None:
            await ctx.wait_stopped()
            if sid in self._queues and not self.closed:
                try:
                    await self._send(
                        {"t": "kill" if ctx.is_killed else "stop", "sid": sid}
                    )
                except (ConnectionError, RuntimeError):
                    pass

        notifier = loop.create_task(cancel_notifier())

        async def iterate() -> AsyncIterator[Any]:
            finished = False
            try:
                while True:
                    msg = await q.get()
                    t = msg.get("t")
                    if t == "item":
                        yield msg.get("p")
                    elif t == "seg":
                        # worker's autopsy payload: fold into the local
                        # record for this rid (or relay further up when
                        # this process is itself a worker)
                        autopsy.merge_pending(ctx.id, msg.get("p"))
                    elif t == "fin":
                        finished = True
                        return
                    elif t == "err":
                        finished = True
                        if msg.get("lost"):
                            raise ConnectionLostError(
                                msg.get("e", "connection lost")
                            )
                        raise RuntimeError(msg.get("e", "remote error"))
            finally:
                notifier.cancel()
                self._queues.pop(sid, None)
                # consumer abandoned the stream early (break / aclose) and
                # never cancelled the context: kill the in-flight request
                if not finished and not ctx.is_stopped and not self.closed:
                    try:
                        await self._send({"t": "kill", "sid": sid})
                    except (ConnectionError, RuntimeError):
                        pass

        return iterate()

    async def close(self) -> None:
        self.closed = True
        if self._rx is not None:
            self._rx.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class ConnectionPool:
    """Caches one EndpointConnection per (host, port).

    Locking is per-target: dialing one unreachable host must not stall
    traffic to healthy workers.
    """

    def __init__(self, connect_timeout_s: float = 5.0) -> None:
        self._conns: dict[tuple[str, int], EndpointConnection] = {}
        self._locks: dict[tuple[str, int], asyncio.Lock] = {}
        self.connect_timeout_s = connect_timeout_s

    async def get(self, host: str, port: int) -> EndpointConnection:
        key = (host, port)
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            conn = self._conns.get(key)
            if conn is None or conn.closed:
                conn = await EndpointConnection.connect(
                    host, port, timeout_s=self.connect_timeout_s
                )
                self._conns[key] = conn
            return conn

    def invalidate(self, host: str, port: int) -> None:
        self._conns.pop((host, port), None)

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
