"""dynamo_tpu SDK: service graph decorators + local serving.

Reference: the BentoML-derived SDK (deploy/sdk/src/dynamo/sdk —
@service/@dynamo_endpoint decorators, depends() graph edges,
`dynamo serve` with circus supervision). Here: plain decorators, a
subprocess supervisor with a store-based control plane, and a TPU
chip allocator.
"""

from dynamo_tpu.sdk.service import (
    DynamoService,
    depends,
    endpoint,
    service,
)

__all__ = ["DynamoService", "depends", "endpoint", "service"]
