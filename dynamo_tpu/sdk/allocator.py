"""TPU resource allocator for local serving.

Reference: deploy/sdk/src/dynamo/sdk/cli/allocator.py:54-255 (GPU
assignment per @service resources). TPU twist: the schedulable unit is a
*chip set* — a worker that wants tp=N needs N chips wired as one mesh,
and JAX processes address chips via TPU_VISIBLE_DEVICES (or fall back to
CPU for control-plane components that request no TPU).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


class AllocationError(RuntimeError):
    pass


@dataclass
class Allocation:
    chip_ids: list[int] = field(default_factory=list)

    def env(self) -> dict[str, str]:
        """Env vars that scope a child process to its chips."""
        if not self.chip_ids:
            # control-plane component: keep it off the TPU entirely
            return {"DYN_JAX_PLATFORM": "cpu"}
        return {
            "TPU_VISIBLE_DEVICES": ",".join(str(c) for c in self.chip_ids),
        }


class TpuAllocator:
    def __init__(self, total_chips: int | None = None):
        if total_chips is None:
            total_chips = int(os.environ.get("DYN_TPU_CHIPS", "1"))
        self.total = total_chips
        self._free: list[int] = list(range(total_chips))
        self._held: dict[str, list[int]] = {}

    @property
    def free_chips(self) -> int:
        return len(self._free)

    def allocate(self, owner: str, resources: dict) -> Allocation:
        want = int(resources.get("tpu", 0))
        if want == 0:
            return Allocation([])
        if want > len(self._free):
            raise AllocationError(
                f"{owner}: wants {want} chips, {len(self._free)} free of {self.total}"
            )
        chips = [self._free.pop(0) for _ in range(want)]
        self._held.setdefault(owner, []).extend(chips)
        return Allocation(chips)

    def release(self, owner: str) -> None:
        self._free.extend(self._held.pop(owner, []))
        self._free.sort()
