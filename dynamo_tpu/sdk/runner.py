"""Component runner: one process serving one DynamoService.

Reference: deploy/sdk/src/dynamo/sdk/cli/serve_dynamo.py:26-318 — the
per-component worker main that creates the DistributedRuntime, binds
dependency clients, and serves each decorated endpoint.

Endpoint methods are ``async def fn(self, request)`` returning a value
or an async iterator; they are adapted onto the runtime's AsyncEngine
streaming interface. Dependency attributes become ``RemoteService``
proxies whose method calls stream from the target component's endpoint
through a round-robin PushRouter.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import inspect
import json
import logging
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import Component
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.sdk.service import DynamoService

log = logging.getLogger("dynamo_tpu.sdk.runner")


class _EndpointEngine(AsyncEngine):
    """Adapts one bound endpoint method onto the streaming engine trait."""

    def __init__(self, bound_method: Any):
        self._fn = bound_method

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        out = self._fn(request)
        if inspect.isasyncgen(out):
            async for item in out:
                if context.is_stopped:
                    return
                yield item
        else:
            yield await out

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


class RemoteService:
    """Client proxy for a depends() edge: ``self.next.generate(req)``
    streams from the target component's matching endpoint."""

    def __init__(self, target: DynamoService, component: Component):
        self._target = target
        self._component = component
        self._routers: dict[str, PushRouter] = {}

    async def _router(self, ep_name: str) -> PushRouter:
        router = self._routers.get(ep_name)
        if router is None:
            client = await self._component.endpoint(ep_name).client()
            await client.wait_for_instances()
            router = PushRouter(client, RouterMode.ROUND_ROBIN)
            self._routers[ep_name] = router
        return router

    def __getattr__(self, ep_name: str) -> Any:
        if ep_name.startswith("_"):
            raise AttributeError(ep_name)
        if ep_name not in self._target.endpoints:
            raise AttributeError(
                f"{self._target.name} has no endpoint {ep_name!r}"
            )

        async def call(request: Any) -> AsyncIterator[Any]:
            router = await self._router(ep_name)
            async for item in router.generate(request, Context()):
                yield item

        return call


async def bind_dependencies(
    instance: Any, svc: DynamoService, drt: DistributedRuntime
) -> None:
    for attr, target in svc.dependencies.items():
        component = drt.namespace(target.config.namespace).component(
            target.name.lower()
        )
        setattr(instance, f"_dynamo_dep_{attr}", RemoteService(target, component))


async def serve_service(
    svc: DynamoService,
    drt: DistributedRuntime,
    instance: Any = None,
) -> Any:
    """Instantiate (unless given) + bind deps + serve all endpoints."""
    if instance is None:
        instance = svc.inner()
    await bind_dependencies(instance, svc, drt)
    init = getattr(instance, "async_init", None)
    if init is not None:
        await init()
    component = drt.namespace(svc.config.namespace).component(svc.name.lower())
    for ep_name, method_name in svc.endpoints.items():
        engine = _EndpointEngine(getattr(instance, method_name))
        await component.endpoint(ep_name).serve(engine)
    return instance


def load_service(spec: str) -> DynamoService:
    """'pkg.module:Attr' -> DynamoService."""
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"service spec must be module:Attr, got {spec!r}")
    mod = importlib.import_module(mod_name)
    svc = getattr(mod, attr)
    if not isinstance(svc, DynamoService):
        raise TypeError(f"{spec} is not a @service (got {type(svc)})")
    return svc


async def _amain(args: argparse.Namespace) -> None:
    from dynamo_tpu.runtime.logging import init_logging

    init_logging()
    svc = load_service(args.service)
    if args.config:
        overrides = json.loads(args.config)
        svc.config = svc.config.merged(overrides)
    drt = await DistributedRuntime.create(
        config=RuntimeConfig.from_settings(
            store_host=args.store_host, store_port=args.store_port
        )
    )
    drt.runtime.install_signal_handlers()
    instance = await serve_service(svc, drt)
    print(f"component {svc.name} serving", flush=True)
    await drt.runtime.wait_shutdown()
    stop = getattr(instance, "async_stop", None)
    if stop is not None:
        await stop()
    await drt.shutdown()


def main() -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-component")
    p.add_argument("service", help="module:Attr of the DynamoService")
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, default=4222)
    p.add_argument("--config", default="", help="JSON ServiceConfig overrides")
    args = p.parse_args()
    from dynamo_tpu.utils.jaxtools import configure_from_env

    configure_from_env()
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
