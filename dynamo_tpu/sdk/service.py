"""@service / @endpoint decorators and depends() graph edges.

Reference: deploy/sdk/src/dynamo/sdk/lib/{service.py:301-342,
decorators.py:27-92, dependency.py}. A decorated class becomes a
``DynamoService`` carrying its namespace/resources/replica config and
its endpoint table; ``depends(Other)`` declares a graph edge that the
component runner resolves into a live endpoint client at serve time.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

ENDPOINT_ATTR = "__dynamo_endpoint__"


@dataclass
class ServiceConfig:
    name: str
    namespace: str = "dynamo"
    resources: dict[str, Any] = field(default_factory=dict)  # {"tpu": 1, ...}
    replicas: int = 1
    config: dict[str, Any] = field(default_factory=dict)  # free-form knobs

    def merged(self, overrides: dict[str, Any]) -> "ServiceConfig":
        out = ServiceConfig(
            name=self.name,
            namespace=overrides.get("namespace", self.namespace),
            resources={**self.resources, **overrides.get("resources", {})},
            replicas=overrides.get("replicas", self.replicas),
            config={**self.config, **overrides.get("config", {})},
        )
        return out


class Dependency:
    """A depends() edge; resolved to a client by the component runner."""

    def __init__(self, target: "DynamoService"):
        self.target = target
        self.attr_name: Optional[str] = None

    def __set_name__(self, owner: type, name: str) -> None:
        self.attr_name = name

    def __get__(self, obj: Any, objtype: Optional[type] = None) -> Any:
        if obj is None:
            return self
        bound = getattr(obj, f"_dynamo_dep_{self.attr_name}", None)
        if bound is None:
            raise RuntimeError(
                f"dependency {self.attr_name!r} not bound (component not "
                "running under serve, or bind_dependencies not called)"
            )
        return bound


def depends(target: "DynamoService") -> Dependency:
    if not isinstance(target, DynamoService):
        raise TypeError("depends() takes a @service-decorated class")
    return Dependency(target)


class DynamoService:
    """A @service-decorated class: config + endpoints + dependencies."""

    def __init__(self, cls: type, config: ServiceConfig):
        self.inner = cls
        self.config = config
        self.endpoints: dict[str, str] = {}  # endpoint name -> method name
        for attr, fn in inspect.getmembers(cls, callable):
            ep_name = getattr(fn, ENDPOINT_ATTR, None)
            if ep_name is not None:
                self.endpoints[ep_name] = attr
        self.dependencies: dict[str, "DynamoService"] = {
            name: dep.target
            for name, dep in vars(cls).items()
            if isinstance(dep, Dependency)
        }

    @property
    def name(self) -> str:
        return self.config.name

    def graph(self) -> list["DynamoService"]:
        """This service + transitive dependencies, dependencies first."""
        seen: dict[str, DynamoService] = {}

        def visit(svc: "DynamoService") -> None:
            if svc.name in seen:
                return
            for dep in svc.dependencies.values():
                visit(dep)
            seen[svc.name] = svc

        visit(self)
        return list(seen.values())

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.inner(*args, **kwargs)

    def __repr__(self) -> str:
        return f"DynamoService({self.name}, endpoints={list(self.endpoints)})"


def service(
    cls: Optional[type] = None,
    *,
    dynamo: Optional[dict[str, Any]] = None,
    resources: Optional[dict[str, Any]] = None,
    replicas: int = 1,
    **config: Any,
) -> Any:
    """Class decorator (reference: sdk service.py:301 @service)."""

    def wrap(c: type) -> DynamoService:
        dyn = dynamo or {}
        return DynamoService(
            c,
            ServiceConfig(
                name=c.__name__,
                namespace=dyn.get("namespace", "dynamo"),
                resources=resources or {},
                replicas=replicas,
                config=config,
            ),
        )

    return wrap(cls) if cls is not None else wrap


def endpoint(name: Optional[str] = None) -> Callable:
    """Method decorator (reference: sdk decorators.py:27 @dynamo_endpoint).
    The method must be ``async def fn(self, request)`` returning either an
    async iterator (streamed) or a single value."""

    def wrap(fn: Callable) -> Callable:
        setattr(fn, ENDPOINT_ATTR, name or fn.__name__)
        return fn

    return wrap
