"""Local graph serving: subprocess supervisor with a store control plane.

Reference: deploy/sdk/src/dynamo/sdk/cli/serving.py:163-300 (circus
arbiter, one watcher per component) + the planner's circus controller
(components/planner/src/dynamo/planner/circusd.py). Here the supervisor
is a plain asyncio parent process:

- one child per component replica, running ``dynamo_tpu.sdk.runner``;
- crash supervision with capped restarts;
- a **control subject** ``{ns}.supervisor.control`` on the store accepts
  {op: add|remove, component} commands — this is the planner's scaling
  lever (reference: local_connector.py add/remove_component);
- live replica state mirrored to the store key ``{ns}/supervisor/state``
  and a local statefile (reference: ~/.dynamo/state/{ns}.json,
  docs/planner.md:91-128).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.sdk.allocator import TpuAllocator
from dynamo_tpu.sdk.service import DynamoService
from dynamo_tpu.store.base import Store

log = logging.getLogger("dynamo_tpu.sdk.serving")

CONTROL_SUBJECT = "supervisor.control"
MAX_RESTARTS = 3


def state_key(namespace: str) -> str:
    return f"{namespace}/supervisor/state"


def state_file(namespace: str) -> str:
    return os.path.join(
        os.environ.get("DYN_LOCAL_STATE_DIR", os.path.expanduser("~/.dynamo_tpu")),
        "state", f"{namespace}.json",
    )


@dataclass
class _Child:
    name: str  # "<component>/<replica-idx>"
    proc: asyncio.subprocess.Process
    restarts: int = 0


@dataclass
class Supervisor:
    entry: DynamoService
    store: Store
    namespace: str
    store_host: str = "127.0.0.1"
    store_port: int = 4222
    overrides: dict[str, dict] = field(default_factory=dict)  # per-component
    allocator: Optional[TpuAllocator] = None
    service_specs: dict[str, str] = field(default_factory=dict)  # name -> module:Attr

    def __post_init__(self) -> None:
        self.allocator = self.allocator or TpuAllocator()
        self._children: dict[str, _Child] = {}
        self._replica_counter: dict[str, int] = {}
        self._services = {s.name: s for s in self.entry.graph()}
        self._stopping = False

    # -- child lifecycle ---------------------------------------------------
    async def _spawn(self, svc: DynamoService) -> _Child:
        idx = self._replica_counter.get(svc.name, 0)
        self._replica_counter[svc.name] = idx + 1
        name = f"{svc.name}/{idx}"
        alloc = self.allocator.allocate(name, svc.config.resources)
        spec = self.service_specs.get(svc.name)
        if spec is None:
            raise RuntimeError(
                f"no module spec for service {svc.name}; pass service_specs"
            )
        overrides = dict(self.overrides.get(svc.name, {}))
        env = {**os.environ, **alloc.env()}
        cmd = [
            sys.executable, "-m", "dynamo_tpu.sdk.runner", spec,
            "--store-host", self.store_host,
            "--store-port", str(self.store_port),
        ]
        if overrides:
            cmd += ["--config", json.dumps(overrides)]
        proc = await asyncio.create_subprocess_exec(*cmd, env=env)
        child = _Child(name, proc)
        self._children[name] = child
        log.info("spawned %s (pid %d, chips %s)", name, proc.pid, alloc.chip_ids)
        return child

    async def _stop_child(
        self, name: str, sig: int = signal.SIGTERM, grace_s: float = 15
    ) -> None:
        child = self._children.pop(name, None)
        if child is None:
            return
        self.allocator.release(name)
        if child.proc.returncode is None:
            child.proc.send_signal(sig)
            try:
                await asyncio.wait_for(child.proc.wait(), timeout=grace_s)
            except asyncio.TimeoutError:
                child.proc.kill()
                await child.proc.wait()
        log.info("stopped %s", name)

    def replicas(self, component: str) -> list[str]:
        return sorted(
            n for n in self._children if n.startswith(component + "/")
        )

    # -- control plane -----------------------------------------------------
    async def handle_command(self, cmd: dict[str, Any]) -> dict[str, Any]:
        op = cmd.get("op")
        comp = cmd.get("component", "")
        svc = self._services.get(comp)
        try:
            if op == "add":
                if svc is None:
                    raise KeyError(f"unknown component {comp!r}")
                child = await self._spawn(svc)
                await self._publish_state()
                return {"ok": True, "name": child.name}
            if op == "remove":
                names = self.replicas(comp)
                if not names:
                    return {"ok": False, "error": f"no replicas of {comp!r}"}
                await self._stop_child(names[-1])  # newest first
                await self._publish_state()
                return {"ok": True, "name": names[-1]}
            if op == "drain":
                # graceful scale-down (docs/robustness.md "Graceful
                # drain"): same SIGTERM as remove — the worker's own
                # handler runs the drain protocol — but with the grace
                # widened past the drain deadline so a busy worker
                # hands its streams off instead of being killed at 15s.
                # Retires the OLDEST replica (remove trims the newest):
                # that is what lets rolling_restart cycle the whole
                # fleet instead of re-restarting its own replacements.
                names = self.replicas(comp)
                if not names:
                    return {"ok": False, "error": f"no replicas of {comp!r}"}
                from dynamo_tpu.runtime.drain import drain_timeout_from_env

                await self._stop_child(
                    names[0], grace_s=drain_timeout_from_env() + 15
                )
                await self._publish_state()
                return {"ok": True, "name": names[0]}
            if op == "state":
                return {"ok": True, "state": self._state()}
            raise ValueError(f"unknown op {op!r}")
        except Exception as exc:
            return {"ok": False, "error": str(exc)}

    def _state(self) -> dict[str, Any]:
        return {
            "components": {
                s.name: {"replicas": len(self.replicas(s.name))}
                for s in self._services.values()
            }
        }

    async def _publish_state(self) -> None:
        data = json.dumps(self._state()).encode()
        await self.store.kv_put(state_key(self.namespace), data)
        path = state_file(self.namespace)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(data.decode())

    async def _control_loop(self) -> None:
        import msgpack

        sub = await self.store.subscribe(f"{self.namespace}.{CONTROL_SUBJECT}")
        async for _subject, payload in sub:
            try:
                cmd = msgpack.unpackb(payload, raw=False)
            except Exception:
                cmd = json.loads(payload.decode())
            result = await self.handle_command(cmd)
            reply_to = cmd.get("reply_to")
            if reply_to:
                await self.store.publish(reply_to, json.dumps(result).encode())

    async def _reaper_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(0.5)
            for name, child in list(self._children.items()):
                rc = child.proc.returncode
                if rc is None or self._stopping:
                    continue
                comp = name.split("/", 1)[0]
                svc = self._services[comp]
                self._children.pop(name)
                self.allocator.release(name)
                if child.restarts >= MAX_RESTARTS:
                    log.error("%s exited rc=%s; restart cap hit", name, rc)
                    continue
                log.warning("%s exited rc=%s; restarting", name, rc)
                new = await self._spawn(svc)
                new.restarts = child.restarts + 1
                await self._publish_state()

    # -- main --------------------------------------------------------------
    async def start(self) -> None:
        for svc in self._services.values():
            for _ in range(max(1, svc.config.replicas)):
                await self._spawn(svc)
        await self._publish_state()
        self._tasks = [
            asyncio.create_task(self._control_loop()),
            asyncio.create_task(self._reaper_loop()),
        ]

    async def shutdown(self) -> None:
        self._stopping = True
        for t in getattr(self, "_tasks", []):
            t.cancel()
        # stop leaves first (reverse dependency order = entry first)
        for svc in reversed(self.entry.graph()):
            for name in self.replicas(svc.name):
                await self._stop_child(name)
