"""Discrete-event fleet simulator: scaling policy as testable code.

No TPUs, no real sleeps — a virtual clock (``sim/core.py``) drives
arrival traces (``sim/traces.py``: diurnal, bursty MMPP, heavy-tail
lengths) through modeled workers (``sim/worker.py``, parameterized from
BENCH_r0x data) while the REAL Planner and AdmissionController run
against it in driven mode, and PR-5 ``FaultPlan``s compose in at
simulated timestamps (``sim/faults.py``). See docs/autoscaling.md.
"""

from dynamo_tpu.sim.core import SimClock, SimLoop, drive
from dynamo_tpu.sim.faults import SimFaultDriver
from dynamo_tpu.sim.fleet import FleetSim, SimConfig, SimConnector
from dynamo_tpu.sim.traces import (
    LengthModel,
    SimRequest,
    bursty_trace,
    diurnal_trace,
    merge_traces,
    poisson_trace,
)
from dynamo_tpu.sim.worker import SimWorker, WorkerProfile

__all__ = [
    "SimClock", "SimLoop", "drive",
    "SimFaultDriver",
    "FleetSim", "SimConfig", "SimConnector",
    "LengthModel", "SimRequest", "bursty_trace", "diurnal_trace",
    "merge_traces", "poisson_trace",
    "SimWorker", "WorkerProfile",
]
