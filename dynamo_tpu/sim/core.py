"""Discrete-event core: a virtual clock over an event heap.

No real time passes anywhere in a simulation: ``SimLoop`` pops
``(timestamp, seq, callback)`` triples in order and advances ``now`` to
each event's timestamp. A million simulated requests cost exactly the
Python time of their event callbacks — the acceptance budget for the
tier-1 replay test (≥100k requests, <30 s wall) rides on this.

Determinism: ties on the timestamp break on a monotone sequence number
assigned at schedule time, so replays with the same seeds pop events in
an identical order regardless of float equality quirks.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Optional


class SimLoop:
    """The event heap + virtual now."""

    def __init__(self) -> None:
        self._heap: list[tuple[float, int, Callable, tuple]] = []
        self._seq = 0
        self._now = 0.0

    @property
    def now(self) -> float:
        return self._now

    def at(self, t: float, fn: Callable, *args: Any) -> None:
        """Schedule ``fn(*args)`` at absolute sim time ``t`` (clamped to
        now — the past is not schedulable)."""
        heapq.heappush(self._heap, (max(t, self._now), self._seq, fn, args))
        self._seq += 1

    def after(self, delay_s: float, fn: Callable, *args: Any) -> None:
        self.at(self._now + max(0.0, delay_s), fn, *args)

    def run(self, until: Optional[float] = None) -> None:
        """Pop events in order until the heap drains (or the next event
        lies beyond ``until``, which is then the final ``now``)."""
        while self._heap:
            t, _, fn, args = self._heap[0]
            if until is not None and t > until:
                break
            heapq.heappop(self._heap)
            self._now = t
            fn(*args)
        if until is not None:
            self._now = max(self._now, until)


class SimClock:
    """The :class:`~dynamo_tpu.utils.clock.Clock` face of a SimLoop.

    ``monotonic()``/``time()`` both return simulated seconds (there is
    no wall/monotonic split in a virtual timeline). ``sleep`` raises:
    sim control loops are *driven* — the fleet calls the planner at the
    right virtual instants instead of the planner sleeping — so any
    await of sim sleep is a bug, not a feature.
    """

    def __init__(self, loop: SimLoop) -> None:
        self._loop = loop

    def monotonic(self) -> float:
        return self._loop.now

    def time(self) -> float:
        return self._loop.now

    async def sleep(self, seconds: float) -> None:
        raise RuntimeError(
            "SimClock.sleep: simulated control loops are driven by the "
            "event heap, not by sleeping (schedule an event instead)"
        )


def drive(coro):
    """Run a coroutine that must complete without awaiting anything
    pending (the driven-planner contract: a SimConnector answers
    immediately, so ``make_adjustments`` never yields to a loop)."""
    try:
        coro.send(None)
    except StopIteration as stop:
        return stop.value
    coro.close()
    raise RuntimeError(
        "driven coroutine awaited a real future inside the simulator"
    )
