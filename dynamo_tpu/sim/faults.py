"""FaultPlan composition: the PR-5 chaos grammar at simulated time.

The same :class:`~dynamo_tpu.faults.plan.FaultPlan` (``DYN_FAULTS``
syntax, JSON files, seeds) drives faults in the simulator, so a chaos
scenario written for a live fleet replays as a what-if against the
virtual one. Rule semantics are identical — per-rule
``random.Random((seed, point, index))`` streams, ``@p``/``@after``/
``@max``/``@match`` — but evaluation has no global side effects (no
process metrics, no process kill): the fleet interprets the fired rules
at its own seams.

Sim injection points and their interpretations (docs/autoscaling.md):

    http.request      per arrival — ``error``/``drop`` fail the request
                      before admission; ``delay=S`` adds S seconds of
                      frontend latency to its TTFT
    engine.step       per worker heartbeat — ``stall=S``/``delay=S``
                      slow that worker's decode by ``stall_factor`` for
                      S simulated seconds
    worker.liveness   per worker heartbeat — ``kill`` removes the
                      worker abruptly: in-flight requests fail, KV
                      vanishes, and only the planner's reconciliation
                      brings capacity back
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.faults.plan import FaultPlan, FaultRule, RuleState

SIM_POINTS = ("http.request", "engine.step", "worker.liveness")


class SimFaultDriver:
    """Side-effect-free re-evaluation of a FaultPlan on the literal
    eligibility algorithm the live injector runs
    (``plan.RuleState.step`` — same counters, same seeded streams),
    minus the acting (the fleet acts) and process-global telemetry."""

    def __init__(self, plan: Optional[FaultPlan]):
        self.plan = plan or FaultPlan()
        self._states = [
            RuleState(rule, self.plan.rule_rng(i))
            for i, rule in enumerate(self.plan.rules)
        ]
        self._by_point: dict[str, list[RuleState]] = {}
        for st in self._states:
            self._by_point.setdefault(st.rule.point, []).append(st)
        self.fired: list[tuple[float, str, str]] = []  # (t, point, kind)

    def due(self, now: float, point: str, **ctx) -> list[FaultRule]:
        """One pass through ``point``; returns the rules that fire."""
        states = self._by_point.get(point)
        if not states:
            return []
        out: list[FaultRule] = []
        for st in states:
            if st.step(ctx):
                self.fired.append((now, st.rule.point, st.rule.kind))
                out.append(st.rule)
        return out

    def stats(self) -> dict:
        return {
            "seed": self.plan.seed,
            "fired_total": len(self.fired),
            "rules": [
                {**st.rule.to_dict(), "passes": st.passes, "fires": st.fires}
                for st in self._states
            ],
        }
