"""FleetSim: a serving fleet as a discrete-event system.

The simulator wires the REAL control-plane code — the
:class:`~dynamo_tpu.planner.planner.Planner` (driven mode, virtual
clock) and the REAL :class:`~dynamo_tpu.http.admission.AdmissionController`
(token bucket on the virtual clock) — to modeled workers
(:mod:`dynamo_tpu.sim.worker`), a prefill server pool, and a
:class:`~dynamo_tpu.sim.faults.SimFaultDriver` interpreting PR-5
FaultPlans at simulated timestamps. Scaling policy, admission limits,
the degradation ladder, and self-healing reconciliation thereby become
tier-1-testable artifacts: ≥100k requests replay in seconds, and two
runs at the same seed are bit-identical.

Request lifecycle::

    arrival ──http.request faults──> admission (429?) ──> prefill pool
        ──> decode placement (slots + KV blocks; least-loaded)
        ──> analytic finish at output_tokens × itl(occupancy)
        ──> SLO scoring (TTFT + ITL vs targets) → rolling window

The one modeling approximation: a request keeps the inter-token latency
of the occupancy it was admitted into (no per-token re-evaluation) —
cheap enough for million-request what-ifs, load-sensitive enough that
fleet sizing moves attainment the way the bench data says it should.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.faults.plan import FaultPlan
from dynamo_tpu.http.admission import AdmissionConfig, AdmissionController
from dynamo_tpu.planner.degradation import LadderPolicy
from dynamo_tpu.planner.planner import Planner, PlannerConfig
from dynamo_tpu.sim.core import SimClock, SimLoop, drive
from dynamo_tpu.sim.faults import SimFaultDriver
from dynamo_tpu.sim.traces import SimRequest
from dynamo_tpu.sim.worker import SimWorker, WorkerProfile


@dataclass
class SimConfig:
    initial_decode: int = 2
    initial_prefill: int = 1
    # SLO targets every finished request is scored against
    slo_ttft_ms: float = 2000.0
    slo_itl_ms: float = 60.0
    slo_window: int = 512
    heartbeat_interval_s: float = 1.0
    metric_interval_s: float = 5.0
    drain_s: float = 120.0
    # admission (level-0 baseline; the degradation ladder tightens it)
    max_queue_depth: int = 400
    max_kv_usage: float = 0.98
    retry_after_s: float = 1.0
    probe_rate_per_s: float = 1.0
    probe_burst: float = 2.0
    spec_enabled: bool = True
    # mid-stream migration (docs/robustness.md "Mid-stream migration"):
    # on by default to match the live routers — a worker kill re-queues
    # its in-flight streams as resumes (re-prefill of prompt+emitted,
    # then the remaining tokens) instead of scoring them lost. Resumes
    # bypass admission, exactly like the live plane. False restores the
    # PR-5 every-death-is-lost behavior.
    migration: bool = True
    # fraction of resumes landing on a cache-hot target (fleet-wide
    # prefix reuse / a prior placement of the same prefix): those pay
    # the cheap onboard rate instead of a full re-prefill. Drawn from a
    # per-resume seeded stream so replays stay bit-identical.
    resume_cache_hot_frac: float = 0.0
    # graceful drain (docs/robustness.md "Graceful drain & rolling
    # restarts"): a worker.drain fault hands every active stream off at
    # a step boundary — zero lost finish to synthesize, and because the
    # departing worker pre-publishes its KV catalog entries the resume
    # pays only the handoff latency plus an onboard-rate re-prefill
    # (vs a kill's full recompute). drain_proactive additionally routes
    # planner scale-downs through the migrating drain instead of the
    # stop-admitting-and-wait removal (off by default so existing
    # seeded runs stay bit-identical).
    drain_handoff_s: float = 0.05
    drain_proactive: bool = False
    # reactive-path detection latency: a KILLED worker's streams are
    # only re-dispatched once the router notices the death (stream
    # error + failover backoff) — the asymmetry the drain protocol
    # removes. 0 (default) keeps the pre-drain instantaneous-requeue
    # model, so existing seeded runs stay bit-identical.
    kill_detect_s: float = 0.0
    # injected stalls multiply decode latency by this until they lapse
    stall_factor: float = 4.0
    # ladder tightening: level>=1 scales the admission caps, level 3
    # clamps the queue to a shallow shed line
    degrade_queue_factor: float = 0.5
    degrade_kv_factor: float = 0.95
    shed_queue_depth: int = 32
    # fleet KV fabric (kvbm/fabric.py) modeled at prefix-family
    # granularity: the first prefill of a prefix_id publishes it to the
    # fleet catalog (G2 somewhere in the fleet); later requests of the
    # family fetch the shared head at the fabric rate instead of
    # recomputing it. Watermark pressure demotes least-popular families
    # — hot ones to the shared bucket (slower fetch, survives), cold
    # ones out of the fabric entirely (a fleet-wide miss; their home is
    # a single worker's private disk). The planner's "demote cold KV"
    # rung scales fabric_host_prefixes via LadderPolicy.
    fabric: bool = False
    fabric_host_prefixes: int = 6  # G2 capacity, in prefix families
    fabric_hot_min_hits: int = 2
    # fetch rates: peer host tier ≫ shared bucket, both ≫ the 20k tok/s
    # prefill recompute they replace, both ≪ the 200k tok/s local onboard
    fabric_peer_fetch_tok_s: float = 60_000.0
    fabric_bucket_fetch_tok_s: float = 30_000.0
    worker: WorkerProfile = field(default_factory=WorkerProfile)


@dataclass
class _InFlight:
    req: SimRequest
    frontend_delay: float = 0.0
    worker: int = -1
    ttft: float = 0.0
    itl: float = 0.0
    # mid-stream migration state: tokens delivered before the last
    # worker death, how many times this stream resumed, whether the
    # current resume found a cache-hot target, and when the current
    # decode segment started emitting
    emitted: int = 0
    resumed_n: int = 0
    resume_hot: bool = False
    decode_start_t: float = 0.0


class SimConnector:
    """The planner's connector, backed by the simulated fleet. Decode
    adds honor the worker profile's provisioning delay (the ack is
    immediate, capacity arrives ``spawn_delay_s`` later — exactly the
    window reconciliation must not mistake for a second loss)."""

    def __init__(self, fleet: "FleetSim"):
        self.fleet = fleet

    async def add_component(self, component: str) -> bool:
        f = self.fleet
        if component == f.prefill_component:
            f.prefill_servers += 1
            f._drain_prefill()
            return True
        f.pending_spawns += 1
        f.loop.after(f.config.worker.spawn_delay_s, f._spawn_worker)
        return True

    async def remove_component(self, component: str) -> bool:
        f = self.fleet
        if component == f.prefill_component:
            if f.prefill_servers <= 0:
                return False
            f.prefill_servers -= 1
            return True
        # drain the least-loaded worker (ties: newest first)
        candidates = [
            w for w in f.workers.values() if not w.draining
        ]
        if not candidates:
            return False
        victim = min(candidates, key=lambda w: (w.occupancy, -w.wid))
        victim.draining = True
        if victim.occupancy == 0:
            f._remove_worker(victim.wid)
        return True

    async def drain_component(self, component: str) -> bool:
        """The planner's graceful scale-down. With ``drain_proactive``
        the victim migrates its active streams through the drain
        protocol (zero lost tokens, onboard-rate resumes); off (the
        default) it falls back to remove_component's stop-admitting-
        and-wait behavior so existing seeded runs stay bit-identical."""
        f = self.fleet
        if component == f.prefill_component or not f.config.drain_proactive:
            return await self.remove_component(component)
        candidates = [w for w in f.workers.values() if not w.draining]
        if not candidates:
            return False
        victim = min(candidates, key=lambda w: (w.occupancy, -w.wid))
        f._drain_worker(victim.wid)
        return True


class FleetSim:
    def __init__(
        self,
        trace: list[SimRequest],
        config: Optional[SimConfig] = None,
        plan: Optional[FaultPlan] = None,
    ):
        self.config = config or SimConfig()
        self.trace = trace
        self.loop = SimLoop()
        self.clock = SimClock(self.loop)
        self.faults = SimFaultDriver(plan)
        self.workers: dict[int, SimWorker] = {}
        self._next_wid = 0
        self.pending_spawns = 0
        self.prefill_servers = self.config.initial_prefill
        self.prefill_component = "prefill"
        self._prefill_busy = 0
        self._prefill_queue: deque[_InFlight] = deque()
        self._decode_queue: deque[_InFlight] = deque()
        self._inflight: dict[int, _InFlight] = {}
        self._base_admission = AdmissionConfig(
            max_queue_depth=self.config.max_queue_depth,
            max_kv_usage=self.config.max_kv_usage,
            retry_after_s=self.config.retry_after_s,
            probe_rate_per_s=self.config.probe_rate_per_s,
            probe_burst=self.config.probe_burst,
        )
        self.admission = AdmissionController(
            AdmissionConfig(**vars(self._base_admission)),
            load_fn=self._load_snapshot,
            clock=self.clock.monotonic,
        )
        self.spec_enabled = self.config.spec_enabled
        self.ladder = LadderPolicy(
            queue_factor=self.config.degrade_queue_factor,
            kv_factor=self.config.degrade_kv_factor,
            shed_queue_depth=self.config.shed_queue_depth,
        )
        self.planner: Optional[Planner] = None
        # scoreboard
        self._outcomes: deque = deque(maxlen=max(1, self.config.slo_window))
        self.arrived = 0
        self.shed = 0
        self.failed_frontend = 0
        self.killed_inflight = 0  # in-flight streams hit by a kill
        self.resumed = 0          # of those, mid-stream (≥1 token) resumes
        self.resumed_hot = 0      # resumes onto a cache-hot target
        self.refailed = 0         # pre-first-token kills replayed as failover
        self.lost_inflight = 0    # of those, dropped (migration off)
        self.completed = 0
        self.met = 0
        self.goodput_tokens = 0
        self.workers_killed = 0
        self.workers_drained = 0  # planned departures (drain protocol)
        self.drained_inflight = 0  # streams handed off by drains
        self.workers_spawned = 0
        self.step_errors = 0
        self.degradation_level = 0
        # fleet KV fabric scoreboard (prefix_id -> tier/hits/last_touch)
        self._fabric: dict[int, dict[str, Any]] = {}
        self._fabric_scale = 1.0
        self.prefix_requests = 0         # prefill passes carrying a prefix
        self.fleet_hits_host = 0
        self.fleet_hits_bucket = 0
        self.fleet_publishes = 0
        self.fleet_demoted_bucket = 0
        self.fleet_demoted_dropped = 0
        self.fleet_fetched_tokens = 0
        self.reprefill_tokens_avoided = 0
        self.prefilled_tokens = 0        # tokens recomputed at prefill rate
        self.timeline: list[dict[str, Any]] = []
        self.horizon = (trace[-1].t if trace else 0.0) + self.config.drain_s
        self._next_adjust_t = 0.0

    # -- public API ---------------------------------------------------------

    def attach_planner(self, pconfig: Optional[PlannerConfig] = None) -> Planner:
        """Create the driven-mode Planner wired to this fleet: sim
        clock, sim connector, fleet degradation hooks. Planner intent
        starts at the fleet's initial sizes."""
        self.planner = Planner(
            store=None,
            component=None,
            connector=SimConnector(self),
            config=pconfig,
            decode_workers=self.config.initial_decode,
            prefill_workers=self.config.initial_prefill,
            clock=self.clock,
            degradation=self,
        )
        self.prefill_component = self.planner.config.prefill_component
        self._next_adjust_t = self.planner.config.adjustment_interval_s
        return self.planner

    def run(self) -> dict[str, Any]:
        for _ in range(self.config.initial_decode):
            self._spawn_worker(initial=True)
        if self.trace:
            self.loop.at(self.trace[0].t, self._on_arrival, 0)
        self.loop.after(self.config.heartbeat_interval_s, self._heartbeat)
        self.loop.after(self.config.metric_interval_s, self._metric_tick)
        # recurring chains self-terminate past the horizon; whatever
        # remains afterwards is finish events — drain them all
        self.loop.run()
        return self.result()

    # -- degradation ladder (planner DegradationHooks) ----------------------

    def set_level(self, level: int) -> None:
        """Apply a planner rung through the SAME LadderPolicy math live
        serving uses (planner/degradation.py): level 1+ tightens
        admission so queued work stays meetable, level 2+ gives KV back
        by turning draft staging off, level 3 clamps to the shed line."""
        self.degradation_level = level
        cfg = self.admission.config
        base = self._base_admission
        cfg.max_queue_depth, cfg.max_kv_usage = self.ladder.admission_caps(
            base.max_queue_depth, base.max_kv_usage, level
        )
        self.spec_enabled = self.ladder.spec_enabled(
            self.config.spec_enabled, level
        )
        # the "demote cold KV" rung: tighten the fabric's G2 watermark
        # through the SAME LadderPolicy math ServingDegradation applies
        # to a live FleetKvFabric, and demote immediately
        self._fabric_scale = self.ladder.fabric_pressure_scale(level)
        if self.config.fabric:
            self._fabric_enforce()

    # -- load + snapshots ---------------------------------------------------

    def _load_snapshot(self):
        from dynamo_tpu.http.admission import LoadSnapshot

        alive = list(self.workers.values())
        kv = (
            sum(w.kv_usage for w in alive) / len(alive) if alive else 0.0
        )
        return LoadSnapshot(
            queue_depth=len(self._prefill_queue) + len(self._decode_queue),
            active_slots=sum(w.occupancy for w in alive),
            total_slots=sum(w.profile.batch_slots for w in alive),
            kv_usage=kv,
        )

    @property
    def attainment(self) -> float:
        if not self._outcomes:
            return 1.0
        return sum(self._outcomes) / len(self._outcomes)

    def snapshot(self) -> dict[str, float]:
        """The planner-facing view — same keys as Planner.collect().
        ``decode_workers_reporting`` counts only alive workers, exactly
        like the live plane (a provisioning pod publishes no metrics
        until the model is loaded), so the planner's spawn-grace credits
        are genuinely exercised: a replacement it just ordered stays
        invisible for ``spawn_delay_s`` and must not be mistaken for a
        second loss."""
        alive = list(self.workers.values())
        kv = (
            sum(w.kv_usage for w in alive) / len(alive) if alive else 1.0
        )
        depth = float(len(self._prefill_queue))
        return {
            "kv_load_mean": kv,
            "decode_workers_reporting": float(len(self.workers)),
            "prefill_queue_depth": depth,
            "prefill_queue_per_worker": depth / max(1, self.prefill_capacity),
            "slo_attainment_mean": self.attainment,
            "goodput_tokens_total": float(self.goodput_tokens),
            "degradation_level": float(self.degradation_level),
            "ts": self.clock.time(),
        }

    # -- workers ------------------------------------------------------------

    def _spawn_worker(self, initial: bool = False) -> None:
        if not initial:
            self.pending_spawns = max(0, self.pending_spawns - 1)
        wid = self._next_wid
        self._next_wid += 1
        self.workers[wid] = SimWorker(wid, self.config.worker)
        self.workers_spawned += 1
        self._drain_decode()

    def _remove_worker(self, wid: int) -> None:
        self.workers.pop(wid, None)

    def _kill_worker(self, wid: int) -> None:
        w = self.workers.pop(wid, None)
        if w is None:
            return
        self.workers_killed += 1
        now = self.loop.now
        requeued = False
        for rid in list(w.active):
            rec = self._inflight.get(rid)
            if rec is None:
                continue
            self.killed_inflight += 1
            if not self.config.migration:
                # PR-5 behavior: the stream is gone — a hard SLO miss,
                # scored so attainment feels the outage
                self._inflight.pop(rid, None)
                self.lost_inflight += 1
                self._outcomes.append(False)
                continue
            # mid-stream migration (mirrors the live routers): tokens
            # already delivered stay delivered; the request re-prefills
            # prompt+emitted elsewhere (cheap onboard when the target is
            # cache-hot) and decodes the remainder. The migration gap
            # lands in the stream's mean ITL at finish time. Resumes
            # re-enter the prefill queue directly — they already paid
            # for admission, exactly like the live bypass.
            seg = 0
            if rec.itl > 0 and now > rec.decode_start_t:
                seg = int((now - rec.decode_start_t) / rec.itl)
            remaining_before = rec.req.output_tokens - rec.emitted
            rec.emitted += max(0, min(seg, remaining_before - 1))
            if rec.emitted > 0:
                # a true mid-stream resume — books like the live
                # plane's dynamo_midstream_resumes_total{ok}
                rec.resumed_n += 1
                self.resumed += 1
            else:
                # the kill landed before this request's FIRST token —
                # the live plane replays it from scratch
                # (pre-first-token failover, FAILOVER_RETRIES), so
                # resumed_n stays 0, the re-placement recomputes its
                # TTFT, and it is NOT counted as a resume
                self.refailed += 1
            rec.worker = -1  # invalidates the pending finish event
            if rec.emitted > 0:
                rng = random.Random(f"resume:{rid}:{rec.resumed_n}")
                rec.resume_hot = (
                    rng.random() < self.config.resume_cache_hot_frac
                )
                if rec.resume_hot:
                    self.resumed_hot += 1
            else:
                # failover replays pay a full re-prefill, like live
                rec.resume_hot = False
            if self.config.kill_detect_s > 0:
                self.loop.after(
                    self.config.kill_detect_s, self._requeue_resume, rec
                )
            else:
                self._prefill_queue.append(rec)
                requeued = True
        if requeued:
            self._drain_prefill()

    def _drain_worker(self, wid: int) -> None:
        """Graceful counterpart of ``_kill_worker``: the worker hands
        every active stream off at a step boundary. Delivered tokens
        stay delivered (same commit-log math as a kill, but nothing to
        synthesize), and because the departing worker pre-publishes its
        KV catalog entries the resume always rides the onboard rate —
        the kill path's full recompute is exactly the cost this
        protocol exists to avoid. Each resume re-enters prefill after
        ``drain_handoff_s`` (flag publish + MIGRATE + re-dispatch)."""
        w = self.workers.pop(wid, None)
        if w is None:
            return
        self.workers_drained += 1
        now = self.loop.now
        for rid in list(w.active):
            rec = self._inflight.get(rid)
            if rec is None:
                continue
            self.drained_inflight += 1
            seg = 0
            if rec.itl > 0 and now > rec.decode_start_t:
                seg = int((now - rec.decode_start_t) / rec.itl)
            remaining_before = rec.req.output_tokens - rec.emitted
            rec.emitted += max(0, min(seg, remaining_before - 1))
            if rec.emitted > 0:
                rec.resumed_n += 1
                self.resumed += 1
                self.resumed_hot += 1
            else:
                # drained before the first token: replayed from scratch
                # (TTFT recomputes), like the live pre-first-token path
                self.refailed += 1
            rec.worker = -1  # invalidates the pending finish event
            rec.resume_hot = True
            self.loop.after(
                self.config.drain_handoff_s, self._requeue_resume, rec
            )

    def _requeue_resume(self, rec: _InFlight) -> None:
        if rec.req.rid not in self._inflight:
            return
        self._prefill_queue.append(rec)
        self._drain_prefill()

    # -- request lifecycle --------------------------------------------------

    def _on_arrival(self, index: int) -> None:
        req = self.trace[index]
        if index + 1 < len(self.trace):
            self.loop.at(self.trace[index + 1].t, self._on_arrival, index + 1)
        self.arrived += 1
        frontend_delay = 0.0
        for rule in self.faults.due(
            self.loop.now, "http.request", rid=f"sim-{req.rid}"
        ):
            if rule.kind in ("error", "drop"):
                self.failed_frontend += 1
                return
            if rule.kind in ("delay", "stall"):
                frontend_delay += rule.delay_s
        if self.admission.check() is not None:
            self.shed += 1
            # sheds are SLO misses in the rolling window (mirrors the
            # live AdmissionController's on_shed -> SloTracker.note_shed):
            # scoring only admitted traffic would let the planner read
            # ~1.0 attainment while the frontend 429s the overload away,
            # and the SLO-breach scale-up would never fire
            self._outcomes.append(False)
            return
        rec = _InFlight(req=req, frontend_delay=frontend_delay)
        self._inflight[req.rid] = rec
        self._prefill_queue.append(rec)
        self._drain_prefill()

    @property
    def prefill_capacity(self) -> int:
        """Concurrent prefills: the dedicated pool, or — at zero prefill
        workers (aggregated mode) — the decode workers prefill locally."""
        return self.prefill_servers or max(1, len(self.workers))

    def _drain_prefill(self) -> None:
        while self._prefill_queue and self._prefill_busy < self.prefill_capacity:
            rec = self._prefill_queue.popleft()
            self._prefill_busy += 1
            # the frontend fault delay applies once (the first pass);
            # resumes re-prefill prompt + delivered tokens, at onboard
            # speed when the placement is cache-hot
            delay, rec.frontend_delay = rec.frontend_delay, 0.0
            self.loop.after(
                self._prefill_duration(rec) + delay,
                self._on_prefill_done, rec,
            )

    def _prefill_duration(self, rec: _InFlight) -> float:
        """Seconds this prefill pass occupies a prefill slot, split
        between fabric fetch (the shared head, when the fleet catalog
        hits) and recompute (everything else). Also the fabric's
        publish/touch point — this is where a live KVBM's pump lands
        blocks in G2 and prefetch pulls them from peers."""
        w = self.config.worker
        tokens = rec.req.prompt_tokens + rec.emitted
        if rec.resume_hot:
            # cache-hot resume: the whole re-prefill rides the local
            # onboard path (no recompute, no fabric round trip)
            return tokens / w.onboard_tok_s
        if not self.config.fabric or rec.req.prefix_id < 0:
            self.prefilled_tokens += tokens
            return tokens / w.prefill_tok_s
        pid = rec.req.prefix_id
        ptoks = min(rec.req.prefix_tokens, tokens)
        self.prefix_requests += 1
        now = self.loop.now
        entry = self._fabric.get(pid)
        if entry is None:
            # first sighting fleet-wide: pay the full prefill once, then
            # publish the family to the catalog (G2 on this placement)
            self._fabric[pid] = {"tier": "host", "hits": 1, "last": now}
            self.fleet_publishes += 1
            self._fabric_enforce()
            self.prefilled_tokens += tokens
            return tokens / w.prefill_tok_s
        entry["hits"] += 1
        entry["last"] = now
        if entry["tier"] == "host":
            self.fleet_hits_host += 1
            fetch_rate = self.config.fabric_peer_fetch_tok_s
        else:
            self.fleet_hits_bucket += 1
            fetch_rate = self.config.fabric_bucket_fetch_tok_s
            # a bucket hit promotes the family back into G2 (the live
            # onboard inserts fetched blocks into the host tier)
            entry["tier"] = "host"
            self._fabric_enforce()
        self.fleet_fetched_tokens += ptoks
        self.reprefill_tokens_avoided += ptoks
        rest = tokens - ptoks
        self.prefilled_tokens += rest
        return ptoks / fetch_rate + rest / w.prefill_tok_s

    def _fabric_enforce(self) -> None:
        """Watermark pressure at prefix-family granularity: when more
        families sit in G2 than the (ladder-scaled) capacity, demote
        popularity-weighted victims — least-hit first, stalest breaking
        ties. Hot families go to the shared bucket (still fleet-
        fetchable, slower); cold ones leave the fabric (their only copy
        is one worker's private disk — a fleet-wide miss)."""
        cap = max(1, int(self.config.fabric_host_prefixes
                         * self._fabric_scale))
        host = [(pid, e) for pid, e in self._fabric.items()
                if e["tier"] == "host"]
        excess = len(host) - cap
        if excess <= 0:
            return
        host.sort(key=lambda pe: (pe[1]["hits"], pe[1]["last"], pe[0]))
        for pid, e in host[:excess]:
            if e["hits"] >= self.config.fabric_hot_min_hits:
                e["tier"] = "bucket"
                self.fleet_demoted_bucket += 1
            else:
                del self._fabric[pid]
                self.fleet_demoted_dropped += 1

    def _on_prefill_done(self, rec: _InFlight) -> None:
        self._prefill_busy = max(0, self._prefill_busy - 1)
        self._drain_prefill()
        if rec.req.rid not in self._inflight:
            return  # lost to a kill while prefilling (worker-agnostic)
        if not self._try_place(rec):
            self._decode_queue.append(rec)

    def _try_place(self, rec: _InFlight) -> bool:
        blocks = self.config.worker.blocks_for(
            rec.req.prompt_tokens, rec.req.output_tokens, self.spec_enabled
        )
        candidates = [
            w for w in self.workers.values() if w.can_admit(blocks)
        ]
        if not candidates:
            return False
        worker = min(candidates, key=lambda w: (w.kv_usage, w.occupancy, w.wid))
        worker.admit(rec.req.rid, blocks)
        now = self.loop.now
        rec.worker = worker.wid
        if rec.resumed_n == 0:
            # a resume's first token already streamed before the kill:
            # its TTFT stands; only the original placement sets it
            rec.ttft = now - rec.req.t + self.config.worker.first_step_s
        rec.itl = worker.itl_s(now, self.spec_enabled)
        rec.decode_start_t = now + self.config.worker.first_step_s
        remaining = rec.req.output_tokens - rec.emitted
        self.loop.after(
            self.config.worker.first_step_s + remaining * rec.itl,
            self._on_finish, rec.req.rid, worker.wid,
        )
        return True

    def _on_finish(self, rid: int, wid: int) -> None:
        # get-then-pop: a STALE finish event (superseded by a kill that
        # migrated this request elsewhere) must not evict the live
        # record — only the finish from the request's current worker
        # consumes it
        rec = self._inflight.get(rid)
        if rec is None or rec.worker != wid:
            return  # superseded by a kill
        self._inflight.pop(rid, None)
        worker = self.workers.get(wid)
        if worker is not None and rid in worker.active:
            worker.release(rid)
            if worker.draining and worker.occupancy == 0:
                self._remove_worker(wid)
        itl = rec.itl
        if rec.resumed_n:
            # the migration gap (re-prefill + queue wait) lands in the
            # stream's mean inter-token latency, exactly as the live
            # SLO tracker (mean decode ITL) would observe it
            first_token_t = rec.req.t + rec.ttft
            itl = (self.loop.now - first_token_t) / max(
                1, rec.req.output_tokens
            )
        met = (
            rec.ttft * 1e3 <= self.config.slo_ttft_ms
            and itl * 1e3 <= self.config.slo_itl_ms
        )
        self._outcomes.append(met)
        self.completed += 1
        if met:
            self.met += 1
            self.goodput_tokens += rec.req.output_tokens
        self._drain_decode()

    def _drain_decode(self) -> None:
        while self._decode_queue:
            if not self._try_place(self._decode_queue[0]):
                return
            self._decode_queue.popleft()

    # -- recurring chains ---------------------------------------------------

    def _heartbeat(self) -> None:
        now = self.loop.now
        for wid in sorted(self.workers):
            worker = self.workers.get(wid)
            if worker is None:
                continue
            for rule in self.faults.due(now, "engine.step", worker=f"w{wid}"):
                if rule.kind in ("stall", "delay"):
                    worker.slow_until = now + rule.delay_s
                    worker.slow_factor = self.config.stall_factor
                elif rule.kind == "error":
                    self.step_errors += 1  # quarantine absorbs it
            for rule in self.faults.due(
                now, "worker.liveness", worker=f"w{wid}"
            ):
                if rule.kind == "kill":
                    self._kill_worker(wid)
            # planned departure: any rule at worker.drain runs the
            # graceful protocol on this worker (the kill-vs-drain A/B
            # fires the same schedule at both points and diffs the dip)
            for rule in self.faults.due(now, "worker.drain", worker=f"w{wid}"):
                self._drain_worker(wid)
        if now + self.config.heartbeat_interval_s <= self.horizon:
            self.loop.after(self.config.heartbeat_interval_s, self._heartbeat)

    def _metric_tick(self) -> None:
        snap = self.snapshot()
        self.timeline.append(snap)
        if self.planner is not None and self.loop.now >= self._next_adjust_t:
            drive(self.planner.make_adjustments(snap))
            self._next_adjust_t = (
                self.loop.now + self.planner.config.adjustment_interval_s
            )
        if self.loop.now + self.config.metric_interval_s <= self.horizon:
            self.loop.after(self.config.metric_interval_s, self._metric_tick)

    # -- results ------------------------------------------------------------

    def result(self) -> dict[str, Any]:
        # _inflight spans arrival -> finish/kill, so prefill- and
        # decode-queued requests are already in it; adding queue lengths
        # would double-count anything still queued at sim end
        unfinished = len(self._inflight)
        return {
            "requests": self.arrived,
            "completed": self.completed,
            "met": self.met,
            "shed": self.shed,
            "failed_frontend": self.failed_frontend,
            "killed_inflight": self.killed_inflight,
            "resumed": self.resumed,
            "resumed_hot": self.resumed_hot,
            "refailed": self.refailed,
            "lost_inflight": self.lost_inflight,
            "unfinished": unfinished,
            # of ADMITTED work (the Tail-at-Scale contract: what you
            # accept, you serve well)
            "slo_attainment": (
                self.met / self.completed if self.completed else 1.0
            ),
            # of OFFERED load: shed, frontend-failed, and killed
            # requests all count as misses, so a policy cannot score
            # 1.0 by rejecting the traffic (the bench headline)
            "slo_attainment_offered": (
                self.met / self.arrived if self.arrived else 1.0
            ),
            "final_window_attainment": self.attainment,
            "goodput_tokens": self.goodput_tokens,
            "goodput_tok_s": self.goodput_tokens / max(1e-9, self.loop.now),
            "workers_spawned": self.workers_spawned,
            "workers_killed": self.workers_killed,
            "workers_drained": self.workers_drained,
            "drained_inflight": self.drained_inflight,
            "step_errors": self.step_errors,
            "faults_fired": len(self.faults.fired),
            "degradation_level": self.degradation_level,
            "decode_workers_final": len(self.workers),
            "prefill_servers_final": self.prefill_servers,
            # fleet KV fabric A/B surface (bench.py --kvfleet headline):
            # prefilled_tokens is the recompute bill — with the fabric
            # on, every fleet hit moves its shared head from this figure
            # into fleet_fetched_tokens
            "fabric": {
                "enabled": self.config.fabric,
                "prefix_requests": self.prefix_requests,
                "fleet_hits": self.fleet_hits_host + self.fleet_hits_bucket,
                "fleet_hits_host": self.fleet_hits_host,
                "fleet_hits_bucket": self.fleet_hits_bucket,
                "fleet_hit_rate": (
                    (self.fleet_hits_host + self.fleet_hits_bucket)
                    / self.prefix_requests if self.prefix_requests else 0.0
                ),
                "publishes": self.fleet_publishes,
                "demoted_bucket": self.fleet_demoted_bucket,
                "demoted_dropped": self.fleet_demoted_dropped,
                "fleet_fetched_tokens": self.fleet_fetched_tokens,
                "reprefill_tokens_avoided": self.reprefill_tokens_avoided,
                "prefilled_tokens": self.prefilled_tokens,
            },
            "planner": (
                {
                    "decode_intent": self.planner.decode_workers,
                    "prefill_intent": self.planner.prefill_workers,
                    "replacements": self.planner.replacements_total,
                    "degradation_level": self.planner.degradation_level,
                }
                if self.planner is not None
                else None
            ),
            "sim_end_s": self.loop.now,
            "timeline": self.timeline,
        }
