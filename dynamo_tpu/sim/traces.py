"""Arrival-trace generators: the offered load side of the simulator.

Three canonical shapes, all seeded and deterministic:

- :func:`diurnal_trace` — a nonhomogeneous Poisson process whose rate
  follows a day-night sinusoid (the planner-benchmark workload at
  fleet scale), sampled by Lewis-Shedler thinning;
- :func:`bursty_trace` — a 2-state Markov-modulated Poisson process
  (calm/burst), the flash-crowd shape that stresses admission control
  and scale-up latency;
- request/output lengths from :class:`LengthModel` — clamped lognormal
  heavy tails (the BurstGPT/ShareGPT-like shape: most requests short,
  a fat tail of long ones that dominates KV pressure).

Every generator returns a time-sorted ``list[SimRequest]``; composition
is concatenation + re-sort (``merge_traces``), which is how the bench's
canned "diurnal + burst" workload is built.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass(frozen=True)
class SimRequest:
    rid: int
    t: float  # arrival, simulated seconds
    prompt_tokens: int
    output_tokens: int
    # shared-prefix identity for the fleet KV fabric model
    # (sim/fleet.py): requests carrying the same prefix_id share their
    # first prefix_tokens prompt tokens (a system prompt / few-shot
    # header), which is what cross-worker prefix sharing dedups.
    # -1 = private prompt (no shared prefix).
    prefix_id: int = -1
    prefix_tokens: int = 0


@dataclass(frozen=True)
class PrefixModel:
    """Shared-prefix popularity: a Zipf draw over ``num_prefixes``
    prefix families for ``share_frac`` of requests (the multi-tenant
    shape: a few giant system prompts dominate, a long tail barely
    repeats). Each family's prefix length is deterministic in its id —
    clamped lognormal, seeded by the id — so every request of a family
    agrees on how many leading tokens are shared."""

    num_prefixes: int = 16
    zipf_s: float = 1.1
    share_frac: float = 0.8
    prefix_median: float = 384.0
    prefix_sigma: float = 0.5
    prefix_min: int = 64
    prefix_max: int = 2048

    def prefix_len(self, prefix_id: int) -> int:
        rng = random.Random(f"prefixlen:{prefix_id}")
        n = rng.lognormvariate(math.log(self.prefix_median),
                               self.prefix_sigma)
        return int(min(self.prefix_max, max(self.prefix_min, n)))

    def sample(self, rng: random.Random) -> tuple[int, int]:
        """(prefix_id, prefix_tokens); (-1, 0) for a private prompt."""
        if rng.random() >= self.share_frac:
            return -1, 0
        weights = [1.0 / (k + 1) ** self.zipf_s
                   for k in range(self.num_prefixes)]
        total = sum(weights)
        u = rng.random() * total
        acc = 0.0
        pid = self.num_prefixes - 1
        for k, w in enumerate(weights):
            acc += w
            if u <= acc:
                pid = k
                break
        return pid, self.prefix_len(pid)


@dataclass(frozen=True)
class LengthModel:
    """Clamped lognormal: ``exp(N(mu, sigma))`` clipped to [lo, hi].
    Defaults give ~180-token prompts / ~80-token outputs with a heavy
    right tail (p99 several times the median)."""

    prompt_median: float = 160.0
    prompt_sigma: float = 0.8
    prompt_min: int = 8
    prompt_max: int = 4096
    output_median: float = 64.0
    output_sigma: float = 0.7
    output_min: int = 4
    output_max: int = 1024

    def sample(self, rng: random.Random) -> tuple[int, int]:
        p = rng.lognormvariate(math.log(self.prompt_median),
                               self.prompt_sigma)
        o = rng.lognormvariate(math.log(self.output_median),
                               self.output_sigma)
        return (
            int(min(self.prompt_max, max(self.prompt_min, p))),
            int(min(self.output_max, max(self.output_min, o))),
        )


def _with_prefix(
    rid: int, t: float, p: int, o: int,
    prefixes: Optional[PrefixModel], rng: random.Random,
) -> SimRequest:
    """Attach a shared-prefix draw: the shared head is PREPENDED to the
    sampled private remainder, so prefix-carrying prompts are longer —
    exactly the cost cross-worker sharing exists to avoid recomputing."""
    pid, ptoks = prefixes.sample(rng) if prefixes is not None else (-1, 0)
    return SimRequest(rid=rid, t=t, prompt_tokens=p + ptoks,
                      output_tokens=o, prefix_id=pid, prefix_tokens=ptoks)


def poisson_trace(
    rate_fn: Callable[[float], float],
    rate_max: float,
    duration_s: float,
    seed: int,
    lengths: Optional[LengthModel] = None,
    rid_base: int = 0,
    prefixes: Optional[PrefixModel] = None,
) -> list[SimRequest]:
    """Nonhomogeneous Poisson arrivals by thinning: propose at the
    envelope rate ``rate_max``, accept with ``rate_fn(t)/rate_max``."""
    assert rate_max > 0
    # str seeds hash via sha512 (stable across processes); tuple seeds
    # would fall back to salted hash() and break replay determinism
    rng = random.Random(f"trace:{seed}")
    lengths = lengths or LengthModel()
    out: list[SimRequest] = []
    t = 0.0
    rid = rid_base
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            break
        if rng.random() <= rate_fn(t) / rate_max:
            p, o = lengths.sample(rng)
            out.append(_with_prefix(rid, t, p, o, prefixes, rng))
            rid += 1
    return out


def diurnal_trace(
    duration_s: float,
    seed: int,
    base_rps: float = 10.0,
    peak_rps: float = 40.0,
    period_s: float = 3600.0,
    lengths: Optional[LengthModel] = None,
    rid_base: int = 0,
    prefixes: Optional[PrefixModel] = None,
) -> list[SimRequest]:
    """Sinusoidal day: rate swings base→peak→base once per period."""
    amp = (peak_rps - base_rps) / 2.0
    mid = base_rps + amp

    def rate(t: float) -> float:
        return mid - amp * math.cos(2.0 * math.pi * t / period_s)

    return poisson_trace(rate, peak_rps, duration_s, seed,
                         lengths=lengths, rid_base=rid_base,
                         prefixes=prefixes)


def bursty_trace(
    duration_s: float,
    seed: int,
    calm_rps: float = 15.0,
    burst_rps: float = 90.0,
    mean_calm_s: float = 120.0,
    mean_burst_s: float = 20.0,
    lengths: Optional[LengthModel] = None,
    rid_base: int = 0,
    prefixes: Optional[PrefixModel] = None,
) -> list[SimRequest]:
    """2-state MMPP: exponential dwell in calm/burst, Poisson arrivals
    at the state's rate. The burst state is the admission-control and
    scale-up-latency stressor."""
    rng = random.Random(f"mmpp:{seed}")
    lengths = lengths or LengthModel()
    out: list[SimRequest] = []
    t = 0.0
    rid = rid_base
    bursting = False
    state_end = rng.expovariate(1.0 / mean_calm_s)
    while t < duration_s:
        rate = burst_rps if bursting else calm_rps
        t_next = t + rng.expovariate(rate)
        if t_next >= state_end:
            # no arrival before the state flips; jump to the boundary
            t = state_end
            bursting = not bursting
            state_end = t + rng.expovariate(
                1.0 / (mean_burst_s if bursting else mean_calm_s)
            )
            continue
        t = t_next
        if t >= duration_s:
            break
        p, o = lengths.sample(rng)
        out.append(_with_prefix(rid, t, p, o, prefixes, rng))
        rid += 1
    return out


def merge_traces(*traces: list[SimRequest]) -> list[SimRequest]:
    """Compose workloads (e.g. diurnal baseline + a flash burst): merge
    by arrival time, re-assigning rids so they stay unique and ordered."""
    merged = sorted(
        (r for tr in traces for r in tr), key=lambda r: (r.t, r.rid)
    )
    return [
        SimRequest(rid=i, t=r.t, prompt_tokens=r.prompt_tokens,
                   output_tokens=r.output_tokens,
                   prefix_id=r.prefix_id, prefix_tokens=r.prefix_tokens)
        for i, r in enumerate(merged)
    ]
