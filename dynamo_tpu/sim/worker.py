"""Worker service-time model, parameterized from the BENCH_r0x data.

One ``SimWorker`` stands in for a single-chip decode worker running the
native engine. Three resources bound it, mirroring the real scheduler:

- **batch slots** (``batch_slots``, the engine's max_batch_size);
- **KV blocks** (``kv_blocks`` × ``block_size`` tokens of paged KV);
- **decode bandwidth**: total token throughput follows the measured
  saturating curve — per-sequence inter-token latency grows linearly
  with occupancy, ``itl(n) = (n + n_half) / decode_tok_s_max``, which
  makes fleet ITL the load signal SLO scaling reacts to. The defaults
  (2000 tok/s ceiling, n_half 16) track the BENCH_r04/r05 single-chip
  batch ladder (B=32 ≈ 1514, B=64 ≈ 2181 tok/s).

Speculative decoding is modeled as a throughput/KV trade: when enabled
it multiplies decode speed by ``spec_speedup`` but charges
``spec_kv_overhead_blocks`` extra blocks per sequence (draft staging),
so the degradation ladder's "disable spec" rung genuinely frees KV
under saturation at an ITL cost — the same trade the real engine makes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass(frozen=True)
class WorkerProfile:
    prefill_tok_s: float = 20_000.0   # pooled prefill server speed
    # cache-hot resume onboarding (mid-stream migration, docs/
    # robustness.md): when the target already holds the request's
    # prefix KV, the "re-prefill" is a block onboard, not a forward
    # pass — an order of magnitude cheaper than prefill_tok_s
    onboard_tok_s: float = 200_000.0
    decode_tok_s_max: float = 2_000.0  # saturated per-worker ceiling
    n_half: int = 16                   # occupancy at half-ceiling
    batch_slots: int = 64
    # sized so KV binds just before the slot budget at the default
    # length mix (~4 blocks/seq incl. spec overhead), like a real 16 GB
    # chip after 8B int8 weights: the KV watermark is the planner's
    # primary signal, exactly as in live serving
    kv_blocks: int = 192
    block_size: int = 128
    first_step_s: float = 0.02         # dispatch + first decode step
    spawn_delay_s: float = 30.0        # provisioning latency on scale-up
    spec_speedup: float = 1.25
    spec_kv_overhead_blocks: int = 1

    def blocks_for(self, prompt_tokens: int, output_tokens: int,
                   spec_on: bool) -> int:
        blocks = math.ceil((prompt_tokens + output_tokens) / self.block_size)
        return blocks + (self.spec_kv_overhead_blocks if spec_on else 0)


class SimWorker:
    def __init__(self, wid: int, profile: WorkerProfile):
        self.wid = wid
        self.profile = profile
        self.active: dict[int, int] = {}  # rid -> kv blocks held
        self.kv_used = 0
        self.draining = False
        self.slow_until = 0.0  # injected stall horizon (sim time)
        self.slow_factor = 1.0

    @property
    def occupancy(self) -> int:
        return len(self.active)

    @property
    def kv_usage(self) -> float:
        return self.kv_used / max(1, self.profile.kv_blocks)

    def can_admit(self, blocks: int) -> bool:
        return (
            not self.draining
            and self.occupancy < self.profile.batch_slots
            and self.kv_used + blocks <= self.profile.kv_blocks
        )

    def admit(self, rid: int, blocks: int) -> None:
        self.active[rid] = blocks
        self.kv_used += blocks

    def release(self, rid: int) -> int:
        blocks = self.active.pop(rid)
        self.kv_used -= blocks
        return blocks

    def itl_s(self, now: float, spec_on: bool) -> float:
        """Per-sequence inter-token latency at the CURRENT occupancy
        (evaluated at admission — the model's one approximation: a
        request keeps the ITL of the load it was admitted into)."""
        n = max(1, self.occupancy)
        itl = (n + self.profile.n_half) / self.profile.decode_tok_s_max
        if spec_on:
            itl /= self.profile.spec_speedup
        if now < self.slow_until:
            itl *= self.slow_factor
        return itl
