"""Speculative decoding subsystem: drafting + batched verification.

Converts spare decode-step FLOPs into tokens/step (Leviathan et al.,
"Fast Inference from Transformers via Speculative Decoding"): a cheap
*drafter* proposes up to K tokens per sequence, the engine scores all of
them in ONE jitted forward over K+1 positions through the existing
paged-KV attention, and on-device rejection sampling keeps the longest
accepted prefix plus one freshly sampled token — provably preserving the
target sampling distribution (greedy mode is bit-identical to
non-speculative greedy by construction).

Layout:
- ``drafter.py`` — the pluggable :class:`Drafter` protocol and the two
  dependency-free drafters (prompt-lookup n-gram matching against the
  request's own token history, and a static bigram table loadable from a
  file), plus :func:`build_drafter` for config-string construction.
- ``verify.py`` — the device-side batched verification (jax) used inside
  the engine's jitted spec step, and the host-side unpack helper.

Engine wiring lives in ``engine/engine.py`` (``_run_spec_step`` for the
serial step, ``_spec_pipeline`` for the overlapped one) and
``engine/scheduler.py`` (``reserve_spec_tokens`` / ``build_spec_arrays``
/ ``plan_pipelined_spec``) — see docs/speculative_decoding.md.
"""

from dynamo_tpu.spec.drafter import (
    BigramTableDrafter,
    Drafter,
    NgramDrafter,
    NgramIndex,
    build_drafter,
)
from dynamo_tpu.spec.verify import harvest_spec_output, pack_spec, verify_tokens

__all__ = [
    "BigramTableDrafter",
    "Drafter",
    "NgramDrafter",
    "NgramIndex",
    "build_drafter",
    "harvest_spec_output",
    "pack_spec",
    "verify_tokens",
]
