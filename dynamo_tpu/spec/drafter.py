"""Dependency-free drafters for speculative decoding.

A drafter proposes up to ``k`` continuation tokens from a sequence's own
token history — no second model, no extra weights in HBM, fully testable
on CPU. Both drafters here emit *deterministic* proposals, i.e. the
draft distribution is a point mass at the proposed token; the engine's
rejection sampler (spec/verify.py) exploits that: accept token ``d``
with probability ``p_target(d)``, else resample from the renormalized
remainder — the output distribution is exactly the target's.

Drafters run on the engine thread's host path (between device
dispatches), so ``propose`` must be cheap: the n-gram matcher is a
vectorised numpy scan over the history, the bigram drafter a table walk.
"""

from __future__ import annotations

import json
from typing import Protocol, Sequence, runtime_checkable

import numpy as np


@runtime_checkable
class Drafter(Protocol):
    """Pluggable proposal source for speculative decoding.

    ``kind`` labels telemetry series; ``propose`` returns 0..k draft
    token ids continuing ``token_ids`` (an empty list = no proposal —
    the sequence decodes one token normally that step). ``window`` is
    the history suffix length the drafter actually reads (None = all):
    the engine materializes only that tail per step, keeping the host
    draft phase O(window) instead of O(context).
    """

    kind: str
    window: "int | None"

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        ...


class NgramDrafter:
    """Prompt-lookup / n-gram drafting (as popularised by vLLM and TGI):
    match the sequence's trailing n-gram against its OWN earlier history
    and propose the tokens that followed the most recent prior
    occurrence. Strong on the workloads self-drafting targets —
    summarisation, code editing, RAG, multi-turn chat — where the
    continuation frequently copies spans of the prompt."""

    kind = "ngram"

    def __init__(
        self, max_ngram: int = 3, min_ngram: int = 1,
        max_window: int = 4096,
    ):
        if max_ngram < min_ngram or min_ngram < 1:
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1, got "
                f"{max_ngram}/{min_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # bound the per-step scan: the matcher reads only the last
        # max_window tokens (vLLM's prompt-lookup bounds its scan the
        # same way) — an unbounded scan is O(context) host work per
        # sequence per decode step on the serialized engine thread
        self.window = max_window

    def make_index(
        self, tokens: Sequence[int], seq_len: int
    ) -> "NgramIndex":
        """Per-sequence incremental index (engine keeps one on each
        Sequence): proposals bit-identical to ``propose`` over the same
        window, without the O(window) re-scan every step. ``tokens`` is
        the sequence's trailing ``window`` slice, ``seq_len`` its
        absolute length."""
        return NgramIndex(
            self.max_ngram, self.min_ngram, self.window, tokens, seq_len
        )

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        arr = np.asarray(token_ids, dtype=np.int64)
        n_hist = len(arr)
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1), self.min_ngram - 1, -1):
            suffix = arr[-n:]
            # windows over arr[:-1]: start positions 0..n_hist-1-n, which
            # excludes the terminal suffix itself (it starts at n_hist-n)
            windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            starts = np.nonzero(np.all(windows == suffix, axis=1))[0]
            if len(starts) == 0:
                continue
            i = int(starts[-1])  # most recent prior occurrence
            cont = arr[i + n : i + n + k]
            if len(cont):
                return [int(t) for t in cont]
        return []


class NgramIndex:
    """Incremental per-sequence occurrence index for :class:`NgramDrafter`.

    The from-scratch matcher re-scans ``tail_tokens(window)`` — O(window
    × n-gram orders) host work per sequence per decode step, on the
    serialized engine thread. This index maintains the same answer
    incrementally: ``extend`` appends accepted tokens (O(orders) per
    token), ``propose`` answers in O(orders × (suffix + k)) via hashed
    last-occurrence lookups, and an unwind/truncation (sequence got
    SHORTER) invalidates the whole index — the engine rebuilds it from
    the tail (``NgramDrafter.make_index``), which is the rare path.

    Exactness contract (pinned by tests): for any committed history and
    any ``suffix`` of not-yet-appended tokens,

        index.propose(k, suffix)
        == drafter.propose((tail_tokens(window) + suffix)[-window:], k)

    i.e. proposals are bit-identical to the from-scratch build over the
    drafter's bounded window. The pieces that make that hold:

    - per (order n, gram) the map keeps the last TWO occurrence start
      positions (absolute): the most recent may be the query's own
      terminal occurrence (excluded, exactly as the scratch scan's
      ``windows over arr[:-1]`` excludes it) — the previous one then
      answers;
    - an occurrence at absolute start ``pos`` is visible only when
      ``pos >= total_len - window`` (the scratch scan never sees older
      tokens) and ``pos + n <= total_len - 1`` (a match must have at
      least one continuation token);
    - occurrences that touch the ``suffix`` region cannot be in the map
      (it only indexes committed tokens), so a short linear scan covers
      the boundary — the suffix is at most K+1 tokens;
    - the retained token list compacts to the last ``window`` tokens
      once it doubles, so memory and rebuild cost stay O(window) no
      matter how long the generation runs.
    """

    def __init__(
        self, max_ngram: int, min_ngram: int, window: int,
        tokens: Sequence[int], seq_len: int,
    ):
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.window = window
        # absolute sequence length covered; tokens = its trailing slice
        self.seq_len = int(seq_len)
        self.tokens: list[int] = [int(t) for t in tokens]
        # maps[n]: gram tuple -> (previous_start, last_start), absolute
        self.maps: dict[int, dict] = {
            n: {} for n in range(min_ngram, max_ngram + 1)
        }
        self._index_from(0)

    @property
    def _base(self) -> int:
        """Absolute position of ``self.tokens[0]``."""
        return self.seq_len - len(self.tokens)

    def _index_from(self, start_rel: int) -> None:
        base = self._base
        toks = self.tokens
        for p in range(start_rel, len(toks)):
            for n in range(self.min_ngram, min(self.max_ngram, p + 1) + 1):
                gram = tuple(toks[p + 1 - n : p + 1])
                m = self.maps[n]
                prev = m.get(gram)
                m[gram] = (prev[1] if prev else None, base + p + 1 - n)

    def extend(self, new_tokens: Sequence[int]) -> None:
        """Append committed tokens (the accepted/emitted ones — never
        staged drafts) and index the grams they complete."""
        if not new_tokens:
            return
        start_rel = len(self.tokens)
        self.tokens.extend(int(t) for t in new_tokens)
        self.seq_len += len(new_tokens)
        self._index_from(start_rel)
        if len(self.tokens) > 2 * self.window:
            # amortized O(1)/token compaction: everything older than the
            # window is invisible to propose() anyway
            self.tokens = self.tokens[-self.window:]
            self.maps = {
                n: {} for n in range(self.min_ngram, self.max_ngram + 1)
            }
            self._index_from(0)

    def _at(self, pos: int, sfx: Sequence[int]):
        """Conceptual token at absolute ``pos`` over committed+suffix
        (None when out of range)."""
        if pos < self.seq_len:
            rel = pos - self._base
            return self.tokens[rel] if rel >= 0 else None
        j = pos - self.seq_len
        return int(sfx[j]) if j < len(sfx) else None

    def _slice(self, a: int, b: int, sfx: Sequence[int]) -> list[int]:
        out: list[int] = []
        for pos in range(a, b):
            t = self._at(pos, sfx)
            if t is None:
                break
            out.append(int(t))
        return out

    def propose(self, k: int, suffix: Sequence[int] = ()) -> list[int]:
        sfx = [int(t) for t in suffix]
        total = self.seq_len + len(sfx)
        n_hist = min(self.window, total)  # the scratch scan's length
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        w0 = total - n_hist  # first visible absolute start position
        for n in range(
            min(self.max_ngram, n_hist - 1), self.min_ngram - 1, -1
        ):
            q = tuple(self._slice(total - n, total, sfx))
            best = -1
            # committed-region occurrences: hashed last-two lookup. An
            # entry ends at or before seq_len, so pos + n <= total - 1
            # holds automatically whenever the suffix is nonempty; with
            # an empty suffix it exactly excludes the terminal gram.
            ent = self.maps[n].get(q)
            if ent is not None:
                for pos in (ent[1], ent[0]):
                    if pos is None or pos < w0:
                        continue
                    if pos + n <= total - 1:
                        best = pos
                        break
            # boundary/suffix occurrences (start touches the suffix):
            # not indexable, but the region is at most |sfx| + 1 starts
            lo = max(w0, self.seq_len - n + 1, 0)
            for j in range(total - 1 - n, lo - 1, -1):
                if j <= best:
                    break  # the map already found something more recent
                if self._slice(j, j + n, sfx) == list(q):
                    best = j
                    break
            if best < 0:
                continue
            cont = self._slice(best + n, best + n + k, sfx)
            if cont:
                return cont
        return []


class BigramTableDrafter:
    """Static bigram drafting: a ``[vocab]`` table of most-likely next
    token (-1 = no entry), chained k steps from the sequence's last
    token. The table ships as a file (offline corpus statistics) so the
    drafter costs one array in host RAM and zero device bytes."""

    kind = "bigram"
    window = 1  # only the last token feeds the table walk

    def __init__(self, table: np.ndarray):
        self.table = np.asarray(table, dtype=np.int64).reshape(-1)

    @classmethod
    def from_file(cls, path: str) -> "BigramTableDrafter":
        """Load a table from ``.npz``/``.npy`` (array under key "next"
        for npz) or JSON ({"token_id": next_id, ...})."""
        if path.endswith(".npz"):
            with np.load(path) as z:
                return cls(z["next"])
        if path.endswith(".npy"):
            return cls(np.load(path))
        with open(path) as f:
            mapping = json.load(f)
        pairs = {int(t): int(n) for t, n in mapping.items()}
        size = max(pairs) + 1 if pairs else 1
        table = np.full((size,), -1, dtype=np.int64)
        for t, n in pairs.items():
            table[t] = n
        return cls(table)

    @classmethod
    def from_corpus(
        cls, token_ids: Sequence[int], vocab_size: int
    ) -> "BigramTableDrafter":
        """Most-frequent-successor table from a token stream (test and
        bench helper; production tables come from from_file)."""
        arr = np.asarray(token_ids, dtype=np.int64)
        table = np.full((vocab_size,), -1, dtype=np.int64)
        if len(arr) < 2:
            return cls(table)
        pair_keys = arr[:-1] * vocab_size + arr[1:]
        keys, counts = np.unique(pair_keys, return_counts=True)
        # ascending count order: the last write per first-token wins
        order = np.argsort(counts, kind="stable")
        firsts = keys[order] // vocab_size
        seconds = keys[order] % vocab_size
        table[firsts] = seconds
        return cls(table)

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        if k <= 0 or not len(token_ids):
            return []
        out: list[int] = []
        cur = int(token_ids[-1])
        for _ in range(k):
            if not (0 <= cur < len(self.table)):
                break
            nxt = int(self.table[cur])
            if nxt < 0:
                break
            out.append(nxt)
            cur = nxt
        return out


def build_drafter(spec: str) -> Drafter:
    """Construct a drafter from a config string:

    - ``"ngram"`` or ``"ngram:N"`` — prompt-lookup with max n-gram N
      (default 3);
    - ``"bigram:PATH"`` — static table from PATH (.npz/.npy/json).
    """
    name, _, arg = spec.partition(":")
    if name == "ngram":
        return NgramDrafter(max_ngram=int(arg) if arg else 3)
    if name == "bigram":
        if not arg:
            raise ValueError("bigram drafter needs a table path: bigram:PATH")
        return BigramTableDrafter.from_file(arg)
    raise ValueError(
        f"unknown drafter {spec!r} (expected ngram[:N] or bigram:PATH)"
    )
