"""Dependency-free drafters for speculative decoding.

A drafter proposes up to ``k`` continuation tokens from a sequence's own
token history — no second model, no extra weights in HBM, fully testable
on CPU. Both drafters here emit *deterministic* proposals, i.e. the
draft distribution is a point mass at the proposed token; the engine's
rejection sampler (spec/verify.py) exploits that: accept token ``d``
with probability ``p_target(d)``, else resample from the renormalized
remainder — the output distribution is exactly the target's.

Drafters run on the engine thread's host path (between device
dispatches), so ``propose`` must be cheap: the n-gram matcher is a
vectorised numpy scan over the history, the bigram drafter a table walk.
"""

from __future__ import annotations

import json
from typing import Protocol, Sequence, runtime_checkable

import numpy as np


@runtime_checkable
class Drafter(Protocol):
    """Pluggable proposal source for speculative decoding.

    ``kind`` labels telemetry series; ``propose`` returns 0..k draft
    token ids continuing ``token_ids`` (an empty list = no proposal —
    the sequence decodes one token normally that step). ``window`` is
    the history suffix length the drafter actually reads (None = all):
    the engine materializes only that tail per step, keeping the host
    draft phase O(window) instead of O(context).
    """

    kind: str
    window: "int | None"

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        ...


class NgramDrafter:
    """Prompt-lookup / n-gram drafting (as popularised by vLLM and TGI):
    match the sequence's trailing n-gram against its OWN earlier history
    and propose the tokens that followed the most recent prior
    occurrence. Strong on the workloads self-drafting targets —
    summarisation, code editing, RAG, multi-turn chat — where the
    continuation frequently copies spans of the prompt."""

    kind = "ngram"

    def __init__(
        self, max_ngram: int = 3, min_ngram: int = 1,
        max_window: int = 4096,
    ):
        if max_ngram < min_ngram or min_ngram < 1:
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1, got "
                f"{max_ngram}/{min_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # bound the per-step scan: the matcher reads only the last
        # max_window tokens (vLLM's prompt-lookup bounds its scan the
        # same way) — an unbounded scan is O(context) host work per
        # sequence per decode step on the serialized engine thread
        self.window = max_window

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        arr = np.asarray(token_ids, dtype=np.int64)
        n_hist = len(arr)
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1), self.min_ngram - 1, -1):
            suffix = arr[-n:]
            # windows over arr[:-1]: start positions 0..n_hist-1-n, which
            # excludes the terminal suffix itself (it starts at n_hist-n)
            windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            starts = np.nonzero(np.all(windows == suffix, axis=1))[0]
            if len(starts) == 0:
                continue
            i = int(starts[-1])  # most recent prior occurrence
            cont = arr[i + n : i + n + k]
            if len(cont):
                return [int(t) for t in cont]
        return []


class BigramTableDrafter:
    """Static bigram drafting: a ``[vocab]`` table of most-likely next
    token (-1 = no entry), chained k steps from the sequence's last
    token. The table ships as a file (offline corpus statistics) so the
    drafter costs one array in host RAM and zero device bytes."""

    kind = "bigram"
    window = 1  # only the last token feeds the table walk

    def __init__(self, table: np.ndarray):
        self.table = np.asarray(table, dtype=np.int64).reshape(-1)

    @classmethod
    def from_file(cls, path: str) -> "BigramTableDrafter":
        """Load a table from ``.npz``/``.npy`` (array under key "next"
        for npz) or JSON ({"token_id": next_id, ...})."""
        if path.endswith(".npz"):
            with np.load(path) as z:
                return cls(z["next"])
        if path.endswith(".npy"):
            return cls(np.load(path))
        with open(path) as f:
            mapping = json.load(f)
        pairs = {int(t): int(n) for t, n in mapping.items()}
        size = max(pairs) + 1 if pairs else 1
        table = np.full((size,), -1, dtype=np.int64)
        for t, n in pairs.items():
            table[t] = n
        return cls(table)

    @classmethod
    def from_corpus(
        cls, token_ids: Sequence[int], vocab_size: int
    ) -> "BigramTableDrafter":
        """Most-frequent-successor table from a token stream (test and
        bench helper; production tables come from from_file)."""
        arr = np.asarray(token_ids, dtype=np.int64)
        table = np.full((vocab_size,), -1, dtype=np.int64)
        if len(arr) < 2:
            return cls(table)
        pair_keys = arr[:-1] * vocab_size + arr[1:]
        keys, counts = np.unique(pair_keys, return_counts=True)
        # ascending count order: the last write per first-token wins
        order = np.argsort(counts, kind="stable")
        firsts = keys[order] // vocab_size
        seconds = keys[order] % vocab_size
        table[firsts] = seconds
        return cls(table)

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        if k <= 0 or not len(token_ids):
            return []
        out: list[int] = []
        cur = int(token_ids[-1])
        for _ in range(k):
            if not (0 <= cur < len(self.table)):
                break
            nxt = int(self.table[cur])
            if nxt < 0:
                break
            out.append(nxt)
            cur = nxt
        return out


def build_drafter(spec: str) -> Drafter:
    """Construct a drafter from a config string:

    - ``"ngram"`` or ``"ngram:N"`` — prompt-lookup with max n-gram N
      (default 3);
    - ``"bigram:PATH"`` — static table from PATH (.npz/.npy/json).
    """
    name, _, arg = spec.partition(":")
    if name == "ngram":
        return NgramDrafter(max_ngram=int(arg) if arg else 3)
    if name == "bigram":
        if not arg:
            raise ValueError("bigram drafter needs a table path: bigram:PATH")
        return BigramTableDrafter.from_file(arg)
    raise ValueError(
        f"unknown drafter {spec!r} (expected ngram[:N] or bigram:PATH)"
    )
