"""Batched on-device verification for speculative decoding.

One call scores a whole batch's draft tokens against the target model's
logits and applies rejection sampling that provably preserves the target
sampling distribution (Leviathan et al., §3.3, specialised to the
deterministic drafters in spec/drafter.py):

- the engine feeds each sequence a ``[S] = [1 + K]`` token run — its
  last committed token followed by up to K draft tokens — through the
  paged-KV prefill attention, getting logits at every position;
- position ``j``'s logits define the target distribution ``p_j`` for the
  sequence's next token (after the same temperature/top-k/top-p/min-p
  shaping ``sample()`` applies — ONE shared keep-mask definition,
  ``engine.sampling.filter_keep_mask``);
- draft ``d_j`` is accepted with probability ``p_j(d_j)`` (the draft
  distribution is a point mass, so the Leviathan acceptance ratio
  ``min(1, p/q)`` reduces to ``p``); greedy rows accept iff
  ``argmax == d_j`` — which makes greedy speculative output
  bit-identical to greedy non-speculative output by construction;
- at the first rejection the replacement token is sampled from the
  residual ``norm(max(0, p - q))`` — for a point-mass q that is ``p``
  with the rejected token masked out, renormalized; if every valid draft
  is accepted, one bonus token is sampled from the next position's
  unmodified ``p``. Either way every step emits at least 1 and at most
  K+1 tokens per sequence.

Distribution preservation (the property tests/test_spec.py checks
statistically): P(emit x at position j) = p_j(x) regardless of what the
drafter proposed — acceptance contributes p(d) mass to d, rejection
contributes (1-p(d)) * p(x)/(1-p(d)) to every other x.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.sampling import NEG_INF, filter_keep_mask


def _shaped_logits(logits_all: jax.Array, s: dict) -> jax.Array:
    """Temperature-scaled, filter-masked logits [B, S, V] — softmax of
    this is the SAME target distribution sample()'s filtered path draws
    from (shared keep mask; see filter_keep_mask)."""
    B, S, V = logits_all.shape
    temperature, top_k, top_p, min_p = (
        s["temperature"], s["top_k"], s["top_p"], s["min_p"]
    )
    temp = jnp.maximum(temperature, 1e-4)[:, None, None]
    scaled = logits_all / temp
    need_filter = (top_k > 0) | (top_p < 1.0) | (min_p > 0.0)

    def filtered(_):
        KF = min(128, V)
        vals, idx = jax.lax.top_k(scaled, KF)  # [B, S, KF] descending
        lse = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)
        keep = filter_keep_mask(
            vals, lse, top_k[:, None], top_p[:, None], min_p[:, None], V
        )
        fvals = jnp.where(keep, vals, NEG_INF)
        b_idx = jnp.arange(B)[:, None, None]
        s_idx = jnp.arange(S)[None, :, None]
        out = jnp.full_like(scaled, NEG_INF).at[b_idx, s_idx, idx].set(fvals)
        return jnp.where(need_filter[:, None, None], out, scaled)

    # the top-k machinery only runs when some row filters
    return jax.lax.cond(
        jnp.any(need_filter), filtered, lambda _: scaled, None
    )


def verify_tokens(
    logits_all: jax.Array,  # [B, S, V] f32 — logits at every fed position
    tokens: jax.Array,  # [B, S] i32 — col 0 = carry token, cols 1.. = drafts
    draft_lens: jax.Array,  # [B] i32 — valid drafts per row (0..S-1)
    s: dict,  # SamplingBatch.arrays (base path only: no penalties/bias)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (out_tokens [B, S] i32, out_lps [B, S] f32, n_emit [B] i32).

    Row i emits ``out_tokens[i, :n_emit[i]]``: its accepted draft prefix
    followed by one sampled (or argmax) token. ``n_emit - 1`` is the
    accepted-draft count — the accept-rate numerator. ``out_lps`` are
    logprobs of the emitted tokens under log_softmax of the raw target
    logits, matching sample()'s emission semantics exactly.
    """
    B, S, V = logits_all.shape
    K = S - 1
    if "allow_mask" in s:
        # guided decoding (docs/guided_decoding.md): the [B, S, V]
        # per-position allow-mask — position j's mask is the automaton
        # state AFTER the first j drafts commit, computed on host from
        # the SAME automaton that masks the serial path. Applying it
        # here, before argmax/shaping/log_softmax, is the transform
        # sample() applies, at every fed position at once: draft
        # acceptance, replacement sampling, the bonus token, and the
        # emitted logprobs all target the constrained distribution, so
        # speculative verification of structured output is EXACT.
        logits_all = jnp.where(s["allow_mask"], logits_all, NEG_INF)
    temperature, seeds = s["temperature"], s["seeds"]
    greedy = temperature <= 0.0
    logprobs_full = jax.nn.log_softmax(logits_all, axis=-1)
    greedy_tok = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)  # [B, S]
    d = tokens[:, 1:]  # [B, K] draft for output position j

    def sampled_branch(_):
        """Acceptance + replacement sampling for non-greedy rows."""
        shaped = _shaped_logits(logits_all, s)
        shaped_lp = shaped - jax.nn.logsumexp(shaped, axis=-1, keepdims=True)
        lp_d = jnp.take_along_axis(
            shaped_lp[:, :K], d[..., None], axis=-1
        )[..., 0]  # [B, K] log p_j(d_j)

        def per_row(seed):
            key = jax.random.key(seed)
            ku, kg = jax.random.split(key)
            return (
                jax.random.uniform(ku, (K,), jnp.float32),
                jax.random.gumbel(kg, (S, V), jnp.float32),
            )

        u, g = jax.vmap(per_row)(seeds)
        # accept d_j with prob p_j(d_j); log-space comparison avoids
        # exp underflow deciding ties
        accept = jnp.log(jnp.maximum(u, 1e-38)) < lp_d  # [B, K]
        # replacement samples at EVERY position (the emitter selects
        # one): gumbel-max over the shaped logits = exact sampling
        plain = jnp.argmax(shaped + g, axis=-1).astype(jnp.int32)  # [B, S]
        # residual at draft positions: point-mass q removed -> mask the
        # rejected draft and renormalize (gumbel-max needs no explicit
        # renormalization)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, K, V), 2)
        masked = jnp.where(col == d[..., None], NEG_INF, shaped[:, :K])
        resid = jnp.argmax(masked + g[:, :K], axis=-1).astype(jnp.int32)
        return accept, resid, plain

    def greedy_branch(_):
        zeros = jnp.zeros((B, S), jnp.int32)
        return (
            jnp.zeros((B, K), bool), zeros[:, :K], zeros,
        )

    # skip the [B, S, V]-sized sampling machinery when the whole batch
    # decodes greedily (runtime branch — both sides compiled, one runs)
    accept_s, resid, plain = jax.lax.cond(
        jnp.all(greedy), greedy_branch, sampled_branch, None
    )
    accept_g = greedy_tok[:, :K] == d
    accept = jnp.where(greedy[:, None], accept_g, accept_s)
    valid = jnp.arange(K, dtype=jnp.int32)[None, :] < draft_lens[:, None]
    ok = (accept & valid).astype(jnp.int32)
    # accepted-prefix length: stops at the first rejection/invalid slot
    a = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)  # [B] in [0, K]

    def at_a(arr_bs):  # gather each row's column ``a``
        return jnp.take_along_axis(arr_bs, a[:, None], axis=1)[:, 0]

    # replacement token at position a: the residual sample when a valid
    # draft was REJECTED there, the plain sample when all valid drafts
    # were accepted (bonus position). resid is only defined for j < K;
    # a == K implies all-accepted, where plain applies.
    resid_ext = jnp.concatenate([resid, plain[:, K:]], axis=1)  # [B, S]
    rejected_here = a < draft_lens
    final_sampled = jnp.where(rejected_here, at_a(resid_ext), at_a(plain))
    final_tok = jnp.where(greedy, at_a(greedy_tok), final_sampled)

    j_idx = jnp.arange(S, dtype=jnp.int32)[None, :]
    d_ext = jnp.concatenate([d, jnp.zeros((B, 1), d.dtype)], axis=1)
    out_tokens = jnp.where(
        j_idx < a[:, None],
        d_ext,
        jnp.where(j_idx == a[:, None], final_tok[:, None], 0),
    ).astype(jnp.int32)
    out_lps = jnp.take_along_axis(
        logprobs_full, out_tokens[..., None], axis=-1
    )[..., 0]
    n_emit = (a + 1).astype(jnp.int32)
    return out_tokens, out_lps, n_emit


def pack_spec(
    out_tokens: jax.Array, out_lps: jax.Array, n_emit: jax.Array
) -> jax.Array:
    """One packed [B, 2S+1] device array for the verify step's outputs
    — ``[S out_tokens | S out_lps | 1 n_emit]`` per row, token ids and
    emit counts exact in f32 (vocab < 2^24). The twin of the engine's
    ``pack_pair``: over a tunneled chip every separate device->host
    read is a full round trip, so the spec harvest syncs exactly one
    array per step — serial and pipelined alike. ``harvest_spec_output``
    below is the matching (and only) unpacker; the overlapped spec
    pipeline additionally gathers the next step's carry column from
    this layout on device (engine ``chain_spec``)."""
    return jnp.concatenate(
        [
            out_tokens.astype(jnp.float32),
            out_lps,
            n_emit[:, None].astype(jnp.float32),
        ],
        axis=1,
    )


def harvest_spec_output(
    packed, S: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sync + split the spec step's packed [B, 2S+1] output into
    (out_tokens [B, S] i32, out_lps [B, S] f32, n_emit [B] i32) — token
    ids are exact in f32 (vocab < 2^24), mirroring the fused window's
    packed-transfer idiom. This is the spec path's DESIGNATED HARVEST
    point (dynalint DL010): the one device->host sync of the verify
    step happens here, not inline in the engine step loop."""
    packed_host = np.asarray(packed)
    toks = packed_host[:, :S].astype(np.int32)
    lps = packed_host[:, S : 2 * S]
    n_emit = packed_host[:, 2 * S].astype(np.int32)
    return toks, lps, n_emit
