"""Control plane: KV + leases + watches, pub/sub, queues, object store.

The reference delegates its control plane to external infrastructure — etcd
for discovery/leases and NATS (+JetStream) for messaging/queues/object store
(reference: SURVEY.md §1 L0; lib/runtime/src/transports/{etcd,nats}.rs).
dynamo-tpu self-hosts an equivalent single "coordinator" service instead:
one process (`python -m dynamo_tpu.store.server`) provides

- versioned KV with leases (TTL + keepalive) and prefix watches  (≈ etcd)
- subject-based pub/sub with wildcard matching                    (≈ NATS)
- at-least-once work queues with ack/visibility-timeout           (≈ JetStream)
- a bytes object store                                            (≈ NATS obj store)

`MemoryStore` implements the full semantics in-process (used directly for
single-process deployments and tests); the TCP server/client expose the same
abstract API across hosts.
"""

from dynamo_tpu.store.base import KvEntry, Store, WatchEvent
from dynamo_tpu.store.memory import MemoryStore

__all__ = ["KvEntry", "MemoryStore", "Store", "WatchEvent"]
