"""Store abstract interface + shared types.

Semantics modelled on what the reference actually uses from etcd/NATS
(reference: lib/runtime/src/transports/etcd.rs:41-496 — kv_create CAS,
kv_get_and_watch_prefix, primary lease w/ keepalive; transports/nats.rs —
publish/subscribe, JetStream queues, object store).
"""

from __future__ import annotations

import abc
import fnmatch
from dataclasses import dataclass
from typing import AsyncIterator, Optional

NO_LEASE = 0


@dataclass(frozen=True)
class KvEntry:
    key: str
    value: bytes
    version: int
    lease_id: int = NO_LEASE


@dataclass(frozen=True)
class WatchEvent:
    """One KV change. type is 'put' or 'delete'."""

    type: str
    entry: KvEntry


@dataclass(frozen=True)
class QueueMessage:
    id: int
    payload: bytes


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style subject matching: '.'-separated tokens, '*' = one token,
    '>' = one-or-more trailing tokens."""
    if "*" not in pattern and ">" not in pattern:
        return pattern == subject
    p_toks = pattern.split(".")
    s_toks = subject.split(".")
    for i, pt in enumerate(p_toks):
        if pt == ">":
            return len(s_toks) >= i + 1
        if i >= len(s_toks):
            return False
        if pt != "*" and pt != s_toks[i]:
            return False
    return len(p_toks) == len(s_toks)


def glob_matches(pattern: str, s: str) -> bool:
    return fnmatch.fnmatchcase(s, pattern)


class Watch(abc.ABC):
    """A prefix watch: initial snapshot + live event stream."""

    @abc.abstractmethod
    def snapshot(self) -> list[KvEntry]: ...

    @abc.abstractmethod
    def __aiter__(self) -> AsyncIterator[WatchEvent]: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Subscription(abc.ABC):
    """A pub/sub subscription yielding (subject, payload)."""

    @abc.abstractmethod
    def __aiter__(self) -> AsyncIterator[tuple[str, bytes]]: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Store(abc.ABC):
    """The full control-plane interface."""

    # -- kv ---------------------------------------------------------------
    @abc.abstractmethod
    async def kv_put(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> int: ...

    @abc.abstractmethod
    async def kv_create(
        self, key: str, value: bytes, lease_id: int = NO_LEASE
    ) -> bool:
        """Atomic create-if-absent (CAS). Returns False if the key exists."""

    @abc.abstractmethod
    async def kv_get(self, key: str) -> Optional[KvEntry]: ...

    @abc.abstractmethod
    async def kv_get_prefix(self, prefix: str) -> list[KvEntry]: ...

    @abc.abstractmethod
    async def kv_delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    async def kv_delete_prefix(self, prefix: str) -> int: ...

    @abc.abstractmethod
    async def watch_prefix(self, prefix: str) -> Watch: ...

    # -- leases -----------------------------------------------------------
    @abc.abstractmethod
    async def lease_grant(self, ttl_s: float) -> int: ...

    @abc.abstractmethod
    async def lease_keepalive(self, lease_id: int) -> bool:
        """Refresh; False if the lease no longer exists (expired/revoked)."""

    @abc.abstractmethod
    async def lease_revoke(self, lease_id: int) -> None:
        """Revoke: deletes all keys attached to the lease (watchers fire)."""

    # -- pub/sub ----------------------------------------------------------
    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def subscribe(self, pattern: str) -> Subscription: ...

    # -- queues (at-least-once w/ ack) ------------------------------------
    @abc.abstractmethod
    async def queue_push(self, queue: str, payload: bytes) -> int: ...

    @abc.abstractmethod
    async def queue_pop(
        self, queue: str, timeout_s: Optional[float] = None, visibility_s: float = 30.0
    ) -> Optional[QueueMessage]:
        """Pop one message; it must be acked within visibility_s or it is
        redelivered to another consumer."""

    @abc.abstractmethod
    async def queue_ack(self, queue: str, msg_id: int) -> bool: ...

    @abc.abstractmethod
    async def queue_len(self, queue: str) -> int: ...

    # -- object store -----------------------------------------------------
    @abc.abstractmethod
    async def obj_put(self, bucket: str, name: str, data: bytes) -> None: ...

    @abc.abstractmethod
    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def obj_delete(self, bucket: str, name: str) -> bool: ...

    @abc.abstractmethod
    async def obj_list(self, bucket: str) -> list[str]: ...

    # -- lifecycle --------------------------------------------------------
    @abc.abstractmethod
    async def close(self) -> None: ...
