"""TCP client for the coordinator store; implements the Store interface.

Multiplexes concurrent requests over one connection; watches and
subscriptions are server-push streams dispatched to local queues.

Reconnect (docs/robustness.md): with ``reconnect=True`` a lost
connection is redialed with capped exponential backoff + jitter
(utils/backoff.py) instead of failing the client permanently — a
flapping or restarting coordinator is never hammered by a tight
reconnect loop. In-flight calls at the moment of loss still fail with
ConnectionError (they cannot be replayed safely), and open
watches/subscriptions END (their consumers — http/discovery.py, the
component Client — resubscribe on their own backoff); calls issued
after the redial succeed.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu import faults
from dynamo_tpu.store.base import (
    NO_LEASE,
    KvEntry,
    QueueMessage,
    Store,
    Subscription,
    Watch,
    WatchEvent,
)
from dynamo_tpu.store.wire import read_frame, write_frame
from dynamo_tpu.telemetry.instruments import STORE_RECONNECTS
from dynamo_tpu.utils.backoff import Backoff

log = logging.getLogger("dynamo_tpu.store.client")


def _dec_entry(d: dict) -> KvEntry:
    return KvEntry(key=d["k"], value=d["v"], version=d["ver"], lease_id=d["l"])


class _RemoteWatch(Watch):
    def __init__(self, client: "StoreClient", sid: int, snapshot: list[KvEntry]):
        self._client = client
        self._sid = sid
        self._snapshot = snapshot
        self.queue: asyncio.Queue[Any] = asyncio.Queue()

    def snapshot(self) -> list[KvEntry]:
        return list(self._snapshot)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[WatchEvent]:
        while True:
            item = await self.queue.get()
            if item is None:
                return
            yield WatchEvent(type=item["t"], entry=_dec_entry(item["e"]))

    async def close(self) -> None:
        await self._client._close_stream(self._sid)


class _RemoteSubscription(Subscription):
    def __init__(self, client: "StoreClient", sid: int):
        self._client = client
        self._sid = sid
        self.queue: asyncio.Queue[Any] = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[tuple[str, bytes]]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[tuple[str, bytes]]:
        while True:
            item = await self.queue.get()
            if item is None:
                return
            yield item["subj"], item["p"]

    async def close(self) -> None:
        await self._client._close_stream(self._sid)


class StoreClient(Store):
    def __init__(self, host: str = "127.0.0.1", port: int = 4222):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._req_ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, asyncio.Queue] = {}
        self._rx_task: asyncio.Task | None = None
        self._write_lock = asyncio.Lock()
        self._closed = False
        self._reconnect = False
        self._connected = False

    @classmethod
    async def connect(
        cls,
        host: str = "127.0.0.1",
        port: int = 4222,
        reconnect: bool = False,
    ) -> "StoreClient":
        client = cls(host, port)
        client._reconnect = reconnect
        client._reader, client._writer = await asyncio.open_connection(host, port)
        client._connected = True
        client._rx_task = asyncio.get_running_loop().create_task(
            client._rx_forever()
        )
        return client

    async def _rx_forever(self) -> None:
        """Read frames until the connection dies; with reconnect
        enabled, redial on capped backoff + jitter and resume."""
        backoff = Backoff(base_s=0.2, cap_s=10.0)
        while True:
            try:
                await self._rx_once()
            finally:
                self._connected = False
            if self._closed or not self._reconnect:
                return
            while not self._closed:
                delay = await backoff.sleep()
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self.host, self.port
                    )
                except OSError:
                    log.debug(
                        "store redial failed (next in ~%.1fs)", delay
                    )
                    continue
                self._connected = True
                backoff.reset()
                STORE_RECONNECTS.inc()
                log.warning(
                    "store connection re-established to %s:%d",
                    self.host, self.port,
                )
                break
            if self._closed:
                return

    async def _rx_once(self) -> None:
        assert self._reader is not None
        try:
            while True:
                msg = await read_frame(self._reader)
                mid = msg.get("i")
                if "s" in msg:  # stream item
                    q = self._streams.get(mid)
                    if q is not None:
                        q.put_nowait(msg["s"])
                elif msg.get("end"):
                    q = self._streams.pop(mid, None)
                    if q is not None:
                        q.put_nowait(None)
                else:  # unary reply
                    fut = self._pending.pop(mid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except asyncio.CancelledError:
            raise  # close() cancels us; finally below still fails waiters
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            # per-connection cleanup: in-flight calls cannot be replayed
            # safely (the op may have applied server-side), so they fail;
            # stream consumers see end-of-stream and resubscribe themselves
            err = ConnectionError("store connection lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            for q in self._streams.values():
                q.put_nowait(None)
            self._streams.clear()

    async def _call(self, op: str, *args: Any) -> Any:
        if self._writer is None or self._closed or not self._connected:
            raise ConnectionError("store client not connected")
        if faults.ACTIVE is not None:
            await faults.ACTIVE.fire_async("store.call", op=op)
        rid = next(self._req_ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._write_lock:
            write_frame(self._writer, {"i": rid, "op": op, "a": list(args)})
            await self._writer.drain()
        reply = await fut
        if not reply.get("ok"):
            raise RuntimeError(f"store error for {op}: {reply.get('e')}")
        return reply.get("v")

    async def _close_stream(self, sid: int) -> None:
        q = self._streams.pop(sid, None)
        if q is not None:
            q.put_nowait(None)
        if not self._closed:
            try:
                await self._call("stream_close", sid)
            except (ConnectionError, RuntimeError):
                pass

    # -- kv ---------------------------------------------------------------
    async def kv_put(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> int:
        return await self._call("kv_put", key, value, lease_id)

    async def kv_create(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> bool:
        return await self._call("kv_create", key, value, lease_id)

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        d = await self._call("kv_get", key)
        return _dec_entry(d) if d else None

    async def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        return [_dec_entry(d) for d in await self._call("kv_get_prefix", prefix)]

    async def kv_delete(self, key: str) -> bool:
        return await self._call("kv_delete", key)

    async def kv_delete_prefix(self, prefix: str) -> int:
        return await self._call("kv_delete_prefix", prefix)

    async def watch_prefix(self, prefix: str) -> Watch:
        v = await self._call("watch_prefix", prefix)
        watch = _RemoteWatch(self, v["sid"], [_dec_entry(d) for d in v["snapshot"]])
        self._streams[v["sid"]] = watch.queue
        return watch

    # -- leases -----------------------------------------------------------
    async def lease_grant(self, ttl_s: float) -> int:
        return await self._call("lease_grant", ttl_s)

    async def lease_keepalive(self, lease_id: int) -> bool:
        return await self._call("lease_keepalive", lease_id)

    async def lease_revoke(self, lease_id: int) -> None:
        await self._call("lease_revoke", lease_id)

    # -- pub/sub ----------------------------------------------------------
    async def publish(self, subject: str, payload: bytes) -> None:
        await self._call("publish", subject, payload)

    async def subscribe(self, pattern: str) -> Subscription:
        v = await self._call("subscribe", pattern)
        sub = _RemoteSubscription(self, v["sid"])
        self._streams[v["sid"]] = sub.queue
        return sub

    # -- queues -----------------------------------------------------------
    async def queue_push(self, queue: str, payload: bytes) -> int:
        return await self._call("queue_push", queue, payload)

    async def queue_pop(
        self, queue: str, timeout_s: Optional[float] = None, visibility_s: float = 30.0
    ) -> Optional[QueueMessage]:
        d = await self._call("queue_pop", queue, timeout_s, visibility_s)
        return QueueMessage(id=d["id"], payload=d["p"]) if d else None

    async def queue_ack(self, queue: str, msg_id: int) -> bool:
        return await self._call("queue_ack", queue, msg_id)

    async def queue_len(self, queue: str) -> int:
        return await self._call("queue_len", queue)

    # -- object store -----------------------------------------------------
    async def obj_put(self, bucket: str, name: str, data: bytes) -> None:
        await self._call("obj_put", bucket, name, data)

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        return await self._call("obj_get", bucket, name)

    async def obj_delete(self, bucket: str, name: str) -> bool:
        return await self._call("obj_delete", bucket, name)

    async def obj_list(self, bucket: str) -> list[str]:
        return await self._call("obj_list", bucket)

    # -- lifecycle --------------------------------------------------------
    async def close(self) -> None:
        self._closed = True
        if self._rx_task is not None:
            self._rx_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
