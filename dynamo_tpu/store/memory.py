"""In-process control-plane implementation with full semantics.

Single source of truth for store behavior: the TCP server wraps one of
these; tests and single-process deployments use it directly.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

from dynamo_tpu.store.base import (
    NO_LEASE,
    KvEntry,
    QueueMessage,
    Store,
    Subscription,
    Watch,
    WatchEvent,
    subject_matches,
)


class _MemWatch(Watch):
    def __init__(self, store: "MemoryStore", prefix: str, snapshot: list[KvEntry]):
        self._store = store
        self.prefix = prefix
        self._snapshot = snapshot
        self._queue: asyncio.Queue[WatchEvent | None] = asyncio.Queue()
        self._closed = False

    def snapshot(self) -> list[KvEntry]:
        return list(self._snapshot)

    def _notify(self, event: WatchEvent) -> None:
        if not self._closed:
            self._queue.put_nowait(event)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            yield ev

    async def close(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)
        self._store._watches.discard(self)


class _MemSubscription(Subscription):
    def __init__(self, store: "MemoryStore", pattern: str):
        self._store = store
        self.pattern = pattern
        self._queue: asyncio.Queue[tuple[str, bytes] | None] = asyncio.Queue()
        self._closed = False

    def _deliver(self, subject: str, payload: bytes) -> None:
        if not self._closed:
            self._queue.put_nowait((subject, payload))

    def __aiter__(self) -> AsyncIterator[tuple[str, bytes]]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[tuple[str, bytes]]:
        while True:
            item = await self._queue.get()
            if item is None:
                return
            yield item

    async def close(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)
        self._store._subs.discard(self)


@dataclass
class _Lease:
    id: int
    ttl_s: float
    expires_at: float
    keys: set[str] = field(default_factory=set)


@dataclass
class _QueueState:
    next_id: itertools.count = field(default_factory=lambda: itertools.count(1))
    ready: deque[QueueMessage] = field(default_factory=deque)
    # msg_id -> (message, redelivery deadline)
    in_flight: dict[int, tuple[QueueMessage, float]] = field(default_factory=dict)
    cond: asyncio.Condition = field(default_factory=asyncio.Condition)


class MemoryStore(Store):
    """Full-semantics in-process store. All methods are asyncio-safe within
    one event loop (the store is not thread-safe by design; cross-thread use
    goes through the TCP client)."""

    def __init__(
        self,
        lease_sweep_interval_s: float = 0.5,
        persist_path: Optional[str] = None,
    ):
        self._kv: dict[str, KvEntry] = {}
        self._version = itertools.count(1)
        self._watches: set[_MemWatch] = set()
        self._subs: set[_MemSubscription] = set()
        self._leases: dict[int, _Lease] = {}
        self._lease_ids = itertools.count(1)
        self._queues: dict[str, _QueueState] = defaultdict(_QueueState)
        self._objects: dict[str, dict[str, bytes]] = defaultdict(dict)
        self._sweep_interval = lease_sweep_interval_s
        self._sweeper: Optional[asyncio.Task] = None
        self._closed = False
        # durability (store/persist.py): WAL + snapshot replay. Without
        # it a coordinator restart loses model registrations, deployment
        # specs, prefill queues, and the whole G4 object tier.
        self._wal = None
        if persist_path:
            from dynamo_tpu.store.persist import WriteAheadLog

            self._wal = WriteAheadLog(persist_path)
            self._restore()

    # -- durability -------------------------------------------------------
    def _restore(self) -> None:
        from dynamo_tpu.store.persist import decode_value

        snap, records = self._wal.replay()
        max_ver = 0
        snap_next: dict[str, int] = {}
        if snap:
            max_ver = int(snap.get("version", 0))
            for e in snap.get("kv", []):
                self._kv[e["k"]] = KvEntry(
                    key=e["k"], value=decode_value(e["v"]),
                    version=int(e["ver"]), lease_id=NO_LEASE,
                )
            for name, qs in snap.get("queues", {}).items():
                q = self._queues[name]
                snap_next[name] = int(qs["next_id"])
                q.next_id = itertools.count(int(qs["next_id"]))
                for m in qs.get("msgs", []):
                    q.ready.append(
                        QueueMessage(id=int(m["id"]), payload=decode_value(m["p"]))
                    )
            for bucket, objs in snap.get("objects", {}).items():
                for name, data in objs.items():
                    self._objects[bucket][name] = decode_value(data)
        acked: dict[str, set[int]] = defaultdict(set)
        pushes: dict[str, list[QueueMessage]] = defaultdict(list)
        q_next: dict[str, int] = {}
        for rec in records:
            op = rec["op"]
            if op == "kv_put":
                max_ver = max(max_ver, int(rec["ver"]))
                self._kv[rec["k"]] = KvEntry(
                    key=rec["k"], value=decode_value(rec["v"]),
                    version=int(rec["ver"]), lease_id=NO_LEASE,
                )
            elif op == "kv_del":
                self._kv.pop(rec["k"], None)
            elif op == "q_push":
                # a crash between snapshot replace and log truncation
                # leaves pre-compaction records behind: anything the
                # snapshot already folded in (id < its next_id) must
                # not replay, or queued work would deliver twice
                if int(rec["id"]) < snap_next.get(rec["q"], 0):
                    continue
                pushes[rec["q"]].append(
                    QueueMessage(id=int(rec["id"]), payload=decode_value(rec["p"]))
                )
                q_next[rec["q"]] = max(
                    q_next.get(rec["q"], 1), int(rec["id"]) + 1
                )
            elif op == "q_ack":
                acked[rec["q"]].add(int(rec["id"]))
            elif op == "obj_put":
                self._objects[rec["b"]][rec["n"]] = decode_value(rec["v"])
            elif op == "obj_del":
                self._objects.get(rec["b"], {}).pop(rec["n"], None)
        for name, msgs in pushes.items():
            q = self._queues[name]
            for m in msgs:
                if m.id not in acked[name]:
                    q.ready.append(m)
        for name, nid in q_next.items():
            self._queues[name].next_id = itertools.count(nid)
        # acks for messages restored from the SNAPSHOT
        for name, ids in acked.items():
            q = self._queues[name]
            if ids:
                q.ready = deque(m for m in q.ready if m.id not in ids)
        self._version = itertools.count(max_ver + 1)

    def _snapshot(self) -> dict:
        from dynamo_tpu.store.persist import snapshot_from_state

        queues = {
            name: (
                next(q.next_id),  # consumes one id: monotonicity kept
                list(q.ready) + [m for m, _ in q.in_flight.values()],
            )
            for name, q in self._queues.items()
        }
        # itertools.count was advanced by the peek above; rebuild
        for name, (nid, _) in queues.items():
            self._queues[name].next_id = itertools.count(nid + 1)
        ver = next(self._version)
        self._version = itertools.count(ver + 1)
        return snapshot_from_state(self._kv, queues, self._objects, ver)

    def _maybe_compact(self) -> None:
        if self._wal is not None and self._wal.needs_compaction():
            self._wal.compact(self._snapshot())

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or self._sweeper.done():
            self._sweeper = asyncio.get_running_loop().create_task(self._sweep_loop())

    async def _sweep_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self._sweep_interval)
            now = time.monotonic()
            expired = [l.id for l in self._leases.values() if l.expires_at <= now]
            for lid in expired:
                await self.lease_revoke(lid)
            # redeliver timed-out in-flight queue messages
            for q in self._queues.values():
                timed_out = [
                    mid for mid, (_, ddl) in q.in_flight.items() if ddl <= now
                ]
                if timed_out:
                    async with q.cond:
                        for mid in timed_out:
                            msg, _ = q.in_flight.pop(mid)
                            q.ready.appendleft(msg)
                        q.cond.notify_all()

    # -- kv ---------------------------------------------------------------
    def _emit(self, event: WatchEvent) -> None:
        for w in list(self._watches):
            if event.entry.key.startswith(w.prefix):
                w._notify(event)

    async def kv_put(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> int:
        # detach from a previous owner lease so a stale lease's expiry can't
        # delete a key that has since been re-registered by a live process
        prev = self._kv.get(key)
        if prev is not None and prev.lease_id != lease_id:
            old = self._leases.get(prev.lease_id)
            if old is not None:
                old.keys.discard(key)
        if lease_id != NO_LEASE:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise KeyError(f"lease {lease_id} does not exist")
            lease.keys.add(key)
        version = next(self._version)
        entry = KvEntry(key=key, value=value, version=version, lease_id=lease_id)
        durable_prev = prev is not None and prev.lease_id == NO_LEASE
        self._kv[key] = entry
        if self._wal is not None:
            from dynamo_tpu.store.persist import encode_value

            if lease_id == NO_LEASE:
                self._wal.append(
                    "kv_put", k=key, v=encode_value(value), ver=version
                )
                self._maybe_compact()
            elif durable_prev:
                # a leased put SHADOWS a previously durable key: tombstone
                # it, or a restart would resurrect the stale value
                # (leased keys themselves are ephemeral by design)
                self._wal.append("kv_del", k=key)
        self._emit(WatchEvent("put", entry))
        return version

    async def kv_create(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> bool:
        if key in self._kv:
            return False
        await self.kv_put(key, value, lease_id)
        return True

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        return sorted(
            (e for k, e in self._kv.items() if k.startswith(prefix)),
            key=lambda e: e.key,
        )

    async def kv_delete(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id != NO_LEASE and entry.lease_id in self._leases:
            self._leases[entry.lease_id].keys.discard(key)
        if self._wal is not None and entry.lease_id == NO_LEASE:
            self._wal.append("kv_del", k=key)
        self._emit(WatchEvent("delete", entry))
        return True

    async def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._kv if k.startswith(prefix)]
        for k in keys:
            await self.kv_delete(k)
        return len(keys)

    async def watch_prefix(self, prefix: str) -> Watch:
        snapshot = await self.kv_get_prefix(prefix)
        w = _MemWatch(self, prefix, snapshot)
        self._watches.add(w)
        return w

    # -- leases -----------------------------------------------------------
    async def lease_grant(self, ttl_s: float) -> int:
        self._ensure_sweeper()
        lid = next(self._lease_ids)
        self._leases[lid] = _Lease(
            id=lid, ttl_s=ttl_s, expires_at=time.monotonic() + ttl_s
        )
        return lid

    async def lease_keepalive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.expires_at = time.monotonic() + lease.ttl_s
        return True

    async def lease_revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self.kv_delete(key)

    # -- pub/sub ----------------------------------------------------------
    async def publish(self, subject: str, payload: bytes) -> None:
        for sub in list(self._subs):
            if subject_matches(sub.pattern, subject):
                sub._deliver(subject, payload)

    async def subscribe(self, pattern: str) -> Subscription:
        sub = _MemSubscription(self, pattern)
        self._subs.add(sub)
        return sub

    # -- queues -----------------------------------------------------------
    async def queue_push(self, queue: str, payload: bytes) -> int:
        self._ensure_sweeper()
        q = self._queues[queue]
        msg = QueueMessage(id=next(q.next_id), payload=payload)
        async with q.cond:
            q.ready.append(msg)
            q.cond.notify()
        # log AFTER the state mutation (like kv_put/obj_put): a
        # compaction triggered by this very append snapshots state that
        # already CONTAINS the message — logging first would let the
        # compaction truncate the push record while the snapshot misses it
        if self._wal is not None:
            from dynamo_tpu.store.persist import encode_value

            self._wal.append(
                "q_push", q=queue, id=msg.id, p=encode_value(payload)
            )
            self._maybe_compact()
        return msg.id

    async def queue_pop(
        self, queue: str, timeout_s: Optional[float] = None, visibility_s: float = 30.0
    ) -> Optional[QueueMessage]:
        q = self._queues[queue]
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        async with q.cond:
            while not q.ready:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(q.cond.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    return None
            msg = q.ready.popleft()
            q.in_flight[msg.id] = (msg, time.monotonic() + visibility_s)
            return msg

    async def queue_ack(self, queue: str, msg_id: int) -> bool:
        q = self._queues[queue]
        acked = q.in_flight.pop(msg_id, None) is not None
        if acked and self._wal is not None:
            self._wal.append("q_ack", q=queue, id=msg_id)
        return acked

    async def queue_len(self, queue: str) -> int:
        q = self._queues[queue]
        return len(q.ready) + len(q.in_flight)

    # -- object store -----------------------------------------------------
    async def obj_put(self, bucket: str, name: str, data: bytes) -> None:
        self._objects[bucket][name] = bytes(data)
        if self._wal is not None:
            from dynamo_tpu.store.persist import encode_value

            self._wal.append("obj_put", b=bucket, n=name, v=encode_value(data))
            self._maybe_compact()

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        return self._objects.get(bucket, {}).get(name)

    async def obj_delete(self, bucket: str, name: str) -> bool:
        deleted = self._objects.get(bucket, {}).pop(name, None) is not None
        if deleted and self._wal is not None:
            self._wal.append("obj_del", b=bucket, n=name)
        return deleted

    async def obj_list(self, bucket: str) -> list[str]:
        return sorted(self._objects.get(bucket, {}).keys())

    # -- lifecycle --------------------------------------------------------
    async def close(self) -> None:
        self._closed = True
        if self._sweeper is not None:
            self._sweeper.cancel()
        for w in list(self._watches):
            await w.close()
        for s in list(self._subs):
            await s.close()
        if self._wal is not None:
            # fold the log into a snapshot: clean restarts replay O(1)
            self._wal.compact(self._snapshot())
            self._wal.close()
