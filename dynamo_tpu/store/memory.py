"""In-process control-plane implementation with full semantics.

Single source of truth for store behavior: the TCP server wraps one of
these; tests and single-process deployments use it directly.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

from dynamo_tpu.store.base import (
    NO_LEASE,
    KvEntry,
    QueueMessage,
    Store,
    Subscription,
    Watch,
    WatchEvent,
    subject_matches,
)


class _MemWatch(Watch):
    def __init__(self, store: "MemoryStore", prefix: str, snapshot: list[KvEntry]):
        self._store = store
        self.prefix = prefix
        self._snapshot = snapshot
        self._queue: asyncio.Queue[WatchEvent | None] = asyncio.Queue()
        self._closed = False

    def snapshot(self) -> list[KvEntry]:
        return list(self._snapshot)

    def _notify(self, event: WatchEvent) -> None:
        if not self._closed:
            self._queue.put_nowait(event)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            yield ev

    async def close(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)
        self._store._watches.discard(self)


class _MemSubscription(Subscription):
    def __init__(self, store: "MemoryStore", pattern: str):
        self._store = store
        self.pattern = pattern
        self._queue: asyncio.Queue[tuple[str, bytes] | None] = asyncio.Queue()
        self._closed = False

    def _deliver(self, subject: str, payload: bytes) -> None:
        if not self._closed:
            self._queue.put_nowait((subject, payload))

    def __aiter__(self) -> AsyncIterator[tuple[str, bytes]]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[tuple[str, bytes]]:
        while True:
            item = await self._queue.get()
            if item is None:
                return
            yield item

    async def close(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)
        self._store._subs.discard(self)


@dataclass
class _Lease:
    id: int
    ttl_s: float
    expires_at: float
    keys: set[str] = field(default_factory=set)


@dataclass
class _QueueState:
    next_id: itertools.count = field(default_factory=lambda: itertools.count(1))
    ready: deque[QueueMessage] = field(default_factory=deque)
    # msg_id -> (message, redelivery deadline)
    in_flight: dict[int, tuple[QueueMessage, float]] = field(default_factory=dict)
    cond: asyncio.Condition = field(default_factory=asyncio.Condition)


class MemoryStore(Store):
    """Full-semantics in-process store. All methods are asyncio-safe within
    one event loop (the store is not thread-safe by design; cross-thread use
    goes through the TCP client)."""

    def __init__(self, lease_sweep_interval_s: float = 0.5):
        self._kv: dict[str, KvEntry] = {}
        self._version = itertools.count(1)
        self._watches: set[_MemWatch] = set()
        self._subs: set[_MemSubscription] = set()
        self._leases: dict[int, _Lease] = {}
        self._lease_ids = itertools.count(1)
        self._queues: dict[str, _QueueState] = defaultdict(_QueueState)
        self._objects: dict[str, dict[str, bytes]] = defaultdict(dict)
        self._sweep_interval = lease_sweep_interval_s
        self._sweeper: Optional[asyncio.Task] = None
        self._closed = False

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or self._sweeper.done():
            self._sweeper = asyncio.get_running_loop().create_task(self._sweep_loop())

    async def _sweep_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self._sweep_interval)
            now = time.monotonic()
            expired = [l.id for l in self._leases.values() if l.expires_at <= now]
            for lid in expired:
                await self.lease_revoke(lid)
            # redeliver timed-out in-flight queue messages
            for q in self._queues.values():
                timed_out = [
                    mid for mid, (_, ddl) in q.in_flight.items() if ddl <= now
                ]
                if timed_out:
                    async with q.cond:
                        for mid in timed_out:
                            msg, _ = q.in_flight.pop(mid)
                            q.ready.appendleft(msg)
                        q.cond.notify_all()

    # -- kv ---------------------------------------------------------------
    def _emit(self, event: WatchEvent) -> None:
        for w in list(self._watches):
            if event.entry.key.startswith(w.prefix):
                w._notify(event)

    async def kv_put(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> int:
        # detach from a previous owner lease so a stale lease's expiry can't
        # delete a key that has since been re-registered by a live process
        prev = self._kv.get(key)
        if prev is not None and prev.lease_id != lease_id:
            old = self._leases.get(prev.lease_id)
            if old is not None:
                old.keys.discard(key)
        if lease_id != NO_LEASE:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise KeyError(f"lease {lease_id} does not exist")
            lease.keys.add(key)
        version = next(self._version)
        entry = KvEntry(key=key, value=value, version=version, lease_id=lease_id)
        self._kv[key] = entry
        self._emit(WatchEvent("put", entry))
        return version

    async def kv_create(self, key: str, value: bytes, lease_id: int = NO_LEASE) -> bool:
        if key in self._kv:
            return False
        await self.kv_put(key, value, lease_id)
        return True

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        return sorted(
            (e for k, e in self._kv.items() if k.startswith(prefix)),
            key=lambda e: e.key,
        )

    async def kv_delete(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id != NO_LEASE and entry.lease_id in self._leases:
            self._leases[entry.lease_id].keys.discard(key)
        self._emit(WatchEvent("delete", entry))
        return True

    async def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._kv if k.startswith(prefix)]
        for k in keys:
            await self.kv_delete(k)
        return len(keys)

    async def watch_prefix(self, prefix: str) -> Watch:
        snapshot = await self.kv_get_prefix(prefix)
        w = _MemWatch(self, prefix, snapshot)
        self._watches.add(w)
        return w

    # -- leases -----------------------------------------------------------
    async def lease_grant(self, ttl_s: float) -> int:
        self._ensure_sweeper()
        lid = next(self._lease_ids)
        self._leases[lid] = _Lease(
            id=lid, ttl_s=ttl_s, expires_at=time.monotonic() + ttl_s
        )
        return lid

    async def lease_keepalive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.expires_at = time.monotonic() + lease.ttl_s
        return True

    async def lease_revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self.kv_delete(key)

    # -- pub/sub ----------------------------------------------------------
    async def publish(self, subject: str, payload: bytes) -> None:
        for sub in list(self._subs):
            if subject_matches(sub.pattern, subject):
                sub._deliver(subject, payload)

    async def subscribe(self, pattern: str) -> Subscription:
        sub = _MemSubscription(self, pattern)
        self._subs.add(sub)
        return sub

    # -- queues -----------------------------------------------------------
    async def queue_push(self, queue: str, payload: bytes) -> int:
        self._ensure_sweeper()
        q = self._queues[queue]
        msg = QueueMessage(id=next(q.next_id), payload=payload)
        async with q.cond:
            q.ready.append(msg)
            q.cond.notify()
        return msg.id

    async def queue_pop(
        self, queue: str, timeout_s: Optional[float] = None, visibility_s: float = 30.0
    ) -> Optional[QueueMessage]:
        q = self._queues[queue]
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        async with q.cond:
            while not q.ready:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(q.cond.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    return None
            msg = q.ready.popleft()
            q.in_flight[msg.id] = (msg, time.monotonic() + visibility_s)
            return msg

    async def queue_ack(self, queue: str, msg_id: int) -> bool:
        q = self._queues[queue]
        return q.in_flight.pop(msg_id, None) is not None

    async def queue_len(self, queue: str) -> int:
        q = self._queues[queue]
        return len(q.ready) + len(q.in_flight)

    # -- object store -----------------------------------------------------
    async def obj_put(self, bucket: str, name: str, data: bytes) -> None:
        self._objects[bucket][name] = bytes(data)

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        return self._objects.get(bucket, {}).get(name)

    async def obj_delete(self, bucket: str, name: str) -> bool:
        return self._objects.get(bucket, {}).pop(name, None) is not None

    async def obj_list(self, bucket: str) -> list[str]:
        return sorted(self._objects.get(bucket, {}).keys())

    # -- lifecycle --------------------------------------------------------
    async def close(self) -> None:
        self._closed = True
        if self._sweeper is not None:
            self._sweeper.cancel()
        for w in list(self._watches):
            await w.close()
        for s in list(self._subs):
            await s.close()
