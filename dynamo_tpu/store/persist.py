"""Durability for the coordinator store: append-only op log + snapshot.

The reference leans on etcd's raft log and JetStream's file store for
control-plane durability (reference: lib/runtime/src/transports/
{etcd,nats}.rs); the self-hosted store mirrors that contract with a
simple WAL: every surviving mutation appends one JSONL record, startup
replays snapshot + log, and compaction folds the log into a fresh
snapshot when it grows past ``compact_bytes``.

What survives a restart (and what deliberately does not):
- KV entries WITHOUT a lease — lease-attached keys are liveness
  registrations; their owners must re-register after a coordinator
  restart (same effective behavior as etcd lease expiry during the
  outage).
- Queues: pushed-but-unacked messages, including in-flight ones (they
  come back READY — at-least-once redelivery, like JetStream).
- The object plane (G4 KV tier, model artifacts).

Values are base64 in the JSONL records: the log stays greppable and the
control plane is low-rate, so text framing costs nothing that matters.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Iterator, Optional

SNAP_SUFFIX = ".snap"


def _b64(data: bytes) -> str:
    return base64.b64encode(bytes(data)).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class WriteAheadLog:
    """Append-only JSONL op log with snapshot-based compaction."""

    def __init__(self, path: str, compact_bytes: int = 8 << 20):
        self.path = path
        self.snap_path = path + SNAP_SUFFIX
        self.compact_bytes = compact_bytes
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = None

    # -- writing ----------------------------------------------------------
    def _file(self):
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def append(self, op: str, **fields: Any) -> None:
        """Append one record, flushed (not fsynced): acked mutations
        survive a PROCESS crash — kernel buffers hold the record even
        after kill -9 — but a host/power crash can lose the unsynced
        tail. For power-loss durability use the native store with
        --fsync-wal (etcd fsyncs its raft log the same way); a per-op
        fsync here would serialize the asyncio control plane on disk
        latency for a guarantee the deployment story doesn't rest on."""
        rec = {"op": op, **fields}
        fh = self._file()
        fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        fh.flush()

    @property
    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def needs_compaction(self) -> bool:
        return self.size > self.compact_bytes

    # -- replay -----------------------------------------------------------
    def replay(self) -> tuple[Optional[dict], Iterator[dict]]:
        """(snapshot dict or None, iterator of log records)."""
        snap = None
        if os.path.exists(self.snap_path):
            with open(self.snap_path, encoding="utf-8") as f:
                snap = json.load(f)

        def records() -> Iterator[dict]:
            if not os.path.exists(self.path):
                return
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        # torn tail write from a crash: stop replay here
                        return

        return snap, records()

    def compact(self, snapshot: dict) -> None:
        """Write a fresh snapshot atomically and truncate the log."""
        tmp = self.snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snapshot, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        with open(self.path, "w", encoding="utf-8") as f:
            f.flush()
            os.fsync(f.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


# -- snapshot/record schema helpers (shared by MemoryStore) ----------------


def snapshot_from_state(kv, queues, objects, version: int) -> dict:
    """Serialize surviving state (see module docstring for the contract)."""
    return {
        "version": version,
        "kv": [
            {"k": e.key, "v": _b64(e.value), "ver": e.version}
            for e in kv.values()
            if e.lease_id == 0  # NO_LEASE — leased keys are ephemeral
        ],
        "queues": {
            name: {
                "next_id": q_next,
                # in-flight comes back ready: at-least-once redelivery
                "msgs": [
                    {"id": m.id, "p": _b64(m.payload)} for m in msgs
                ],
            }
            for name, (q_next, msgs) in queues.items()
        },
        "objects": {
            bucket: {name: _b64(data) for name, data in objs.items()}
            for bucket, objs in objects.items()
        },
    }


def encode_value(value: bytes) -> str:
    return _b64(value)


def decode_value(s: str) -> bytes:
    return _unb64(s)
