"""TCP server exposing a MemoryStore to remote clients.

One coordinator process per deployment replaces the reference's etcd+NATS
pair (reference: SURVEY.md §1 L0). Run with::

    python -m dynamo_tpu.store.server --host 0.0.0.0 --port 4222

Protocol: length-prefixed msgpack frames. Client request:
``{i: req_id, op: name, a: [args]}``. Server unary reply:
``{i, ok, v}`` (or ``{i, ok: false, e: msg}``). Streams (watch/subscribe)
are server-push: ``{i: stream_id, s: item}`` and ``{i, end: true}``.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
from typing import Any

from dynamo_tpu.store.base import KvEntry, QueueMessage, WatchEvent
from dynamo_tpu.store.memory import MemoryStore
from dynamo_tpu.store.wire import read_frame, shutdown_server, write_frame

log = logging.getLogger("dynamo_tpu.store.server")


def _enc_entry(e: KvEntry) -> dict:
    return {"k": e.key, "v": e.value, "ver": e.version, "l": e.lease_id}


def _enc_event(ev: WatchEvent) -> dict:
    return {"t": ev.type, "e": _enc_entry(ev.entry)}


def _enc_qmsg(m: QueueMessage | None) -> dict | None:
    if m is None:
        return None
    return {"id": m.id, "p": m.payload}


class StoreServer:
    def __init__(self, store: MemoryStore | None = None, host: str = "127.0.0.1", port: int = 4222):
        self.store = store or MemoryStore()
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._conn_writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        log.info("store server listening on %s:%d", self.host, self.port)

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        await shutdown_server(self._server, self._conn_writers)
        await self.store.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._conn_writers.add(writer)
        # per-connection state: leases granted and streams opened, cleaned on drop
        conn_leases: set[int] = set()
        streams: dict[int, asyncio.Task] = {}
        stream_handles: dict[int, Any] = {}
        stream_ids = itertools.count(1)
        write_lock = asyncio.Lock()

        async def send(obj: Any) -> None:
            async with write_lock:
                write_frame(writer, obj)
                await writer.drain()

        async def pump_watch(sid: int, watch: Any) -> None:
            try:
                async for ev in watch:
                    await send({"i": sid, "s": _enc_event(ev)})
                await send({"i": sid, "end": True})
            except asyncio.CancelledError:
                raise  # stream_close/conn-drop cancels pumps; stay cancellable
            except ConnectionError:
                pass

        async def pump_sub(sid: int, sub: Any) -> None:
            try:
                async for subject, payload in sub:
                    await send({"i": sid, "s": {"subj": subject, "p": payload}})
                await send({"i": sid, "end": True})
            except asyncio.CancelledError:
                raise  # stream_close/conn-drop cancels pumps; stay cancellable
            except ConnectionError:
                pass

        store = self.store
        pending: set[asyncio.Task] = set()

        async def handle_request(rid: int, op: str, args: list) -> None:
            try:
                value: Any = None
                if op == "kv_put":
                    value = await store.kv_put(args[0], args[1], args[2])
                elif op == "kv_create":
                    value = await store.kv_create(args[0], args[1], args[2])
                elif op == "kv_get":
                    e = await store.kv_get(args[0])
                    value = _enc_entry(e) if e else None
                elif op == "kv_get_prefix":
                    value = [_enc_entry(e) for e in await store.kv_get_prefix(args[0])]
                elif op == "kv_delete":
                    value = await store.kv_delete(args[0])
                elif op == "kv_delete_prefix":
                    value = await store.kv_delete_prefix(args[0])
                elif op == "watch_prefix":
                    watch = await store.watch_prefix(args[0])
                    sid = next(stream_ids)
                    stream_handles[sid] = watch
                    streams[sid] = asyncio.get_running_loop().create_task(
                        pump_watch(sid, watch)
                    )
                    value = {
                        "sid": sid,
                        "snapshot": [_enc_entry(e) for e in watch.snapshot()],
                    }
                elif op == "lease_grant":
                    value = await store.lease_grant(args[0])
                    conn_leases.add(value)
                elif op == "lease_keepalive":
                    value = await store.lease_keepalive(args[0])
                elif op == "lease_revoke":
                    await store.lease_revoke(args[0])
                    conn_leases.discard(args[0])
                    value = True
                elif op == "publish":
                    await store.publish(args[0], args[1])
                    value = True
                elif op == "subscribe":
                    sub = await store.subscribe(args[0])
                    sid = next(stream_ids)
                    stream_handles[sid] = sub
                    streams[sid] = asyncio.get_running_loop().create_task(
                        pump_sub(sid, sub)
                    )
                    value = {"sid": sid}
                elif op == "stream_close":
                    sid = args[0]
                    handle = stream_handles.pop(sid, None)
                    task = streams.pop(sid, None)
                    if handle is not None:
                        await handle.close()
                    if task is not None:
                        task.cancel()
                    value = True
                elif op == "queue_push":
                    value = await store.queue_push(args[0], args[1])
                elif op == "queue_pop":
                    value = _enc_qmsg(
                        await store.queue_pop(args[0], args[1], args[2])
                    )
                elif op == "queue_ack":
                    value = await store.queue_ack(args[0], args[1])
                elif op == "queue_len":
                    value = await store.queue_len(args[0])
                elif op == "obj_put":
                    await store.obj_put(args[0], args[1], args[2])
                    value = True
                elif op == "obj_get":
                    value = await store.obj_get(args[0], args[1])
                elif op == "obj_delete":
                    value = await store.obj_delete(args[0], args[1])
                elif op == "obj_list":
                    value = await store.obj_list(args[0])
                elif op == "ping":
                    value = "pong"
                else:
                    raise ValueError(f"unknown op {op!r}")
                await send({"i": rid, "ok": True, "v": value})
            except asyncio.CancelledError:
                raise  # connection teardown cancels pending requests
            except ConnectionError:
                pass
            except Exception as exc:  # structured error back to caller
                try:
                    await send({"i": rid, "ok": False, "e": f"{type(exc).__name__}: {exc}"})
                except ConnectionError:
                    pass

        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                # each request runs concurrently so a blocking queue_pop
                # doesn't stall keepalives on the same connection
                task = asyncio.get_running_loop().create_task(
                    handle_request(msg["i"], msg["op"], msg.get("a", []))
                )
                pending.add(task)
                task.add_done_callback(pending.discard)
        finally:
            for task in pending:
                task.cancel()
            for task in streams.values():
                task.cancel()
            for handle in stream_handles.values():
                try:
                    await handle.close()
                except Exception:
                    pass
            # a dropped connection revokes its leases (liveness semantics:
            # same effect as etcd lease expiry in the reference)
            for lid in conn_leases:
                try:
                    await store.lease_revoke(lid)
                except Exception:
                    pass
            self._conn_writers.discard(writer)
            writer.close()


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu coordinator store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=4222)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    server = StoreServer(host=args.host, port=args.port)
    asyncio.run(server.serve_forever())


if __name__ == "__main__":
    main()
