"""Length-prefixed msgpack framing shared by the store server and client."""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # 256 MiB: object store blobs can be large


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(4)
    (length,) = struct.unpack("<I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(struct.pack("<I", len(body)) + body)


async def shutdown_server(
    server: asyncio.AbstractServer | None,
    conn_writers: set[asyncio.StreamWriter],
    drain_timeout_s: float = 5.0,
) -> None:
    """Close a listener and force-close its live connections.

    py3.12's ``Server.wait_closed()`` blocks until every connection handler
    returns, so open client connections must be closed first.
    """
    if server is not None:
        server.close()
    for w in list(conn_writers):
        w.close()
    if server is not None:
        try:
            await asyncio.wait_for(server.wait_closed(), timeout=drain_timeout_s)
        except asyncio.TimeoutError:  # pragma: no cover
            pass
