"""dynamo_tpu.telemetry — dependency-free tracing + metrics.

Two halves (docs/observability.md is the operator-facing guide):

- **Spans** (spans.py): ``get_tracer().span("name", parent=ctx)`` with
  trace-context propagation over the existing transport. Enabled by
  ``DYN_TRACE_FILE`` (JSONL); ``dynamo-tpu trace export`` renders
  Perfetto/chrome://tracing flame graphs (export.py).
- **Metrics** (metrics.py): one process registry of labeled counters/
  gauges/histograms with Prometheus text exposition and cardinality
  guard rails; the serving stack's catalog lives in instruments.py.
"""

from dynamo_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Metric,
    Registry,
    REGISTRY,
    check_scrape_safety,
    escape_label_value,
)
from dynamo_tpu.telemetry.spans import (  # noqa: F401
    NULL_SPAN,
    JsonlSpanExporter,
    Span,
    Tracer,
    get_tracer,
    new_span_id,
    new_trace_id,
    propagation_context,
    reset_tracer,
)
